"""Build script (parity: the reference's ``setup.py:583-671`` feature-switch
scheme, collapsed to the two native artifacts this framework ships).

The native core (``libhvdtpu.so``, the controller/ring runtime) and the
TensorFlow op library (``libhvdtf.so``) are compiled by their Makefiles at
build time. Switches follow the reference's convention:

- ``HOROVOD_WITHOUT_NATIVE=1``  — skip the native core (pure-Python mode;
  multi-process host worlds will refuse to start).
- ``HOROVOD_WITH_NATIVE=1``     — fail the build if the native core can't
  compile (default: best-effort, it also builds lazily at first import).
- ``HOROVOD_WITHOUT_TENSORFLOW=1`` / ``HOROVOD_WITH_TENSORFLOW=1`` — same
  for the TF op library (needs an importable tensorflow at build time,
  which with pip means ``--no-build-isolation``).

The Makefiles build in-tree (``horovod_tpu/lib/``) and the artifacts ride
into the wheel as package data — the same location the lazy first-import
build uses, so an installed tree and a source tree behave identically.
Read-only checkouts should install from a prebuilt wheel.
"""

import os
import subprocess
import sys

from setuptools import setup
from setuptools.command.build_py import build_py

HERE = os.path.dirname(os.path.abspath(__file__))


def _env_on(name):
    return os.environ.get(name, "").lower() in ("1", "true", "yes", "on")


def _make(subdir, required, what):
    path = os.path.join(HERE, "horovod_tpu", subdir)
    try:
        subprocess.run(["make", "-C", path], check=True, timeout=600)
        return True
    except Exception as e:
        msg = f"building {what} failed: {e}"
        if required:
            raise RuntimeError(
                msg + f" (required because HOROVOD_WITH_"
                f"{what.upper()}=1 was set)") from e
        print(f"warning: {msg}; it will be built lazily at first import "
              f"instead", file=sys.stderr)
        return False


class BuildWithNative(build_py):
    def run(self):
        if not _env_on("HOROVOD_WITHOUT_NATIVE"):
            _make("csrc", _env_on("HOROVOD_WITH_NATIVE"), "native")
        if not _env_on("HOROVOD_WITHOUT_TENSORFLOW"):
            import importlib.util

            # NOTE: under pip's default PEP 517 build isolation the build
            # env contains only setuptools, so tensorflow is never visible
            # here even when installed — pass --no-build-isolation to get
            # the TF op library built at install time. Without it the
            # library still builds lazily at first import, so skipping is
            # the right default behavior, not an error.
            have_tf = importlib.util.find_spec("tensorflow") is not None
            if have_tf:
                _make(os.path.join("tensorflow", "csrc"),
                      _env_on("HOROVOD_WITH_TENSORFLOW"), "tensorflow")
            elif _env_on("HOROVOD_WITH_TENSORFLOW"):
                raise RuntimeError(
                    "HOROVOD_WITH_TENSORFLOW=1 but tensorflow is not "
                    "importable in the build environment (if it IS "
                    "installed, rerun with pip --no-build-isolation)")
        super().run()


setup(cmdclass={"build_py": BuildWithNative})
