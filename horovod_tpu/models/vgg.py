"""VGG (TPU-idiomatic flax): one of the reference's three headline
benchmark models (``README.rst:80-84`` / ``docs/benchmarks.rst:8-13``
report 68% scaling efficiency for VGG-16 at 512 GPUs — VGG's huge dense
head makes it the communication-heavy stress case of the trio).

TPU notes: conv stacks run in bf16 (fp32 params) so the elementwise
ReLU chains ride HBM at half width; the classifier head computes in
fp32. The 25088->4096 dense layers dominate the parameter count
(~138 M 224px/1000 classes) exactly as in the original architecture —
that is the point of benchmarking VGG: gradient allreduce bytes per
step are ~20x ResNet-50's.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


class VGG(nn.Module):
    # (convs per stage, filters per stage) — VGG-D is [2,2,3,3,3].
    stage_convs: Sequence[int]
    num_classes: int = 1000
    num_filters: Sequence[int] = (64, 128, 256, 512, 512)
    dense_width: int = 4096
    dtype: Any = jnp.bfloat16
    # Classic VGG has no batch norm; the widely-benchmarked "vgg16"
    # (incl. tf_cnn_benchmarks) is the plain version. BN variant
    # (vgg16_bn) is opt-in.
    batch_norm: bool = False
    # Cross-replica BN statistics (see resnet.ResNet.sync_bn_axis);
    # effective only with batch_norm=True.
    sync_bn_axis: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, kernel_size=(3, 3),
                                 padding="SAME", dtype=self.dtype,
                                 param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        for i, n_convs in enumerate(self.stage_convs):
            for j in range(n_convs):
                x = conv(self.num_filters[i], name=f"conv{i}_{j}")(x)
                if self.batch_norm:
                    x = nn.BatchNorm(use_running_average=not train,
                                     momentum=0.9, epsilon=1e-5,
                                     dtype=self.dtype,
                                     param_dtype=jnp.float32,
                                     axis_name=self.sync_bn_axis,
                                     name=f"bn{i}_{j}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for k in range(2):
            x = nn.Dense(self.dense_width, dtype=self.dtype,
                         param_dtype=jnp.float32, name=f"fc{k}")(x)
            x = nn.relu(x)
        # fp32 head for a stable softmax.
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(x)


VGG11 = functools.partial(VGG, stage_convs=[1, 1, 2, 2, 2])
VGG13 = functools.partial(VGG, stage_convs=[2, 2, 2, 2, 2])
VGG16 = functools.partial(VGG, stage_convs=[2, 2, 3, 3, 3])
VGG19 = functools.partial(VGG, stage_convs=[2, 2, 4, 4, 4])
