"""Flagship sharded transformer: dp x pp x sp x tp (+ ep on dp).

This model is the parallelism showcase the TPU build adds beyond the
reference's DP-only surface (SURVEY §2.5): every mesh axis of
``horovod_tpu.parallel.mesh`` is exercised in one training step —

- **dp**: batch sharded; gradients reduced across dp by the autodiff
  transpose of the replicated-parameter broadcast (the same math
  ``hvd.DistributedOptimizer`` performs explicitly).
- **pp**: decoder layers split into stages, GPipe schedule via
  ``parallel.pipeline.spmd_pipeline`` (params sharded over ``pp``).
- **sp**: sequence/context parallelism — the token axis is sharded and
  attention runs as ring attention (``parallel.ring_attention``) or
  all-to-all Ulysses-style re-sharding (``parallel.ulysses``), selected
  by ``TransformerConfig.sp_strategy``.
- **tp**: Megatron-style tensor parallelism — attention heads and MLP
  hidden dim sharded over ``tp``, partial outputs psum'd.
- **ep**: MoE experts sharded over the dp axis with all_to_all dispatch
  (``parallel.moe``), Switch-style.

Pure-jax pytree params (no flax) so shard_map in_specs map 1:1 onto leaves.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..common.compat import shard_map as _compat_shard_map
from ..parallel.moe import moe_layer
from ..parallel.pipeline import spmd_pipeline
from ..parallel.ulysses import context_parallel_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    d_head: int = 16
    d_ff: int = 256
    n_layers: int = 4
    max_seq: int = 64
    use_moe: bool = False
    n_experts: int = 4
    d_expert: int = 128
    capacity_factor: float = 2.0
    moe_top_k: int = 1  # 1 = Switch, 2 = GShard renormalized top-2
    dtype: Any = jnp.float32
    # Sequence-parallel attention strategy over the sp axis: "ring"
    # (K/V rotation, no head constraint), "ulysses" (all-to-all head
    # re-shard, needs (n_heads/tp) % sp == 0), or "auto"
    # (parallel/ulysses.py).
    sp_strategy: str = "ring"
    # Sliding-window attention (Mistral-style SWA): each token attends
    # to itself plus the `attention_window - 1` preceding tokens
    # (receptive field = attention_window; mask q_pos - k_pos < W).
    # None = full causal. Out-of-window K tiles are culled in the
    # kernels.
    attention_window: Optional[int] = None
    # Rematerialize each decoder layer in the backward pass
    # (jax.checkpoint): activations are recomputed instead of saved, so
    # activation HBM drops from O(n_layers) to O(1) layers — the
    # standard trade that lets long sequences fit, at ~1/3 extra FLOPs.
    remat: bool = False
    # Grouped-query attention (Llama/Mistral-style): n_kv_heads < n_heads
    # shares each K/V head across n_heads/n_kv_heads query heads (KV
    # params cut by that factor; K/V expanded before the kernel — the
    # training-side GQA formulation). None = multi-head (= n_heads).
    n_kv_heads: Optional[int] = None
    # Rotary position embeddings instead of the learned position table.
    # Positions are GLOBAL (sp-sharded ranks offset by their shard), so
    # RoPE composes with sequence parallelism.
    rope: bool = False
    rope_theta: float = 10000.0

    def __post_init__(self):
        if self.n_kv_heads is not None:
            if self.n_kv_heads < 1:
                raise ValueError(
                    f"n_kv_heads must be >= 1, got {self.n_kv_heads}")
            if self.n_heads % self.n_kv_heads != 0:
                raise ValueError(
                    f"n_heads ({self.n_heads}) must divide by n_kv_heads "
                    f"({self.n_kv_heads})")
        if self.rope and self.d_head % 2 != 0:
            raise ValueError(f"rope needs an even d_head, got "
                             f"{self.d_head}")

    @property
    def kv_heads(self) -> int:
        return self.n_heads if self.n_kv_heads is None else self.n_kv_heads


def _param_specs(cfg: TransformerConfig) -> Dict[str, P]:
    """PartitionSpecs for every param leaf (leading dims: [S(tage), L(ayer/

    stage)] on per-layer params)."""
    specs = {
        "embed": P(),
        "ln1": P("pp"),
        "wo": P("pp", None, "tp"),
        "ln2": P("pp"),
        "final_ln": P(),
        "head": P(),
    }
    if not cfg.rope:
        specs["pos"] = P()
    if cfg.kv_heads == cfg.n_heads:
        specs["wqkv"] = P("pp", None, None, None, "tp")
    else:
        specs["wq"] = P("pp", None, None, "tp")
        specs["wkv"] = P("pp", None, None, None, "tp")
    if cfg.use_moe:
        specs.update({
            "gate": P("pp"),
            "we_in": P("pp", None, "dp"),
            "we_out": P("pp", None, "dp"),
        })
    else:
        specs.update({
            "w1": P("pp", None, None, "tp"),
            "w2": P("pp", None, "tp"),
        })
    return specs


def init_params(cfg: TransformerConfig, rng, n_stages: int) -> Dict:
    """Global (unsharded) parameter pytree; shard with ``shard_params``."""
    assert cfg.n_layers % n_stages == 0, "n_layers must divide into stages"
    lps = cfg.n_layers // n_stages
    H, Dh, d, F = cfg.n_heads, cfg.d_head, cfg.d_model, cfg.d_ff
    ks = jax.random.split(rng, 12)
    dt = cfg.dtype

    def norm(key, shape, scale):
        return (jax.random.normal(key, shape) * scale).astype(dt)

    params = {
        "embed": norm(ks[0], (cfg.vocab, d), 0.02),
        "ln1": jnp.ones((n_stages, lps, d), jnp.float32),
        "wo": norm(ks[3], (n_stages, lps, H, Dh, d), (H * Dh) ** -0.5),
        "ln2": jnp.ones((n_stages, lps, d), jnp.float32),
        "final_ln": jnp.ones((d,), jnp.float32),
        "head": norm(ks[4], (d, cfg.vocab), d ** -0.5),
    }
    if not cfg.rope:
        params["pos"] = norm(ks[1], (cfg.max_seq, d), 0.02)
    Hkv = cfg.kv_heads
    if Hkv == H:
        params["wqkv"] = norm(ks[2], (n_stages, lps, d, 3, H, Dh),
                              d ** -0.5)
    else:
        params["wq"] = norm(ks[2], (n_stages, lps, d, H, Dh), d ** -0.5)
        params["wkv"] = norm(ks[8], (n_stages, lps, d, 2, Hkv, Dh),
                             d ** -0.5)
    if cfg.use_moe:
        E, Fe = cfg.n_experts, cfg.d_expert
        params.update({
            "gate": norm(ks[5], (n_stages, lps, d, E), d ** -0.5
                         ).astype(jnp.float32),
            "we_in": norm(ks[6], (n_stages, lps, E, d, Fe), d ** -0.5),
            "we_out": norm(ks[7], (n_stages, lps, E, Fe, d), Fe ** -0.5),
        })
    else:
        params.update({
            "w1": norm(ks[5], (n_stages, lps, d, F), d ** -0.5),
            "w2": norm(ks[6], (n_stages, lps, F, d), F ** -0.5),
        })
    return params


def _validate_mesh_divisibility(cfg: TransformerConfig, mesh) -> None:
    """Head counts must divide the tp axis: wq/wqkv shard the query-head
    dim and wkv the KV-head dim over 'tp', and an indivisible split only
    surfaces later as an opaque XLA sharding error at compile time.
    Checked here — where the mesh is known — rather than in
    ``__post_init__``, which never sees it."""
    tp = dict(mesh.shape).get("tp", 1)
    if cfg.n_heads % tp != 0:
        raise ValueError(
            f"n_heads ({cfg.n_heads}) must be divisible by the mesh's tp "
            f"axis ({tp}) — wq/wqkv shard the head dim over tp")
    if cfg.kv_heads % tp != 0:
        raise ValueError(
            f"kv_heads ({cfg.kv_heads}) must be divisible by the mesh's "
            f"tp axis ({tp}) — wkv shards the KV-head dim over tp; use "
            f"n_kv_heads that is a multiple of tp (or tp <= n_kv_heads)")


def shard_params(params: Dict, cfg: TransformerConfig, mesh) -> Dict:
    _validate_mesh_divisibility(cfg, mesh)
    specs = _param_specs(cfg)
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }


def _rope(x, positions, theta):
    """Rotary position embeddings (rotate-half convention).

    x: [b, t, H, Dh] (Dh even); positions: [t] GLOBAL token positions —
    sequence-parallel shards pass their offset range, which is what
    makes RoPE compose with the sp axis."""
    Dh = x.shape[-1]
    half = Dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, None].astype(jnp.float32) * freqs[None]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], -1).astype(x.dtype)


def _layernorm(x, scale):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + 1e-5) * scale).astype(x.dtype)


def _make_stage_fn(cfg: TransformerConfig, packed: bool = False):
    """stage_fn(stage_params, x) applying this stage's layers.

    x: [mb, t_local, d] (or ``(x, segment_ids)`` with ``packed`` — the
    ids ride the pipeline ring with the activations and pass through
    each stage unchanged); runs under the full (dp, pp, sp, tp) mesh.
    """

    def layer(x, lp, seg, gathered_seg):
        # --- attention (tp-sharded heads, sp ring) --------------------------
        h = _layernorm(x, lp["ln1"])
        if "wqkv" in lp:
            qkv = jnp.einsum("btd,dchk->btchk", h, lp["wqkv"])  # h=H/tp
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:  # GQA: separate q and (fewer-headed) kv projections
            q = jnp.einsum("btd,dhk->bthk", h, lp["wq"])
            kv = jnp.einsum("btd,dchk->btchk", h, lp["wkv"])  # h=Hkv/tp
            k, v = kv[:, :, 0], kv[:, :, 1]
        if cfg.rope:
            t_local = x.shape[1]
            pos = (lax.axis_index("sp") * t_local
                   + jnp.arange(t_local, dtype=jnp.int32))
            q = _rope(q, pos, cfg.rope_theta)
            k = _rope(k, pos, cfg.rope_theta)
        # GQA K/V stay at their reduced head width here — the
        # context-parallel strategies carry them across the sp fabric
        # at that width and expand only at the kernel boundary.
        attn = context_parallel_attention(
            q, k, v, axis_name="sp", causal=True,
            strategy=cfg.sp_strategy, segment_ids=seg,
            gathered_segment_ids=gathered_seg,
            window=cfg.attention_window)
        out = jnp.einsum("bthk,hkd->btd", attn, lp["wo"])
        out = lax.psum(out, "tp")  # combine head shards
        x = x + out
        # --- feed-forward ----------------------------------------------------
        h = _layernorm(x, lp["ln2"])
        if cfg.use_moe:
            B, T, d = h.shape
            flat = h.reshape(B * T, d)
            y = moe_layer(flat, {"gate": lp["gate"], "w_in": lp["we_in"],
                                 "w_out": lp["we_out"]},
                          axis_name="dp",
                          capacity_factor=cfg.capacity_factor,
                          top_k=cfg.moe_top_k)
            y = y.reshape(B, T, d)
        else:
            y = jax.nn.gelu(jnp.einsum("btd,df->btf", h, lp["w1"]))
            y = jnp.einsum("btf,fd->btd", y, lp["w2"])
            y = lax.psum(y, "tp")  # combine hidden-dim shards
        return x + y

    layer_fn = jax.checkpoint(layer) if cfg.remat else layer

    def stage_fn(stage_params, x):
        seg = gathered = None
        if packed:
            x, seg = x
            if cfg.sp_strategy in ("ulysses", "auto"):
                # Hoist the loop-invariant id gather out of the layer
                # scan (XLA won't lift collectives out of scan bodies);
                # if "auto" resolves to ring, the unused gather is DCE'd.
                from ..parallel.ulysses import gather_segment_ids

                gathered = gather_segment_ids(seg, "sp")

        def body(x, lp):
            return layer_fn(x, lp, seg, gathered), None

        x, _ = lax.scan(body, x, stage_params)
        return (x, seg) if packed else x

    return stage_fn


def _spmd_forward(cfg: TransformerConfig, stage_fn, params, tokens,
                  n_microbatches: int, segment_ids=None):
    """Shared SPMD forward (embed → pipeline → final norm → logits).

    Runs under the (dp, pp, sp, tp) mesh; tokens: local [b, t];
    ``segment_ids`` (int [b, t], sequence-sharded like tokens): packed
    sequences — microbatched alongside the activations so each pipeline
    stage masks attention for the microbatch it is holding."""
    b, t = tokens.shape
    sp_idx = lax.axis_index("sp")
    x = params["embed"][tokens]  # [b, t, d]
    if "pos" in params:  # learned positions; RoPE rotates in the layers
        pos = lax.dynamic_slice_in_dim(params["pos"], sp_idx * t, t,
                                       axis=0)
        x = x + pos[None]
    x = x.astype(cfg.dtype)

    # microbatch for the pipeline: [M, mb, t, d]
    M = n_microbatches
    x = x.reshape(M, b // M, t, x.shape[-1])
    if segment_ids is not None:
        seg_mb = jnp.asarray(segment_ids, jnp.int32).reshape(M, b // M, t)
        x = (x, seg_mb)
    # Per-stage params: strip the leading pp dim. The local slice MUST be
    # exactly one stage — if init_params was built with a different stage
    # count than the mesh's pp size, layers would silently be dropped.
    stage_params = {}
    for k, v in params.items():
        if k in ("embed", "pos", "final_ln", "head"):
            continue
        assert v.shape[0] == 1, (
            f"param '{k}' has {v.shape[0]} local stages; init_params "
            "n_stages must equal the mesh pp size")
        stage_params[k] = v[0]
    # Packed mode: segment ids ride the ring carry for later stages but
    # are side data, not outputs — collect only the activation leaf.
    y = spmd_pipeline(
        stage_fn, stage_params, x, axis_name="pp",
        collect_fn=(lambda s: s[0]) if segment_ids is not None else None)
    y = y.reshape(b, t, -1)

    y = _layernorm(y, params["final_ln"])
    return jnp.einsum("btd,dv->btv", y.astype(jnp.float32),
                      params["head"].astype(jnp.float32))


def make_loss_fn(cfg: TransformerConfig, mesh, n_microbatches: int = 2,
                 packed: bool = False):
    """Build loss(params, tokens, labels) -> scalar, shard_mapped over the
    full mesh. tokens/labels: [B_global, T_global] sharded P('dp','sp').

    ``packed=True`` builds loss(params, tokens, labels, segment_ids)
    instead: attention masks within segments (packed sequences). The
    loss itself stays plain mean cross-entropy — mask cross-segment
    next-token positions through the labels (e.g. weight-zero ids) as
    your data pipeline defines them."""
    _validate_mesh_divisibility(cfg, mesh)
    stage_fn = _make_stage_fn(cfg, packed=packed)
    specs = _param_specs(cfg)

    def spmd_loss(params, tokens, labels, segment_ids=None):
        logits = _spmd_forward(cfg, stage_fn, params, tokens,
                               n_microbatches, segment_ids=segment_ids)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        loss = -jnp.mean(ll)
        return lax.pmean(loss, ("dp", "sp"))

    data = P("dp", "sp")
    in_specs = ((specs, data, data, data) if packed
                else (specs, data, data))
    return _compat_shard_map(spmd_loss, mesh=mesh, in_specs=in_specs,
                         out_specs=P(), check_vma=False)


def make_train_step(cfg: TransformerConfig, optimizer, mesh,
                    n_microbatches: int = 2, opt_shardings=None,
                    packed: bool = False):
    """Full sharded training step: loss + grads + optimizer update, jitted
    once over the 4-axis mesh.

    ``opt_shardings`` (a pytree of NamedShardings matching the optimizer
    state, e.g. ``jax.tree.map(lambda x: x.sharding, opt_state)`` from a
    ``training.init_opt_state(..., zero_axis="dp")`` state) pins the
    updated optimizer state to those shardings inside the compiled
    program — the ZeRO-1 composition: moments stay partitioned over dp
    on top of the params' tp/pp sharding, and XLA inserts the
    slice/gather collectives around the elementwise update.

    ``packed=True`` builds step(params, opt_state, tokens, labels,
    segment_ids) for packed-sequence training (``make_loss_fn``)."""
    import optax

    loss_fn = make_loss_fn(cfg, mesh, n_microbatches, packed=packed)

    def apply(grads, params, opt_state):
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if opt_shardings is not None:
            opt_state = jax.lax.with_sharding_constraint(
                opt_state, opt_shardings)
        return optax.apply_updates(params, updates), opt_state

    if packed:
        def step(params, opt_state, tokens, labels, segment_ids):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, tokens, labels, segment_ids)
            params, opt_state = apply(grads, params, opt_state)
            return params, opt_state, loss
    else:
        def step(params, opt_state, tokens, labels):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens,
                                                      labels)
            params, opt_state = apply(grads, params, opt_state)
            return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def dense_reference_loss(cfg: TransformerConfig, params, tokens, labels,
                         segment_ids=None):
    """Unsharded single-device oracle: mathematically identical to the
    sharded loss (pipeline == sequential layers; ring attention == dense
    causal attention; MoE exact when capacity is ample). Used by tests to
    validate sharded loss AND gradients."""
    from ..parallel.ring_attention import local_flash_attention

    def attend(q, k, v):
        if segment_ids is None and cfg.attention_window is None:
            return local_flash_attention(q, k, v, causal=True)
        T = q.shape[1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / jnp.sqrt(
            jnp.asarray(q.shape[-1], jnp.float32))
        iq = jnp.arange(T)[:, None]
        ik = jnp.arange(T)[None, :]
        allowed = (iq >= ik)[None, None]
        if cfg.attention_window is not None:
            allowed = allowed & (iq - ik < cfg.attention_window)[None, None]
        if segment_ids is not None:
            seg = jnp.asarray(segment_ids)
            allowed = allowed & (seg[:, None, :, None]
                                 == seg[:, None, None, :])
        s = jnp.where(allowed, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    b, t = tokens.shape
    x = params["embed"][tokens]
    if "pos" in params:
        x = x + params["pos"][:t][None]
    x = x.astype(cfg.dtype)
    n_stages, lps = params["ln1"].shape[:2]

    for s in range(n_stages):
        for li in range(lps):
            h = _layernorm(x, params["ln1"][s, li])
            if "wqkv" in params:
                qkv = jnp.einsum("btd,dchk->btchk", h,
                                 params["wqkv"][s, li])
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            else:
                q = jnp.einsum("btd,dhk->bthk", h, params["wq"][s, li])
                kv = jnp.einsum("btd,dchk->btchk", h,
                                params["wkv"][s, li])
                k, v = kv[:, :, 0], kv[:, :, 1]
            if cfg.rope:
                pos = jnp.arange(t, dtype=jnp.int32)
                q = _rope(q, pos, cfg.rope_theta)
                k = _rope(k, pos, cfg.rope_theta)
            if k.shape[2] != q.shape[2]:
                g = q.shape[2] // k.shape[2]
                k = jnp.repeat(k, g, axis=2)
                v = jnp.repeat(v, g, axis=2)
            attn = attend(q, k, v)
            x = x + jnp.einsum("bthk,hkd->btd", attn, params["wo"][s, li])
            h = _layernorm(x, params["ln2"][s, li])
            if cfg.use_moe:
                d = h.shape[-1]
                flat = h.reshape(b * t, d).astype(jnp.float32)
                logits = flat @ params["gate"][s, li]
                probs = jax.nn.softmax(logits, -1)
                gates, idxs = lax.top_k(probs, cfg.moe_top_k)
                if cfg.moe_top_k > 1:
                    gates = gates / jnp.sum(gates, -1, keepdims=True)
                y = 0.0
                for j in range(cfg.moe_top_k):
                    idx = idxs[:, j]
                    w_in = params["we_in"][s, li].astype(jnp.float32)[idx]
                    w_out = params["we_out"][s, li].astype(jnp.float32)[idx]
                    yj = jax.nn.gelu(jnp.einsum("td,tdf->tf", flat, w_in),
                                     approximate=False)
                    yj = jnp.einsum("tf,tfd->td", yj, w_out)
                    y = y + yj * gates[:, j][:, None]
                x = x + y.reshape(b, t, d).astype(x.dtype)
            else:
                y = jax.nn.gelu(jnp.einsum(
                    "btd,df->btf", h, params["w1"][s, li]))
                x = x + jnp.einsum("btf,fd->btd", y, params["w2"][s, li])

    x = _layernorm(x, params["final_ln"])
    logits = jnp.einsum("btd,dv->btv", x.astype(jnp.float32),
                        params["head"].astype(jnp.float32))
    logp = jax.nn.log_softmax(logits, -1)
    ll = jnp.take_along_axis(logp, labels[..., None], -1)[..., 0]
    return -jnp.mean(ll)


def make_forward_fn(cfg: TransformerConfig, mesh, n_microbatches: int = 2):
    """Inference forward returning logits, sharded like the loss."""
    stage_fn = _make_stage_fn(cfg)
    specs = _param_specs(cfg)

    def spmd_fwd(params, tokens):
        return _spmd_forward(cfg, stage_fn, params, tokens, n_microbatches)

    return jax.jit(_compat_shard_map(
        spmd_fwd, mesh=mesh,
        in_specs=(specs, P("dp", "sp")),
        out_specs=P("dp", "sp"), check_vma=False))
