"""Inception V3 (TPU-idiomatic flax): one of the reference's three
headline benchmark models (``README.rst:80-84`` /
``docs/benchmarks.rst:8-13`` report 90% scaling efficiency for
Inception V3 at 512 GPUs).

Structure follows the published architecture (Szegedy et al. 2015,
"Rethinking the Inception Architecture"): stem → 3×InceptionA →
InceptionB → 4×InceptionC → InceptionD → 2×InceptionE → pool → head.
The mixed blocks' parallel branches are a good fit for XLA: each branch
is an independent conv chain the compiler schedules side by side, and
the concatenations are layout no-ops on TPU's channel-last tiling.

TPU notes: all convs bf16 with fp32 params/BN-stats (elementwise chains
at half HBM width), fp32 classifier head. The canonical input is
299×299 (the stem's three stride-2 reductions need ≥75×75); the aux
classifier is omitted (benchmark configs run without it, and the
reference's tf_cnn_benchmarks default does too).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


class ConvBN(nn.Module):
    """Conv → BN → ReLU, the Inception building unit."""

    filters: int
    kernel: Sequence[int] = (1, 1)
    strides: Sequence[int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16
    # Cross-replica BN statistics (see resnet.ResNet.sync_bn_axis).
    sync_bn_axis: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.filters, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype,
                         param_dtype=jnp.float32,
                         axis_name=self.sync_bn_axis)(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16
    sync_bn_axis: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype,
                                sync_bn_axis=self.sync_bn_axis)
        b1 = cbn(64)(x, train)
        b2 = cbn(48)(x, train)
        b2 = cbn(64, (5, 5))(b2, train)
        b3 = cbn(64)(x, train)
        b3 = cbn(96, (3, 3))(b3, train)
        b3 = cbn(96, (3, 3))(b3, train)
        b4 = cbn(self.pool_features)(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35→17."""

    dtype: Any = jnp.bfloat16
    sync_bn_axis: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype,
                                sync_bn_axis=self.sync_bn_axis)
        b1 = cbn(384, (3, 3), (2, 2), padding="VALID")(x, train)
        b2 = cbn(64)(x, train)
        b2 = cbn(96, (3, 3))(b2, train)
        b2 = cbn(96, (3, 3), (2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7x7 branches."""

    c7: int
    dtype: Any = jnp.bfloat16
    sync_bn_axis: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype,
                                sync_bn_axis=self.sync_bn_axis)
        c = self.c7
        b1 = cbn(192)(x, train)
        b2 = cbn(c)(x, train)
        b2 = cbn(c, (1, 7))(b2, train)
        b2 = cbn(192, (7, 1))(b2, train)
        b3 = cbn(c)(x, train)
        b3 = cbn(c, (7, 1))(b3, train)
        b3 = cbn(c, (1, 7))(b3, train)
        b3 = cbn(c, (7, 1))(b3, train)
        b3 = cbn(192, (1, 7))(b3, train)
        b4 = cbn(192)(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17→8."""

    dtype: Any = jnp.bfloat16
    sync_bn_axis: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype,
                                sync_bn_axis=self.sync_bn_axis)
        b1 = cbn(192)(x, train)
        b1 = cbn(320, (3, 3), (2, 2), padding="VALID")(b1, train)
        b2 = cbn(192)(x, train)
        b2 = cbn(192, (1, 7))(b2, train)
        b2 = cbn(192, (7, 1))(b2, train)
        b2 = cbn(192, (3, 3), (2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    """Expanded-filter-bank blocks for the 8x8 grid."""

    dtype: Any = jnp.bfloat16
    sync_bn_axis: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype,
                                sync_bn_axis=self.sync_bn_axis)
        b1 = cbn(320)(x, train)
        b2 = cbn(384)(x, train)
        b2 = jnp.concatenate([cbn(384, (1, 3))(b2, train),
                              cbn(384, (3, 1))(b2, train)], axis=-1)
        b3 = cbn(448)(x, train)
        b3 = cbn(384, (3, 3))(b3, train)
        b3 = jnp.concatenate([cbn(384, (1, 3))(b3, train),
                              cbn(384, (3, 1))(b3, train)], axis=-1)
        b4 = cbn(192)(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    sync_bn_axis: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype,
                                sync_bn_axis=self.sync_bn_axis)
        x = x.astype(self.dtype)
        # Stem: 299 -> 35x35x192.
        x = cbn(32, (3, 3), (2, 2), padding="VALID")(x, train)
        x = cbn(32, (3, 3), padding="VALID")(x, train)
        x = cbn(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = cbn(80)(x, train)
        x = cbn(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # Mixed blocks.
        for pf in (32, 64, 64):
            x = InceptionA(pool_features=pf, dtype=self.dtype,
                           sync_bn_axis=self.sync_bn_axis)(x, train)
        x = InceptionB(dtype=self.dtype,
                       sync_bn_axis=self.sync_bn_axis)(x, train)
        for c7 in (128, 160, 160, 192):
            x = InceptionC(c7=c7, dtype=self.dtype,
                           sync_bn_axis=self.sync_bn_axis)(x, train)
        x = InceptionD(dtype=self.dtype,
                       sync_bn_axis=self.sync_bn_axis)(x, train)
        x = InceptionE(dtype=self.dtype,
                       sync_bn_axis=self.sync_bn_axis)(x, train)
        x = InceptionE(dtype=self.dtype,
                       sync_bn_axis=self.sync_bn_axis)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32)(x)
