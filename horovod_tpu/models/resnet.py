"""ResNet family (flax), the benchmark workhorse.

The reference's headline numbers are ResNet-class models driven through
``examples/pytorch_synthetic_benchmark.py`` / tf_cnn_benchmarks (BASELINE.md);
this is the TPU-native equivalent model zoo. Design notes for the MXU:

- NHWC layout (TPU-native; conv lowers to MXU-tiled matmuls).
- bfloat16 activations/weights with float32 batch-norm statistics and
  float32 softmax/loss — the standard TPU mixed-precision recipe.
- No data-dependent control flow; everything static-shape for XLA.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


def space_to_depth(x, block: int = 2):
    """[N, H, W, C] -> [N, H/b, W/b, b*b*C]; channel order is
    (dh, dw, c) — the layout :func:`stem_weights_to_s2d` maps onto."""
    n, h, w, c = x.shape
    b = block
    x = x.reshape(n, h // b, b, w // b, b, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, h // b, w // b, b * b * c)


def stem_weights_to_s2d(w):
    """Exact re-tiling of a 7x7/stride-2 stem kernel [7, 7, C, F] into
    the equivalent 4x4/stride-1 kernel [4, 4, 4*C, F] over
    space-to-depth(2) input: new tap (m, n) with sub-position (dh, dw)
    carries original tap kh = 2m + dh, kw = 2n + dw (m, n in 0..3, so
    kh, kw in 0..7; the pad books balance because XLA SAME's pad_lo=2
    for k=7/s=2 equals 2x the s2d conv's pad_lo=1). The one
    out-of-range slot per axis (kh or kw = 7) stays zero."""
    import numpy as np

    kh_, kw_, c, f = w.shape
    assert (kh_, kw_) == (7, 7), "stem re-tiling is for the 7x7 kernel"
    w2 = np.zeros((4, 4, 4 * c, f), np.asarray(w).dtype)
    for m in range(4):
        for n in range(4):
            for dh in range(2):
                for dw in range(2):
                    kh = 2 * m + dh
                    kw = 2 * n + dw
                    if 0 <= kh < 7 and 0 <= kw < 7:
                        w2[m, n, (dh * 2 + dw) * c:(dh * 2 + dw + 1) * c] \
                            = np.asarray(w)[kh, kw]
    return w2


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    # MXU stem (the public MLPerf ResNet trick): the 7x7/stride-2 conv on
    # 3-channel input uses 3 of the MXU's 128 input lanes; space-to-depth
    # by 2 turns it into an equivalent 4x4/stride-1 conv on 12 channels
    # (4x the lane utilization, same FLOPs, bit-identical function class —
    # stem_weights_to_s2d maps any original kernel exactly). Opt-in so
    # checkpoints keep the reference layout by default.
    space_to_depth_stem: bool = False
    # Cross-replica batch norm (the compiled-path role of the reference's
    # torch SyncBatchNorm, torch/sync_batch_norm.py:35-194): with a mesh
    # axis name, BN statistics are psum'd over that axis inside the
    # sharded step, so normalization uses GLOBAL-batch statistics — the
    # correctness lever for small per-chip batches at large dp. On ICI
    # this is a pair of tiny per-layer allreduces XLA overlaps with the
    # convs; None (default) keeps per-shard stats.
    sync_bn_axis: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 param_dtype=jnp.float32, padding="SAME")
        # BN statistics are computed in fp32 regardless of ``dtype`` (flax
        # promotes the reductions) and the running stats live in fp32
        # (param_dtype); the OUTPUT stays in the model dtype so the
        # act+residual elementwise chains between convs run at bf16 HBM
        # width instead of fp32 — on v5e this path is bandwidth-bound.
        norm = functools.partial(nn.BatchNorm, use_running_average=not train,
                                 momentum=0.9, epsilon=1e-5,
                                 dtype=self.dtype, param_dtype=jnp.float32,
                                 axis_name=self.sync_bn_axis)
        x = x.astype(self.dtype)
        if self.space_to_depth_stem:
            x = space_to_depth(x, 2)
            x = conv(self.num_filters, (4, 4), (1, 1),
                     padding=((1, 2), (1, 2)), name="conv_init_s2d")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm, act=self.act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BasicBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckBlock)
