from . import inception, resnet, vgg  # noqa: F401
