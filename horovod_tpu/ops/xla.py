"""In-jit functional collectives — the XLA/ICI backend.

This is the TPU-native replacement for the reference's NCCL op layer
(``ops/nccl_operations.cc``): instead of host-driven ``ncclAllReduce`` calls
on private streams, collectives are *compiled into the program* as XLA HLO
(AllReduce/AllGather/ReduceScatter/CollectivePermute) and scheduled by XLA
over ICI with near-optimal compute/communication overlap (SURVEY §7 design
stance).

Use these inside ``jax.shard_map`` / ``pjit`` with a bound mesh axis::

    @partial(jax.shard_map, mesh=mesh, in_specs=P('hvd'), out_specs=P('hvd'))
    def step(batch):
        ...
        grads = hvd.xla.allreduce(grads, op=hvd.Average)

The eager API (``horovod_tpu.ops.eager``) builds on these same primitives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size

from ..common.state import AXIS_CROSS, AXIS_GLOBAL, AXIS_LOCAL


class ReduceOp:
    """Reduction op ids (parity: ``horovod_reduce_op_*``, operations.cc:793-806)."""

    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4


Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX


_LOW_PRECISION = (jnp.bfloat16, jnp.float16)


def _scale(acc, factor):
    """Multiply inside the accumulation window: ``acc`` is already at
    the accumulation dtype (fp32 for low-precision inputs), so the
    factor never rounds at 16-bit precision. No-op for factor 1 — the
    default path's program is untouched."""
    if factor != 1.0:
        acc = acc * jnp.asarray(factor, dtype=acc.dtype)
    return acc


def _scale_f32(tensor, factor):
    """Scale at fp32 regardless of input dtype (no-op for factor 1, no
    upcast then either). Scaling bf16/fp16 in their own dtype loses the
    factor's precision and can overflow for large factors — the
    prescale precision bug; every scaling site routes through here or
    ``_scale``."""
    if factor == 1.0:
        return tensor
    return tensor.astype(jnp.float32) * jnp.float32(factor)


def _apply_prescale(tensor, prescale_factor):
    """Dtype-preserving pre-scale for the per-tensor (Adasum) paths:
    fp32 math (see ``_scale_f32``), rounded back once. The elementwise
    reduce paths scale inside their fp32 accumulation window instead
    (no extra round-trip); this helper exists for callers that must
    hand a dtype-stable tensor onward (Adasum's per-tensor
    coefficients)."""
    if tensor.dtype in _LOW_PRECISION:
        return _scale_f32(tensor, prescale_factor).astype(tensor.dtype)
    return _scale(tensor, prescale_factor)


def _apply_postscale(tensor, postscale_factor):
    """Dtype-preserving post-scale; fp32 math for bf16/fp16 (see
    ``_apply_prescale``)."""
    if tensor.dtype in _LOW_PRECISION:
        return _scale_f32(tensor, postscale_factor).astype(tensor.dtype)
    return _scale(tensor, postscale_factor)


def _resolve_compression(compression):
    if compression is None:
        return None
    from ..common.compression import resolve_compression

    return resolve_compression(compression)


def allreduce(
    tensor,
    axis_name: str = AXIS_GLOBAL,
    op: int = ReduceOp.SUM,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=None,
):
    """Allreduce a per-participant tensor across ``axis_name``.

    Uncompressed, low-precision inputs (bf16/fp16) are accumulated in
    fp32 — the TPU analog of the reference's AVX fp32-accumulation fp16
    path (``adasum.h:426-468``) — then cast back: the *wire* dtype is
    fp32. With ``compression`` (a ``common/compression`` compressor,
    its name, or None), floating tensors reduce IN the compressed wire
    dtype — the compiled all-reduce operand is f16/bf16, halving wire
    bytes — and post-reduction arithmetic (averaging, postscale) runs in
    fp32 on the reduced value before casting back to the input dtype.

    Pre/postscale factors are applied in fp32 inside the accumulation
    window (never in a 16-bit dtype), on both paths.

    Adasum ignores compression: its dot/norm coefficients are computed
    per tensor in fp32, and quantizing the operands would bias the
    coefficients themselves, not just the payload.
    """
    if op == ReduceOp.ADASUM:
        from .adasum import adasum_allreduce

        return adasum_allreduce(tensor, axis_name=axis_name)

    comp = _resolve_compression(compression)
    dtype = tensor.dtype
    wire = comp.wire_dtype(dtype) if comp is not None else None
    if wire is not None:
        acc = _scale_f32(tensor, prescale_factor).astype(wire)
    else:
        acc = tensor.astype(jnp.float32) if dtype in _LOW_PRECISION else tensor
        acc = _scale(acc, prescale_factor)
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
        out = lax.psum(acc, axis_name)
    elif op == ReduceOp.MIN:
        out = lax.pmin(acc, axis_name)
    elif op == ReduceOp.MAX:
        out = lax.pmax(acc, axis_name)
    else:
        raise ValueError(f"unknown reduce op {op}")
    if wire is not None:
        # fp32 accumulation on the reduced value: averaging/postscale
        # must not round at wire precision.
        out = out.astype(jnp.float32)
    if op == ReduceOp.AVERAGE:
        n = _axis_size(axis_name)
        out = out / jnp.asarray(n, dtype=out.dtype)
    return _scale(out, postscale_factor).astype(dtype)


def _grouped(tensors, reduce_fn, bucket_cap_bytes=None, compression=None):
    """Shared dtype-concat fusion: flatten, concatenate per plan bucket,
    reduce each fused buffer with ``reduce_fn``, slice results back out.

    TPU-native tensor fusion: rather than memcpy into a fusion buffer
    (reference ``MemcpyInFusionBuffer``, ``gpu_operations.cc:97``), we
    concatenate flattened tensors inside the compiled program and let XLA
    emit one AllReduce per bucket; the concat/split are fused away or
    become cheap on-chip moves.

    ``bucket_cap_bytes`` unset → the v1 monolithic plan (one bucket per
    dtype, parameter order) — byte-identical programs to before the
    planner existed. Set → size-capped dtype-pure buckets in reverse
    parameter (≈ backward-production) order from
    ``common/fusion.plan_buckets``, so each bucket's AllReduce depends
    only on its own gradients and XLA can overlap communication with the
    rest of the backward pass (tensor-fusion v2; see
    ``docs/tensor-fusion.md``).
    """
    from ..common.fusion import plan_buckets_for

    if not tensors:
        return []
    flats = [jnp.ravel(t) for t in tensors]
    out = [None] * len(tensors)
    # The plan budgets the COMPRESSED wire dtype when compression is on
    # (fusion.leaf_wire_nbytes), so one HOROVOD_FUSION_THRESHOLD keeps
    # meaning wire bytes; buckets are dtype-pure either way, so the fused
    # buffer compresses as one cast inside reduce_fn.
    for bucket in plan_buckets_for(flats, bucket_cap_bytes, compression):
        idxs = list(bucket.indices)
        fused = (jnp.concatenate([flats[i] for i in idxs])
                 if len(idxs) > 1 else flats[idxs[0]])
        red = reduce_fn(fused)
        off = 0
        for i in idxs:
            n = flats[i].shape[0]
            out[i] = jnp.reshape(lax.dynamic_slice_in_dim(red, off, n),
                                 tensors[i].shape)
            off += n
    return out


def grouped_allreduce(tensors, axis_name: str = AXIS_GLOBAL, op: int = ReduceOp.SUM,
                      prescale_factor: float = 1.0, postscale_factor: float = 1.0,
                      bucket_cap_bytes=None, compression=None):
    """Allreduce a list of tensors as one fused operation (see ``_grouped``).

    ``bucket_cap_bytes`` (bytes, or ``"auto"`` to follow
    ``HOROVOD_FUSION_THRESHOLD``) switches v1's one-AllReduce-per-dtype
    fusion to size-capped backward-order buckets — one AllReduce per
    bucket that XLA can launch while earlier-layer gradients are still
    being computed. Unset keeps the v1 monolithic behavior exactly.

    Adasum is NOT a per-element reduction: its dot/norm coefficients are
    per tensor, so a fused Adasum group applies the combination per
    tensor instead of on the concatenated buffer (reference
    ``tensor_counts`` contract, ``adasum_gpu_operations.cc:208-232``) —
    XLA still compiles the whole group into one program, so fusion's
    launch-overhead win is preserved. Bucketing partitions the *launch*
    groups only; the per-tensor Adasum contract is unchanged.

    ``compression`` (see ``allreduce``) makes each bucket reduce in the
    compressed wire dtype, and the plan budget the compressed width.
    Adasum ignores it (per-tensor fp32 coefficients).
    """
    from ..common.fusion import resolve_bucket_cap

    cap = resolve_bucket_cap(bucket_cap_bytes)
    if op == ReduceOp.ADASUM:
        from .adasum import grouped_adasum_allreduce

        pre = [_apply_prescale(t, prescale_factor) for t in tensors]
        red = _grouped_per_tensor(
            pre, lambda chunk: grouped_adasum_allreduce(
                chunk, axis_name=axis_name), cap)
        return [_apply_postscale(t, postscale_factor) for t in red]
    comp = _resolve_compression(compression)
    return _grouped(
        tensors,
        lambda fused: allreduce(fused, axis_name=axis_name, op=op,
                                prescale_factor=prescale_factor,
                                postscale_factor=postscale_factor,
                                compression=comp),
        bucket_cap_bytes=cap, compression=comp)


def _grouped_per_tensor(tensors, group_fn, bucket_cap_bytes):
    """Bucketing for per-tensor (non-elementwise) group reductions
    (Adasum): partition the tensor list with the same backward-order
    planner, apply ``group_fn`` to each bucket's tensors as a list.
    With no cap this is a single call over the whole list — identical to
    the unbucketed path."""
    from ..common.fusion import plan_buckets_for

    if not tensors:
        return []
    if not bucket_cap_bytes:
        return group_fn(tensors)
    out = [None] * len(tensors)
    for bucket in plan_buckets_for(tensors, bucket_cap_bytes):
        idxs = list(bucket.indices)
        for i, r in zip(idxs, group_fn([tensors[i] for i in idxs])):
            out[i] = r
    return out


def hierarchical_allreduce(tensor, op: int = ReduceOp.SUM,
                           prescale_factor: float = 1.0,
                           postscale_factor: float = 1.0,
                           compression=None):
    """ICI-then-DCN hierarchical allreduce over the (cross, local) mesh.

    TPU-native analog of ``NCCLHierarchicalAllreduce``
    (``nccl_operations.cc:164-357``): reduce-scatter along the fast LOCAL
    (ICI) axis, allreduce the shards along the CROSS (DCN) axis, then
    all-gather back along LOCAL. Must run under the hierarchical mesh with
    axes (AXIS_CROSS, AXIS_LOCAL).

    ``compression`` runs every ladder leg (scatter, cross psum, gather)
    in the compressed wire dtype — the DCN leg is exactly where wire
    bytes hurt most — with averaging/postscale in fp32 on the reduced
    value, as in the flat path. Pre/postscale are applied in fp32 inside
    the accumulation window.
    """
    # Same dtype contract as the flat path (allreduce above): accumulate
    # low-precision inputs in fp32, cast the result back, so routing
    # through HOROVOD_HIERARCHICAL_ALLREDUCE never changes dtypes or
    # precision semantics.
    comp = _resolve_compression(compression)
    dtype = tensor.dtype
    wire = comp.wire_dtype(dtype) if comp is not None else None
    if wire is not None:
        acc = _scale_f32(tensor, prescale_factor).astype(wire)
    else:
        acc = (tensor.astype(jnp.float32)
               if dtype in _LOW_PRECISION else tensor)
        acc = _scale(acc, prescale_factor)
    flat = jnp.ravel(acc)
    local_n = _axis_size(AXIS_LOCAL)
    pad = (-flat.shape[0]) % local_n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = lax.psum_scatter(flat, AXIS_LOCAL, tiled=True)
    shard = lax.psum(shard, AXIS_CROSS)
    full = lax.all_gather(shard, AXIS_LOCAL, tiled=True)
    if pad:
        full = full[: flat.shape[0] - pad]
    out = jnp.reshape(full, acc.shape)
    if wire is not None:
        out = out.astype(jnp.float32)
    if op == ReduceOp.AVERAGE:
        n = _axis_size(AXIS_LOCAL) * _axis_size(AXIS_CROSS)
        out = out / jnp.asarray(n, dtype=out.dtype)
    return _scale(out, postscale_factor).astype(dtype)


def grouped_hierarchical_allreduce(tensors, op: int = ReduceOp.SUM,
                                   prescale_factor: float = 1.0,
                                   postscale_factor: float = 1.0,
                                   bucket_cap_bytes=None, compression=None):
    """Fused hierarchical allreduce (dtype-concat fusion like
    ``grouped_allreduce``, ICI/DCN split like ``hierarchical_allreduce``).
    Supports SUM/AVERAGE (``psum_scatter``-expressible) and ADASUM — the
    latter per tensor (Adasum coefficients are per-tensor; see
    ``grouped_allreduce``) via ``hierarchical_adasum_allreduce``.
    ``bucket_cap_bytes`` buckets exactly as in ``grouped_allreduce``;
    each bucket runs the full ICI/DCN ladder independently, so the
    scatter leg of bucket k overlaps the backward that produces bucket
    k+1."""
    from ..common.fusion import resolve_bucket_cap

    cap = resolve_bucket_cap(bucket_cap_bytes)
    if op == ReduceOp.ADASUM:
        from .adasum import grouped_hierarchical_adasum_allreduce

        pre = [_apply_prescale(t, prescale_factor) for t in tensors]
        red = _grouped_per_tensor(
            pre, grouped_hierarchical_adasum_allreduce, cap)
        return [_apply_postscale(t, postscale_factor) for t in red]
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"hierarchical allreduce supports SUM/AVERAGE/ADASUM, got op {op}")

    comp = _resolve_compression(compression)

    def reduce_fn(fused):
        # Pre/postscale ride into the ladder's accumulation window
        # (fp32/wire math there) instead of rounding at the input dtype.
        return hierarchical_allreduce(fused, op=op,
                                      prescale_factor=prescale_factor,
                                      postscale_factor=postscale_factor,
                                      compression=comp)

    return _grouped(tensors, reduce_fn, bucket_cap_bytes=cap,
                    compression=comp)


def allgather(tensor, axis_name: str = AXIS_GLOBAL):
    """Concatenate per-participant tensors along dim 0 (parity:
    ``MPIAllgather``/``NCCLAllgather`` semantics, same-shape fast path)."""
    return lax.all_gather(tensor, axis_name, tiled=True)


def hierarchical_allgather(tensor):
    """ICI-then-DCN hierarchical allgather over the (cross, local) mesh.

    TPU-native analog of ``MPIHierarchicalAllgather``
    (``mpi_operations.cc:177-328``: node-local shared-memory gather + a
    cross-node gather over node leaders): gather along the fast LOCAL (ICI)
    axis first, then exchange the per-group blocks along CROSS (DCN). With
    the global mesh laid out cross-major (rank = cross*L + local), the
    (CROSS, LOCAL) concatenation order reproduces the flat rank order."""
    local = lax.all_gather(tensor, AXIS_LOCAL, tiled=True)
    return lax.all_gather(local, AXIS_CROSS, tiled=True)


def broadcast(tensor, root_rank: int, axis_name: str = AXIS_GLOBAL):
    """Every participant receives root's tensor.

    Lowered as a masked psum, which XLA rewrites into an efficient ICI
    broadcast; avoids host-driven root designation entirely.
    """
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root_rank, tensor, jnp.zeros_like(tensor))
    # Integer/bool types are summed exactly; floats too since all-but-one
    # contribution is exactly zero.
    if tensor.dtype == jnp.bool_:
        return lax.psum(masked.astype(jnp.int32), axis_name).astype(jnp.bool_)
    return lax.psum(masked, axis_name)


def reducescatter(tensor, axis_name: str = AXIS_GLOBAL, op: int = ReduceOp.SUM):
    """Reduce-scatter along dim 0 (capability extension; the reference gained
    this op after v0.19 — included for completeness on TPU)."""
    out = lax.psum_scatter(tensor, axis_name, tiled=True)
    if op == ReduceOp.AVERAGE:
        out = out / jnp.asarray(_axis_size(axis_name), dtype=out.dtype)
    return out


def alltoall(tensor, axis_name: str = AXIS_GLOBAL):
    """Exchange equal splits of dim 0 between all participants."""
    n = _axis_size(axis_name)
    x = jnp.reshape(tensor, (n, -1) + tensor.shape[1:] if tensor.ndim > 1 else (n, tensor.shape[0] // n))
    x = lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0, tiled=False)
    return jnp.reshape(x, (-1,) + tensor.shape[1:])


def barrier(axis_name: str = AXIS_GLOBAL):
    """A minimal synchronizing collective."""
    return lax.psum(jnp.ones((), dtype=jnp.int32), axis_name)


# ---- ZeRO partitioning legs (zero.py; docs/zero.md) -------------------------
#
# The named collective legs of the ZeRO step, kept here so the partition
# plane speaks the same op vocabulary as the data plane: one place owns
# the fp32-accumulation-window discipline for the scatter leg and the
# prefetch-chaining trick for the gather leg.


def zero_reducescatter(flat, axis_name: str = AXIS_GLOBAL, wire_dtype=None):
    """The gradient-partitioning leg: reduce-scatter one padded fp32
    bucket flat, each rank keeping its own 1/d shard of the sum.

    With ``wire_dtype`` (fp16/bf16 compression) the payload travels — and
    the ring accumulates — at the 16-bit wire dtype, and the reduced
    shard is upcast to fp32 before any averaging happens on it: fp32
    accumulation on the reduced value, the same window discipline as
    ``allreduce``. Callers average (``/ d``) outside, at fp32."""
    payload = flat.astype(wire_dtype) if wire_dtype is not None else flat
    seg = lax.psum_scatter(payload, axis_name, tiled=True)
    return seg.astype(jnp.float32) if wire_dtype is not None else seg


def zero_allgather(seg, axis_name: str = AXIS_GLOBAL, gather_dtype=None,
                   anchor=None):
    """The parameter-(re)assembly leg: all-gather one 1/d shard segment
    into the full padded bucket flat, optionally at a narrower
    ``gather_dtype`` (uniform-dtype models gather at the model dtype —
    half the wire bytes of fp32 for bf16 params).

    ``anchor`` is the prefetch chain (docs/zero.md): when given, the
    gather takes a dataflow dependence on it through an
    ``optimization_barrier`` — zero bytes of real data (callers pass a
    zero-length slice of an earlier gather's output), but a real edge in
    the program, so a gather chained to the gather p+1 buckets earlier
    cannot be hoisted arbitrarily far ahead of the compute front. The
    barrier bounds how many gathered bucket flats can be in flight at
    ~(p+1) without serializing consecutive gathers against compute —
    exactly the shape the latency-hiding scheduler overlaps. NOTE:
    ``optimization_barrier`` has no differentiation rule; inside a
    differentiated step this helper must be called from a
    ``custom_vjp`` forward (zero.py does), never from open AD-traced
    code."""
    if anchor is not None:
        seg, _ = lax.optimization_barrier((seg, anchor))
    if gather_dtype is not None:
        seg = seg.astype(gather_dtype)
    return lax.all_gather(seg, axis_name, tiled=True)
