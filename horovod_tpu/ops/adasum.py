"""Adasum: scaling-insensitive gradient combination, TPU-native.

The reference implements Adasum as recursive vector-halving distance-doubling
over MPI point-to-point with AVX fp32 accumulation for fp16
(``ops/adasum/adasum.h:194-398, 426-546``). The math at each level combines
partner vectors a, b as::

    a' = (1 - a.b / (2*||a||^2)) * a + (1 - a.b / (2*||b||^2)) * b

which is associative across the recursion tree: after log2(n) pairwise
levels every participant holds the same result.

TPU-native design: the *halving* in VHDD is purely a bandwidth optimization
for point-to-point networks. On an ICI torus, XLA's CollectivePermute moves
full vectors at link speed, so we express the same recursion as log2(n)
``lax.ppermute`` partner exchanges on full vectors with fp32 dot/norm
accumulation on-chip — identical numerics, compiled into one program. A
reduce-scatter-based halved variant rides the same recursion for very large
tensors (see ``horovod_tpu/ops/xla.py:hierarchical_allreduce`` for the
ICI/DCN split the reference's AdasumGpuAllreduceOp uses,
``adasum_gpu_operations.cc:38-270``).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..common.state import AXIS_GLOBAL


def _is_power_of_two(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def _adasum_combine(a, b, eps=1e-30):
    """One Adasum pairwise combination with fp32 accumulation."""
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.sum(af * bf)
    na = jnp.sum(af * af)
    nb = jnp.sum(bf * bf)
    ca = 1.0 - dot / (2.0 * jnp.maximum(na, eps))
    cb = 1.0 - dot / (2.0 * jnp.maximum(nb, eps))
    # If either vector is (near-)zero, fall back to plain sum semantics.
    ca = jnp.where(na <= eps, 1.0, ca)
    cb = jnp.where(nb <= eps, 1.0, cb)
    return ca * af + cb * bf


def adasum_allreduce(tensor, axis_name: str = AXIS_GLOBAL):
    """In-jit Adasum allreduce over ``axis_name`` (power-of-two size).

    Parity target: ``AdasumMPIAllreduceOp`` (``adasum_mpi_operations.cc:87``)
    verified against the same NumPy reference the reference tests use
    (``test_adasum_pytorch.py``).
    """
    n = lax.axis_size(axis_name)
    if not _is_power_of_two(n):
        raise ValueError(
            f"Adasum requires a power-of-two participant count, got {n}"
        )
    dtype = tensor.dtype
    shape = tensor.shape
    a = jnp.ravel(tensor).astype(jnp.float32)
    level = 1
    while level < n:
        # Partner exchange: rank r <-> r ^ level. The combination is
        # symmetric in (a, b), so no rank-dependent branching is needed.
        perm = [(r, r ^ level) for r in range(n)]
        b = lax.ppermute(a, axis_name, perm)
        a = _adasum_combine(a, b)
        level <<= 1
    return jnp.reshape(a, shape).astype(dtype)


# ---- NumPy reference (test oracle, mirrors test_adasum_pytorch.py's role) --


def adasum_reference(tensors):
    """Pure-NumPy recursive-halving-free Adasum over a list of vectors.

    Used by the test suite as the ground-truth oracle, the same role the
    NumPy model plays in the reference's ``test_adasum_pytorch.py:216``.
    """
    vecs = [np.asarray(t, dtype=np.float64) for t in tensors]
    n = len(vecs)
    assert _is_power_of_two(n), "adasum reference needs power-of-two inputs"

    def combine(a, b, eps=1e-30):
        dot = float(np.sum(a * b))
        na = float(np.sum(a * a))
        nb = float(np.sum(b * b))
        ca = 1.0 if na <= eps else 1.0 - dot / (2.0 * na)
        cb = 1.0 if nb <= eps else 1.0 - dot / (2.0 * nb)
        return ca * a + cb * b

    while len(vecs) > 1:
        vecs = [combine(vecs[i], vecs[i + 1]) for i in range(0, len(vecs), 2)]
    return vecs[0]
