"""Adasum: scaling-insensitive gradient combination, TPU-native.

The reference implements Adasum as recursive vector-halving distance-doubling
over MPI point-to-point with AVX fp32 accumulation for fp16
(``ops/adasum/adasum.h:194-398, 426-546``). The math at each level combines
partner vectors a, b as::

    a' = (1 - a.b / (2*||a||^2)) * a + (1 - a.b / (2*||b||^2)) * b

which is associative across the recursion tree: after log2(n) pairwise
levels every participant holds the same result.

TPU-native design: the *halving* in VHDD is purely a bandwidth optimization
for point-to-point networks. On an ICI torus, XLA's CollectivePermute moves
full vectors at link speed, so we express the same recursion as log2(n)
``lax.ppermute`` partner exchanges on full vectors with fp32 dot/norm
accumulation on-chip — identical numerics, compiled into one program. A
reduce-scatter-based halved variant rides the same recursion for very large
tensors (see ``horovod_tpu/ops/xla.py:hierarchical_allreduce`` for the
ICI/DCN split the reference's AdasumGpuAllreduceOp uses,
``adasum_gpu_operations.cc:38-270``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size

from ..common.state import AXIS_CROSS, AXIS_GLOBAL, AXIS_LOCAL


def _is_power_of_two(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def _combine_with_scalars(af, bf, dot, na, nb, eps=1e-30):
    """Adasum linear combination given (possibly cross-replica-reduced)
    fp32 dot/norm scalars."""
    ca = 1.0 - dot / (2.0 * jnp.maximum(na, eps))
    cb = 1.0 - dot / (2.0 * jnp.maximum(nb, eps))
    # If either vector is (near-)zero, fall back to plain sum semantics.
    ca = jnp.where(na <= eps, 1.0, ca)
    cb = jnp.where(nb <= eps, 1.0, cb)
    return ca * af + cb * bf


def _adasum_combine(a, b):
    """One Adasum pairwise combination with fp32 accumulation."""
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.sum(af * bf)
    na = jnp.sum(af * af)
    nb = jnp.sum(bf * bf)
    return _combine_with_scalars(af, bf, dot, na, nb)


def adasum_allreduce(tensor, axis_name: str = AXIS_GLOBAL):
    """In-jit Adasum allreduce over ``axis_name`` (power-of-two size).

    Parity target: ``AdasumMPIAllreduceOp`` (``adasum_mpi_operations.cc:87``)
    verified against the same NumPy reference the reference tests use
    (``test_adasum_pytorch.py``).
    """
    n = _axis_size(axis_name)
    if not _is_power_of_two(n):
        raise ValueError(
            f"Adasum requires a power-of-two participant count, got {n}"
        )
    dtype = tensor.dtype
    shape = tensor.shape
    a = jnp.ravel(tensor).astype(jnp.float32)
    level = 1
    while level < n:
        # Partner exchange: rank r <-> r ^ level. The combination is
        # symmetric in (a, b), so no rank-dependent branching is needed.
        perm = [(r, r ^ level) for r in range(n)]
        b = lax.ppermute(a, axis_name, perm)
        a = _adasum_combine(a, b)
        level <<= 1
    return jnp.reshape(a, shape).astype(dtype)


def _fused_segments(tensors):
    """Promote to fp32, flatten, concatenate; return (fused, seg_ids,
    boundaries) where seg_ids[i] is the tensor index owning element i.
    Per-tensor dot/norm scalars then come from one ``segment_sum`` over
    the fused buffer — the XLA-plane analog of the host plane's
    tensor_counts bookkeeping (ring_ops.cc VHDD)."""
    flats = [jnp.ravel(t).astype(jnp.float32) for t in tensors]
    sizes = [f.shape[0] for f in flats]
    fused = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
    seg_ids = np.repeat(np.arange(len(flats)), sizes)
    bounds = np.concatenate([[0], np.cumsum(sizes)])
    return fused, jnp.asarray(seg_ids), bounds


def _split_back(fused, tensors, bounds):
    return [
        jnp.reshape(fused[bounds[i]: bounds[i + 1]],
                    t.shape).astype(t.dtype)
        for i, t in enumerate(tensors)
    ]


def _fused_combine(a, b, seg_ids, n_tensors, extra_reduce=None, eps=1e-30):
    """One Adasum pairwise level on a fused buffer with PER-TENSOR
    coefficients: dot/norm scalars are segment-summed per tensor (and
    optionally ``extra_reduce``d across replicas holding shards of the
    same vectors), then broadcast back to element space."""
    def seg(x):
        s = jax.ops.segment_sum(x, seg_ids, num_segments=n_tensors)
        return extra_reduce(s) if extra_reduce is not None else s

    dot = seg(a * b)
    na = seg(a * a)
    nb = seg(b * b)
    ca = jnp.where(na <= eps, 1.0, 1.0 - dot / (2.0 * jnp.maximum(na, eps)))
    cb = jnp.where(nb <= eps, 1.0, 1.0 - dot / (2.0 * jnp.maximum(nb, eps)))
    return ca[seg_ids] * a + cb[seg_ids] * b


def grouped_adasum_allreduce(tensors, axis_name: str = AXIS_GLOBAL):
    """Fused Adasum over a tensor group: ONE ppermute exchange per level
    on the concatenated buffer, with the combination's dot/norm
    coefficients computed per tensor (reference ``tensor_counts``
    contract) via segment sums — the wire cost of one allreduce chain
    instead of ``len(tensors)`` of them, exact per-tensor math."""
    n = _axis_size(axis_name)
    if not _is_power_of_two(n):
        raise ValueError(
            f"Adasum requires a power-of-two participant count, got {n}")
    fused, seg_ids, bounds = _fused_segments(tensors)
    T = len(tensors)
    level = 1
    while level < n:
        perm = [(r, r ^ level) for r in range(n)]
        b = lax.ppermute(fused, axis_name, perm)
        fused = _fused_combine(fused, b, seg_ids, T)
        level <<= 1
    return _split_back(fused, tensors, bounds)


def grouped_hierarchical_adasum_allreduce(tensors):
    """Fused hierarchical Adasum (see ``hierarchical_adasum_allreduce``
    for the semantics): LOCAL reduce-scatter on the concatenated buffer,
    per-tensor-scalar Adasum recursion across CROSS, LOCAL all-gather.
    Per-tensor dots survive the scatter because each rank's shard keeps
    its element→tensor segment map (sliced by ``axis_index``) and the
    scalars are psum'd over AXIS_LOCAL before use."""
    n = _axis_size(AXIS_CROSS)
    if not _is_power_of_two(n):
        raise ValueError(
            f"hierarchical Adasum requires a power-of-two cross size, got {n}"
        )
    fused, seg_ids, bounds = _fused_segments(tensors)
    T = len(tensors)
    local_n = _axis_size(AXIS_LOCAL)
    pad = (-fused.shape[0]) % local_n
    if pad:
        fused = jnp.pad(fused, (0, pad))
        # Padding elements get a dedicated segment so they never touch
        # any real tensor's dot/norm scalars.
        seg_ids = jnp.concatenate(
            [seg_ids, jnp.full((pad,), T, seg_ids.dtype)])
    a = lax.psum_scatter(fused, AXIS_LOCAL, tiled=True)
    shard_len = a.shape[0]
    my_seg = lax.dynamic_slice_in_dim(
        seg_ids, lax.axis_index(AXIS_LOCAL) * shard_len, shard_len)
    level = 1
    while level < n:
        perm = [(r, r ^ level) for r in range(n)]
        b = lax.ppermute(a, AXIS_CROSS, perm)
        a = _fused_combine(a, b, my_seg, T + 1,
                           extra_reduce=lambda s: lax.psum(s, AXIS_LOCAL))
        level <<= 1
    full = lax.all_gather(a, AXIS_LOCAL, tiled=True)
    if pad:
        full = full[: full.shape[0] - pad]
    return _split_back(full, tensors, bounds)


def hierarchical_adasum_allreduce(tensor):
    """Hierarchical Adasum over the (AXIS_CROSS, AXIS_LOCAL) hier mesh.

    Reference semantics (``AdasumGpuAllreduceOp``,
    ``adasum_gpu_operations.cc:38-270``): gradients within the fast LOCAL
    group are plain-summed — the reference runs NCCL ReduceScatter and
    starts VHDD at ``start_level = local_size``, i.e. the intra-node
    levels are ordinary summation — and the Adasum combination applies
    only ACROSS the slower CROSS links. The dot/norm scalars must still
    span the pair's FULL vectors, which after the reduce-scatter live
    distributed over the LOCAL axis; the reference reduces them over
    ``reduction_comms`` spanning every holder (``adasum_mpi.cc:29-69``),
    which here is a ``psum`` over AXIS_LOCAL (each cross rank holds its
    whole fragment, so no cross-block scalar reduction is needed — the
    halving that forced it in the reference is a point-to-point
    bandwidth optimization XLA's ICI collectives replace).

    TPU-native shape: reduce-scatter(SUM) along LOCAL (ICI), log2(cross)
    ``ppermute`` partner exchanges along CROSS (DCN) with LOCAL-psum'd
    fp32 scalars, then all-gather along LOCAL — all inside one compiled
    program.

    Note this is deliberately NOT numerically equal to the flat
    ``adasum_allreduce``: intra-group plain summation is the reference's
    documented hierarchical behavior (LR-scaling guidance ~= local_size,
    ``docs/adasum_user_guide.rst:208-210``).
    """
    n = _axis_size(AXIS_CROSS)
    if not _is_power_of_two(n):
        raise ValueError(
            f"hierarchical Adasum requires a power-of-two cross size, got {n}"
        )
    dtype = tensor.dtype
    shape = tensor.shape
    flat = jnp.ravel(tensor).astype(jnp.float32)
    local_n = _axis_size(AXIS_LOCAL)
    pad = (-flat.shape[0]) % local_n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    a = lax.psum_scatter(flat, AXIS_LOCAL, tiled=True)
    level = 1
    while level < n:
        perm = [(r, r ^ level) for r in range(n)]
        b = lax.ppermute(a, AXIS_CROSS, perm)
        dot = lax.psum(jnp.sum(a * b), AXIS_LOCAL)
        na = lax.psum(jnp.sum(a * a), AXIS_LOCAL)
        nb = lax.psum(jnp.sum(b * b), AXIS_LOCAL)
        a = _combine_with_scalars(a, b, dot, na, nb)
        level <<= 1
    full = lax.all_gather(a, AXIS_LOCAL, tiled=True)
    if pad:
        full = full[: flat.shape[0] - pad]
    return jnp.reshape(full, shape).astype(dtype)


# ---- NumPy reference (test oracle, mirrors test_adasum_pytorch.py's role) --


def adasum_reference(tensors):
    """Pure-NumPy recursive-halving-free Adasum over a list of vectors.

    Used by the test suite as the ground-truth oracle, the same role the
    NumPy model plays in the reference's ``test_adasum_pytorch.py:216``.
    """
    vecs = [np.asarray(t, dtype=np.float64) for t in tensors]
    n = len(vecs)
    assert _is_power_of_two(n), "adasum reference needs power-of-two inputs"

    def combine(a, b, eps=1e-30):
        dot = float(np.sum(a * b))
        na = float(np.sum(a * a))
        nb = float(np.sum(b * b))
        ca = 1.0 if na <= eps else 1.0 - dot / (2.0 * na)
        cb = 1.0 if nb <= eps else 1.0 - dot / (2.0 * nb)
        return ca * a + cb * b

    while len(vecs) > 1:
        vecs = [combine(vecs[i], vecs[i + 1]) for i in range(0, len(vecs), 2)]
    return vecs[0]


def hierarchical_adasum_reference(tensors, local_size):
    """NumPy oracle for ``hierarchical_adasum_allreduce``: plain sum
    within each consecutive ``local_size`` group (cross-major rank
    order), Adasum across the group sums — the reference's documented
    NCCL-mode behavior (intra-node summation, ``adasum_user_guide.rst``).
    """
    assert len(tensors) % local_size == 0
    sums = [
        np.sum([np.asarray(t, dtype=np.float64)
                for t in tensors[g: g + local_size]], axis=0)
        for g in range(0, len(tensors), local_size)
    ]
    if len(sums) == 1:
        return sums[0]
    return adasum_reference(sums)
