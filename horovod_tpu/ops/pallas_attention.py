"""Flash attention as a Pallas TPU kernel.

The hot op of the transformer stack, written for the hardware: Q/K/V tiles
stream HBM -> VMEM, the S = QK^T and P.V matmuls run on the MXU in fp32,
and the online-softmax state (running max / normalizer / accumulator)
lives in VMEM scratch across the innermost K-tile grid dimension, so the
full attention matrix never materializes (the same streaming-accumulation
math as ``parallel.ring_attention``).

Scope: forward AND backward. Training's forward emits the per-row
log-sum-exp alongside O; the backward is the standard flash backward as
two Pallas kernels — one accumulating dQ across K tiles, one accumulating
dK/dV across Q tiles — each re-materializing P = exp(S - lse) on-chip from
the saved lse, so neither pass ever writes the attention matrix to HBM.
``flash_attention_block_grads`` exposes the same per-block backward for
ring attention's backward ring pass (``parallel.ring_attention``).

Block offsets ride in as prefetched scalars, so the same kernel serves
ring attention's rotating K/V blocks (global causal masking between
sequence blocks) and the plain single-block case. On CPU the kernel runs
in interpreter mode (tests); on TPU it compiles through Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..common.compat import pallas_tpu_compiler_params as _compiler_params

NEG_INF = -1e30

# Tile sizes: multiples of the fp32 (8, 128) tile, sized by an on-chip
# sweep (v5e, T=2048 D=128 causal): 512x512 runs 1.18x faster than XLA's
# fused attention; 128x128 pays too much per-step overhead. VMEM use at
# D=128 stays ~1 MB per pipeline stage.
BLOCK_Q = 512
BLOCK_K = 512


def _attn_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                 acc_ref, *, causal: bool, block_q: int, block_k: int,
                 num_k_tiles: int, return_state: bool = False,
                 mo_ref=None, lo_ref=None, lse_ref=None,
                 qs_ref=None, ks_ref=None, window=None):
    """One (batch*head, q-tile, k-tile) grid step.

    Refs: q (1, block_q, D), k/v (1, block_k, D), o (1, block_q, D);
    scratch m/l (block_q, 1) and acc (block_q, D) carry the online-softmax
    state across the sequential k dimension. offs = [q_off, k_off] global
    token offsets of sequence block 0 (ring attention rotates k blocks).
    qs/ks (1, block, 1) int32: optional packed-sequence segment ids —
    the mask composes with causal at trace time, so the segment-free
    path compiles identically to before.
    """
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # program_id must be read OUTSIDE pl.when bodies (the predicated
    # sub-jaxpr escapes the interpreter's program_id rewrite).
    qi = pl.program_id(1)
    q_base = offs_ref[0] + qi * block_q
    k_base = offs_ref[1] + ki * block_k
    if causal:
        # Causal tile culling: a K tile strictly in this Q tile's future
        # contributes nothing — predicate the whole update away (halves
        # the causal FLOPs; the reference flash kernels do the same).
        visible = q_base + block_q - 1 >= k_base
        if window is not None:
            # Sliding-window culling: a K tile entirely beyond the
            # window into this Q tile's past is dead too — for
            # T >> window most tiles skip, the real SWA saving.
            visible = jnp.logical_and(
                visible, k_base + block_k - 1 >= q_base - (window - 1))
    else:
        visible = True

    @pl.when(visible)
    def _update():
        # Feed the MXU its native input dtype (bf16 x bf16 -> f32
        # accumulate); pre-casting to f32 would halve matmul throughput.
        q = q_ref[0]
        k = k_ref[0]
        scale = 1.0 / (q.shape[-1] ** 0.5)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        if causal:
            q_pos = (q_base +
                     jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
            k_pos = (k_base +
                     jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            if window is not None:
                s = jnp.where(q_pos - k_pos < window, s, NEG_INF)
        if qs_ref is not None:
            s = jnp.where(qs_ref[0] == ks_ref[0].reshape(1, -1),
                          s, NEG_INF)

        m_prev = m_ref[:]                      # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alive = m_new > NEG_INF / 2
        corr = jnp.where(alive, jnp.exp(m_prev - m_new), 1.0)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        l_new = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        # P rides the MXU in the V dtype (f32 accumulation preserved by
        # preferred_element_type) — the standard TPU flash-kernel trade.
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = m_new
        l_ref[:] = l_new

    @pl.when(ki == num_k_tiles - 1)
    def _finalize():
        if return_state:
            # Block mode (ring attention): emit the UNnormalized
            # accumulator plus (m, l) so the caller merges blocks with the
            # standard online-softmax combine.
            o_ref[0] = acc_ref[:].astype(o_ref.dtype)
            mo_ref[0] = m_ref[:]
            lo_ref[0] = l_ref[:]
        else:
            o_ref[0] = (acc_ref[:] /
                        jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)
            if lse_ref is not None:
                # Rows with no visible key get +NEG_INF's negation so the
                # backward's exp(s - lse) underflows to exactly zero
                # instead of exploding on lse = -inf.
                m = m_ref[:]
                l = l_ref[:]
                lse_ref[0] = jnp.where(l > 0.0, m + jnp.log(
                    jnp.maximum(l, 1e-30)), -NEG_INF)


def _attn_kernel_state(offs_ref, q_ref, k_ref, v_ref, o_ref, mo_ref,
                       lo_ref, m_ref, l_ref, acc_ref, **kw):
    """Block-mode positional adapter: pallas passes outputs before
    scratch, so the three outputs (acc, m, l) precede the scratch refs."""
    _attn_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                 acc_ref, return_state=True, mo_ref=mo_ref, lo_ref=lo_ref,
                 **kw)


def _attn_kernel_state_seg(offs_ref, q_ref, k_ref, v_ref, qs_ref, ks_ref,
                           o_ref, mo_ref, lo_ref, m_ref, l_ref, acc_ref,
                           **kw):
    """Block-mode adapter with segment-id tiles (inputs ride after v)."""
    _attn_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                 acc_ref, return_state=True, mo_ref=mo_ref, lo_ref=lo_ref,
                 qs_ref=qs_ref, ks_ref=ks_ref, **kw)


def _attn_kernel_train(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                       m_ref, l_ref, acc_ref, **kw):
    """Training-forward adapter: normalized O plus the per-row lse
    residual the flash backward re-materializes P from."""
    _attn_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                 acc_ref, lse_ref=lse_ref, **kw)


def _attn_kernel_train_seg(offs_ref, q_ref, k_ref, v_ref, qs_ref, ks_ref,
                           o_ref, lse_ref, m_ref, l_ref, acc_ref, **kw):
    """Training-forward adapter with segment-id tiles."""
    _attn_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                 acc_ref, lse_ref=lse_ref, qs_ref=qs_ref, ks_ref=ks_ref,
                 **kw)


def _attn_bwd_dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                        delta_ref, dq_ref, dq_acc, *, causal: bool,
                        block_q: int, block_k: int, num_k_tiles: int,
                        qs_ref=None, ks_ref=None, window=None):
    """dQ pass: grid (batch*head, q-tile, k-tile), sequential over K tiles.

    P = exp(S - lse) is rebuilt on-chip from the saved lse;
    dS = P * (dO.V^T - delta); dQ accumulates dS.K in VMEM across the K
    dimension. delta = rowsum(dO * O), precomputed by the caller.
    """
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    qi = pl.program_id(1)
    q_base = offs_ref[0] + qi * block_q
    k_base = offs_ref[1] + ki * block_k
    visible = (q_base + block_q - 1 >= k_base) if causal else True
    if causal and window is not None:
        visible = jnp.logical_and(
            visible, k_base + block_k - 1 >= q_base - (window - 1))

    @pl.when(visible)
    def _update():
        q = q_ref[0]
        k = k_ref[0]
        scale = 1.0 / (q.shape[-1] ** 0.5)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # [bq, bk]
        p = jnp.exp(s - lse_ref[0])                          # [bq, bk]
        if causal:
            q_pos = q_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = k_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
            if window is not None:
                p = jnp.where(q_pos - k_pos < window, p, 0.0)
        if qs_ref is not None:
            p = jnp.where(qs_ref[0] == ks_ref[0].reshape(1, -1), p, 0.0)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, bk]
        ds = p * (dp - delta_ref[0]) * scale                 # [bq, bk]
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, D]

    @pl.when(ki == num_k_tiles - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _attn_bwd_dq_kernel_seg(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                            delta_ref, qs_ref, ks_ref, dq_ref, dq_acc,
                            **kw):
    """dQ adapter with segment-id tiles (inputs ride after delta)."""
    _attn_bwd_dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                        delta_ref, dq_ref, dq_acc, qs_ref=qs_ref,
                        ks_ref=ks_ref, **kw)


def _attn_bwd_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                         causal: bool, block_q: int, block_k: int,
                         num_q_tiles: int, qs_ref=None, ks_ref=None,
                         window=None):
    """dK/dV pass: grid (batch*head, k-tile, q-tile), sequential over Q
    tiles. Same [bq, bk] orientation as the dQ pass; the transposed
    contractions (P^T.dO, dS^T.Q) ride dot_general dimension numbers so
    no tile is ever explicitly transposed."""
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    ki = pl.program_id(1)
    q_base = offs_ref[0] + qi * block_q
    k_base = offs_ref[1] + ki * block_k
    visible = (q_base + block_q - 1 >= k_base) if causal else True
    if causal and window is not None:
        visible = jnp.logical_and(
            visible, k_base + block_k - 1 >= q_base - (window - 1))

    @pl.when(visible)
    def _update():
        q = q_ref[0]
        k = k_ref[0]
        do = do_ref[0]
        scale = 1.0 / (q.shape[-1] ** 0.5)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # [bq, bk]
        p = jnp.exp(s - lse_ref[0])
        if causal:
            q_pos = q_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = k_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
            if window is not None:
                p = jnp.where(q_pos - k_pos < window, p, 0.0)
        if qs_ref is not None:
            p = jnp.where(qs_ref[0] == ks_ref[0].reshape(1, -1), p, 0.0)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bk, D]
        dp = jax.lax.dot_general(
            do, v_ref[0], dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, bk]
        ds = p * (dp - delta_ref[0]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bk, D]

    @pl.when(qi == num_q_tiles - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _attn_bwd_dkv_kernel_seg(offs_ref, q_ref, k_ref, v_ref, do_ref,
                             lse_ref, delta_ref, qs_ref, ks_ref, dk_ref,
                             dv_ref, dk_acc, dv_acc, **kw):
    """dK/dV adapter with segment-id tiles (inputs ride after delta)."""
    _attn_bwd_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                         qs_ref=qs_ref, ks_ref=ks_ref, **kw)


def _seg3(seg):
    """[BH, T] int32 -> [BH, T, 1]: the row-oriented layout the lse/delta
    tiles already use. Mosaic requires the last two block dims be
    (8, 128)-divisible or full-extent; a (1, block, 1) tile satisfies
    that for EVERY _pick_block size (block >= 8 on the sublane dim, the
    lane dim full at 1) — the lane-major (1, 1, block) layout fails for
    blocks < 128."""
    return seg[:, :, None]


def _seg_specs(bq, bk):
    """BlockSpecs for the (1, block, 1) int32 segment-id tiles."""
    return [
        pl.BlockSpec((1, bq, 1), lambda bh, qi, ki, offs: (bh, qi, 0)),
        pl.BlockSpec((1, bk, 1), lambda bh, qi, ki, offs: (bh, ki, 0)),
    ]


def int_cotangent(x):
    """Symbolic-zero cotangent for an optional integer array argument of
    a custom_vjp (None passes through)."""
    import numpy as np

    return None if x is None else np.zeros(x.shape,
                                           dtype=jax.dtypes.float0)


def _pallas_block_state(q, k, v, offs, causal: bool, interpret: bool,
                        q_seg=None, k_seg=None, window=None):
    """q/k/v: [BH, T, D]. Returns (acc f32 [BH,Tq,D], m f32 [BH,Tq,1],
    l f32 [BH,Tq,1]) — the unmerged online-softmax state of this K block
    (ring attention merges blocks as they rotate). ``q_seg``/``k_seg``:
    optional int32 [BH, T] segment ids (streamed as extra tiles)."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    bq = _pick_block(Tq, BLOCK_Q)
    bk = _pick_block(Tk, BLOCK_K)
    num_q = Tq // bq
    num_k = Tk // bk

    from jax.experimental.pallas import tpu as pltpu

    in_specs = [
        pl.BlockSpec((1, bq, D), lambda bh, qi, ki, offs: (bh, qi, 0)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, ki, offs: (bh, ki, 0)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, ki, offs: (bh, ki, 0)),
    ]
    args = [offs, q, k, v]
    if q_seg is not None:
        in_specs += _seg_specs(bq, bk)
        args += [_seg3(q_seg), _seg3(k_seg)]
        kernel_fn = _attn_kernel_state_seg
    else:
        kernel_fn = _attn_kernel_state
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(BH, num_q, num_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D),
                         lambda bh, qi, ki, offs: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1),
                         lambda bh, qi, ki, offs: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1),
                         lambda bh, qi, ki, offs: (bh, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        kernel_fn, causal=causal, block_q=bq, block_k=bk,
        num_k_tiles=num_k, window=window)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, Tq, 1), jnp.float32),
            jax.ShapeDtypeStruct((BH, Tq, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)


def _apply_segment_mask(x, q_seg, k_seg, fill):
    """Packed-sequence masking, the single definition: positions with
    differing segment ids take ``fill`` (NEG_INF on scores, 0 on
    probabilities). x: [BH, Tq, Tk]; q_seg/k_seg: int32 [BH, T]."""
    return jnp.where(q_seg[:, :, None] == k_seg[:, None, :], x, fill)


def _require_both_segs(q_seg, k_seg):
    if (q_seg is None) != (k_seg is None):
        raise ValueError("pass both q_segment_ids and k_segment_ids")


def _check_window(window, causal):
    if window is None:
        return
    if not causal:
        raise ValueError("sliding-window attention is defined for the "
                         "causal case; pass causal=True with window")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def _xla_block_state(q, k, v, offs, causal, q_seg=None, k_seg=None,
                     window=None):
    """XLA twin of the block-mode kernel (backward recompute + fallback).
    ``offs`` = int32[2] (q_off, k_off) — an array, not statics, because
    ring attention traces the rotating block origin. ``q_seg``/``k_seg``:
    optional int32 [BH, T] per-block segment ids (packed sequences)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("btd,bsd->bts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        iq = jnp.arange(q.shape[1])[:, None] + offs[0]
        ik = jnp.arange(k.shape[1])[None, :] + offs[1]
        s = jnp.where(iq >= ik, s, NEG_INF)
        if window is not None:
            s = jnp.where(iq - ik < window, s, NEG_INF)
    if q_seg is not None:
        s = _apply_segment_mask(s, q_seg, k_seg, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m))
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bts,bsd->btd", p.astype(v.dtype),
                     v).astype(jnp.float32)
    return acc, m, l


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _block_state_core(q, k, v, offs, q_seg, k_seg, causal, interpret,
                      window):
    if _pick_block(q.shape[1], BLOCK_Q) is None or \
            _pick_block(k.shape[1], BLOCK_K) is None:
        return _xla_block_state(q, k, v, offs, causal, q_seg=q_seg,
                                k_seg=k_seg, window=window)
    return _pallas_block_state(q, k, v, offs, causal, interpret,
                               q_seg=q_seg, k_seg=k_seg, window=window)


def _block_state_fwd(q, k, v, offs, q_seg, k_seg, causal, interpret,
                     window):
    return _block_state_core(q, k, v, offs, q_seg, k_seg, causal,
                             interpret, window), (q, k, v, offs, q_seg,
                                                  k_seg)


def _block_state_bwd(causal, interpret, window, res, g):
    import numpy as np

    q, k, v, offs, q_seg, k_seg = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _xla_block_state(q_, k_, v_, offs, causal,
                                            q_seg=q_seg, k_seg=k_seg,
                                            window=window),
        q, k, v)
    dq, dk, dv = vjp(g)

    # Integer offsets/segment ids carry the symbolic-zero cotangent.
    return (dq, dk, dv, np.zeros((2,), dtype=jax.dtypes.float0),
            int_cotangent(q_seg), int_cotangent(k_seg))


_block_state_core.defvjp(_block_state_fwd, _block_state_bwd)


def _resolve_dispatch(use_pallas: Optional[bool]):
    """Shared backend policy: (use_pallas, interpret). Mosaic on TPU,
    interpreter under HVD_PALLAS_INTERPRET=1 (tests), XLA elsewhere."""
    import os

    if use_pallas is None:
        platform = jax.default_backend()
        if platform == "tpu":
            return True, False
        if os.environ.get("HVD_PALLAS_INTERPRET"):
            return True, True
        return False, False
    if use_pallas:
        return True, jax.default_backend() != "tpu"
    return False, False


def _merge_heads(x):
    """[B, T, H, D] -> [B*H, T, D]."""
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def flash_attention_block(q, k, v, q_off, k_off, causal: bool = True,
                          use_pallas: Optional[bool] = None,
                          q_segment_ids=None, k_segment_ids=None,
                          window: Optional[int] = None):
    """One K/V block's unmerged attention state for ring attention.

    q/k/v: [B, T, H, D]. Returns (acc, m, l) with acc f32 [B, T, H, D]
    (unnormalized P.V), m/l f32 [B, H, T] — merge across blocks with the
    online-softmax combine. Dispatch rules match ``flash_attention``
    (shared ``_resolve_dispatch``); segment ids stream into the same
    kernels as extra id tiles (packed sequences).
    """
    B, Tq, H, D = q.shape

    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    _require_both_segs(q_segment_ids, k_segment_ids)
    q_seg = k_seg = None
    if q_segment_ids is not None:
        q_seg = _tile_seg(q_segment_ids, H)
        k_seg = _tile_seg(k_segment_ids, H)
    _check_window(window, causal)
    use_pallas, interpret = _resolve_dispatch(use_pallas)
    if use_pallas:
        acc, m, l = _block_state_core(
            _merge_heads(q), _merge_heads(k), _merge_heads(v), offs,
            q_seg, k_seg, causal, interpret, window)
    else:
        acc, m, l = _xla_block_state(
            _merge_heads(q), _merge_heads(k), _merge_heads(v), offs,
            causal, q_seg=q_seg, k_seg=k_seg, window=window)
    acc = acc.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    m = m.reshape(B, H, Tq)
    l = l.reshape(B, H, Tq)
    return acc, m, l


def flash_attention_block_grads(q, k, v, do, lse, delta, q_off, k_off,
                                causal: bool = True,
                                use_pallas: Optional[bool] = None,
                                q_segment_ids=None, k_segment_ids=None,
                                window: Optional[int] = None):
    """One K/V block's (dq, dk, dv) for ring attention's backward pass.

    q/k/v/do: [B, T, H, D]; lse/delta: f32 [B, H, T] — the GLOBAL row
    statistics (lse over all keys, delta = rowsum(dO*O)), so each block's
    P = exp(S - lse) is already globally normalized and the per-block
    gradients simply sum across the ring. Returns f32 arrays in the
    [B, T, H, D] layout (f32 so the ring's cross-block accumulation
    doesn't round at the model dtype each step).
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    use_pallas, interpret = _resolve_dispatch(use_pallas)

    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    qm, km, vm, dom = (_merge_heads(x) for x in (q, k, v, do))
    lse_m = lse.reshape(B * H, Tq, 1)
    delta_m = delta.reshape(B * H, Tq, 1)
    _require_both_segs(q_segment_ids, k_segment_ids)
    q_seg = k_seg = None
    if q_segment_ids is not None:
        q_seg = _tile_seg(q_segment_ids, H)
        k_seg = _tile_seg(k_segment_ids, H)
    _check_window(window, causal)
    if use_pallas and _pick_block(Tq, BLOCK_Q) is not None and \
            _pick_block(Tk, BLOCK_K) is not None:
        dq, dk, dv = _pallas_bwd(qm, km, vm, dom, lse_m, delta_m, offs,
                                 causal, interpret, out_dtype=jnp.float32,
                                 q_seg=q_seg, k_seg=k_seg, window=window)
    else:
        dq, dk, dv = _xla_block_grads(qm, km, vm, dom, lse_m, delta_m,
                                      offs, causal, out_dtype=jnp.float32,
                                      q_seg=q_seg, k_seg=k_seg,
                                      window=window)

    def split(x, t):
        return x.reshape(B, H, t, D).transpose(0, 2, 1, 3)

    return split(dq, Tq), split(dk, Tk), split(dv, Tk)


def _attn_kernel_seg(offs_ref, q_ref, k_ref, v_ref, qs_ref, ks_ref,
                     o_ref, m_ref, l_ref, acc_ref, **kw):
    """Plain-forward adapter with segment-id tiles (no lse residual)."""
    _attn_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                 acc_ref, qs_ref=qs_ref, ks_ref=ks_ref, **kw)


def _pallas_attention_fwd(q, k, v, q_off, k_off, causal: bool,
                          interpret: bool, q_seg=None, k_seg=None,
                          window=None):
    """q/k/v: [BH, T, D] (already merged batch*heads, padded to tiles)."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    bq = _pick_block(Tq, BLOCK_Q)
    bk = _pick_block(Tk, BLOCK_K)
    num_q = Tq // bq
    num_k = Tk // bk

    from jax.experimental.pallas import tpu as pltpu

    in_specs = [
        pl.BlockSpec((1, bq, D), lambda bh, qi, ki, offs: (bh, qi, 0)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, ki, offs: (bh, ki, 0)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, ki, offs: (bh, ki, 0)),
    ]
    offs = jnp.asarray([q_off, k_off], jnp.int32)
    args = [offs, q, k, v]
    if q_seg is not None:
        in_specs += _seg_specs(bq, bk)
        args += [_seg3(q_seg), _seg3(k_seg)]
        kernel_fn = _attn_kernel_seg
    else:
        kernel_fn = _attn_kernel
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(BH, num_q, num_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, D),
                               lambda bh, qi, ki, offs: (bh, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        kernel_fn, causal=causal, block_q=bq, block_k=bk,
        num_k_tiles=num_k, window=window)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)


def _pallas_attention_fwd_train(q, k, v, offs, causal: bool,
                                interpret: bool, q_seg=None, k_seg=None,
                                window=None):
    """Forward with residuals: (o [BH,T,D] in q.dtype, lse f32 [BH,T,1])."""
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    bq = _pick_block(Tq, BLOCK_Q)
    bk = _pick_block(Tk, BLOCK_K)
    num_q = Tq // bq
    num_k = Tk // bk

    from jax.experimental.pallas import tpu as pltpu

    in_specs = [
        pl.BlockSpec((1, bq, D), lambda bh, qi, ki, offs: (bh, qi, 0)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, ki, offs: (bh, ki, 0)),
        pl.BlockSpec((1, bk, D), lambda bh, qi, ki, offs: (bh, ki, 0)),
    ]
    args = [offs, q, k, v]
    if q_seg is not None:
        in_specs += _seg_specs(bq, bk)
        args += [_seg3(q_seg), _seg3(k_seg)]
        kernel_fn = _attn_kernel_train_seg
    else:
        kernel_fn = _attn_kernel_train
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(BH, num_q, num_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi, ki, offs: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi, ki, offs: (bh, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        kernel_fn, causal=causal, block_q=bq, block_k=bk,
        num_k_tiles=num_k, window=window)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Tq, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)


def _pallas_bwd(q, k, v, do, lse, delta, offs, causal: bool,
                interpret: bool, out_dtype=None, q_seg=None, k_seg=None,
                window=None):
    """The two flash-backward kernels; returns (dq, dk, dv) in the input
    dtypes (or ``out_dtype`` when given — ring accumulation wants f32).
    lse/delta: f32 [BH, T, 1]."""
    dq_dt = out_dtype or q.dtype
    dk_dt = out_dtype or k.dtype
    dv_dt = out_dtype or v.dtype
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    bq = _pick_block(Tq, BLOCK_Q)
    bk = _pick_block(Tk, BLOCK_K)
    num_q = Tq // bq
    num_k = Tk // bk

    from jax.experimental.pallas import tpu as pltpu

    q_spec = pl.BlockSpec((1, bq, D), lambda bh, qi, ki, offs: (bh, qi, 0))
    k_spec = pl.BlockSpec((1, bk, D), lambda bh, qi, ki, offs: (bh, ki, 0))
    row_spec = pl.BlockSpec((1, bq, 1), lambda bh, qi, ki, offs: (bh, qi, 0))
    dq_in_specs = [q_spec, k_spec, k_spec, q_spec, row_spec, row_spec]
    dq_args = [offs, q, k, v, do, lse, delta]
    if q_seg is not None:
        dq_in_specs += _seg_specs(bq, bk)
        dq_args += [_seg3(q_seg), _seg3(k_seg)]
        dq_kernel = _attn_bwd_dq_kernel_seg
    else:
        dq_kernel = _attn_bwd_dq_kernel
    dq = pl.pallas_call(
        functools.partial(dq_kernel, causal=causal, block_q=bq,
                          block_k=bk, num_k_tiles=num_k, window=window),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, num_q, num_k),
            in_specs=dq_in_specs,
            out_specs=q_spec,
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((BH, Tq, D), dq_dt),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dq_args)

    # dK/dV pass: K tiles are the parallel dimension, Q tiles sequential.
    qkv_spec = pl.BlockSpec((1, bq, D), lambda bh, ki, qi, offs: (bh, qi, 0))
    kkv_spec = pl.BlockSpec((1, bk, D), lambda bh, ki, qi, offs: (bh, ki, 0))
    rowkv_spec = pl.BlockSpec((1, bq, 1),
                              lambda bh, ki, qi, offs: (bh, qi, 0))
    kv_in_specs = [qkv_spec, kkv_spec, kkv_spec, qkv_spec, rowkv_spec,
                   rowkv_spec]
    kv_args = [offs, q, k, v, do, lse, delta]
    if q_seg is not None:
        kv_in_specs += [
            pl.BlockSpec((1, bq, 1), lambda bh, ki, qi, offs: (bh, qi, 0)),
            pl.BlockSpec((1, bk, 1), lambda bh, ki, qi, offs: (bh, ki, 0)),
        ]
        kv_args += [_seg3(q_seg), _seg3(k_seg)]
        kv_kernel = _attn_bwd_dkv_kernel_seg
    else:
        kv_kernel = _attn_bwd_dkv_kernel
    dk, dv = pl.pallas_call(
        functools.partial(kv_kernel, causal=causal, block_q=bq,
                          block_k=bk, num_q_tiles=num_q, window=window),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, num_k, num_q),
            in_specs=kv_in_specs,
            out_specs=[kkv_spec, kkv_spec],
            scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                            pltpu.VMEM((bk, D), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((BH, Tk, D), dk_dt),
                   jax.ShapeDtypeStruct((BH, Tk, D), dv_dt)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*kv_args)
    return dq, dk, dv


def _xla_block_grads(q, k, v, do, lse, delta, offs, causal: bool,
                     out_dtype=None, q_seg=None, k_seg=None, window=None):
    """XLA twin of the backward kernels (fallback for untileable shapes
    and non-TPU platforms). Same math, same lse/delta residuals."""
    dq_dt = out_dtype or q.dtype
    dk_dt = out_dtype or k.dtype
    dv_dt = out_dtype or v.dtype
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("btd,bsd->bts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    p = jnp.exp(s - lse)
    if causal:
        iq = jnp.arange(q.shape[1])[:, None] + offs[0]
        ik = jnp.arange(k.shape[1])[None, :] + offs[1]
        p = jnp.where((iq >= ik)[None], p, 0.0)
        if window is not None:
            p = jnp.where((iq - ik < window)[None], p, 0.0)
    if q_seg is not None:
        p = _apply_segment_mask(p, q_seg, k_seg, 0.0)
    dof = do.astype(jnp.float32)
    dv = jnp.einsum("bts,btd->bsd", p, dof)
    dp = jnp.einsum("btd,bsd->bts", dof, v.astype(jnp.float32))
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bts,bsd->btd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bts,btd->bsd", ds, q.astype(jnp.float32))
    return dq.astype(dq_dt), dk.astype(dk_dt), dv.astype(dv_dt)


def _pick_block(t: int, cap: int) -> Optional[int]:
    """Largest MXU-friendly tile (multiple of the fp32 sublane count, up
    to ``cap``) that divides ``t``; None when ``t`` isn't tileable
    (callers fall back to the XLA path rather than reason about
    padded-position masking). Candidates extend above the 512 default so
    a BLOCK_Q/BLOCK_K override (tools/pallas_bench.py --sweep-blocks)
    genuinely changes the tiling."""
    for c in (2048, 1024, 512, 256, 128, 64, 32, 16, 8):
        if c <= cap and t % c == 0:
            return c
    return None


def _xla_flash(q, k, v, q_off, k_off, causal, q_seg=None, k_seg=None,
               window=None):
    """XLA reference path (backward recompute + non-TPU fallback), fp32
    accumulation — the same math as parallel.ring_attention.
    ``q_seg``/``k_seg``: optional int32 [BH, T] segment ids (packed
    sequences); tokens attend only within their segment."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("btd,bsd->bts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        iq = jnp.arange(q.shape[1])[:, None] + q_off
        ik = jnp.arange(k.shape[1])[None, :] + k_off
        s = jnp.where(iq >= ik, s, NEG_INF)
        if window is not None:
            s = jnp.where(iq - ik < window, s, NEG_INF)
    if q_seg is not None:
        s = _apply_segment_mask(s, q_seg, k_seg, NEG_INF)
    # Rows whose keys are all masked normalize to zero output, matching
    # the kernel's max(l, eps) guard.
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m))
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    o = jnp.einsum("bts,bsd->btd", p / l, v.astype(jnp.float32))
    return o.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_core(q, k, v, q_seg, k_seg, q_off, k_off, causal, interpret,
                window):
    if _pick_block(q.shape[1], BLOCK_Q) is None or \
            _pick_block(k.shape[1], BLOCK_K) is None:
        return _xla_flash(q, k, v, q_off, k_off, causal, q_seg=q_seg,
                          k_seg=k_seg, window=window)
    return _pallas_attention_fwd(q, k, v, q_off, k_off, causal, interpret,
                                 q_seg=q_seg, k_seg=k_seg, window=window)


def _flash_fwd(q, k, v, q_seg, k_seg, q_off, k_off, causal, interpret,
               window):
    if _pick_block(q.shape[1], BLOCK_Q) is None or \
            _pick_block(k.shape[1], BLOCK_K) is None:
        return _xla_flash(q, k, v, q_off, k_off, causal, q_seg=q_seg,
                          k_seg=k_seg, window=window), \
            (q, k, v, q_seg, k_seg, None, None)
    offs = jnp.asarray([q_off, k_off], jnp.int32)
    o, lse = _pallas_attention_fwd_train(q, k, v, offs, causal, interpret,
                                         q_seg=q_seg, k_seg=k_seg,
                                         window=window)
    return o, (q, k, v, q_seg, k_seg, o, lse)


def _flash_bwd(q_off, k_off, causal, interpret, window, res, g):
    q, k, v, q_seg, k_seg, o, lse = res

    if lse is None:
        # Untileable shapes: recompute through the XLA twin.
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _xla_flash(q_, k_, v_, q_off, k_off, causal,
                                          q_seg=q_seg, k_seg=k_seg,
                                          window=window),
            q, k, v)
        return (*vjp(g), int_cotangent(q_seg), int_cotangent(k_seg))
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    offs = jnp.asarray([q_off, k_off], jnp.int32)
    dq, dk, dv = _pallas_bwd(q, k, v, g, lse, delta, offs, causal,
                             interpret, q_seg=q_seg, k_seg=k_seg,
                             window=window)
    return dq, dk, dv, int_cotangent(q_seg), int_cotangent(k_seg)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def _tile_seg(seg, heads):
    """[B, T] int segment ids -> [B*H, T] aligned with _merge_heads."""
    return jnp.repeat(jnp.asarray(seg, jnp.int32), heads, axis=0)


def flash_attention(q, k, v, causal: bool = True, q_off: int = 0,
                    k_off: int = 0, use_pallas: Optional[bool] = None,
                    q_segment_ids=None, k_segment_ids=None,
                    window: Optional[int] = None):
    """Blocked flash attention. q/k/v: [B, T, H, D].

    ``use_pallas=None`` auto-selects via ``_resolve_dispatch``.
    ``q_off``/``k_off`` are the global token offsets of the blocks — ring
    attention passes the rotating K block's origin so causal masking stays
    globally correct.

    ``q_segment_ids``/``k_segment_ids`` (int [B, T]): packed-sequence
    masking — a token attends only to keys with its segment id (composed
    with the causal mask). The Mosaic kernels stream the ids as extra
    (1, block) int32 tiles; the mask composes at trace time so the
    segment-free path compiles unchanged.
    """
    B, Tq, H, D = q.shape

    def split(x, t):
        return x.reshape(B, H, t, D).transpose(0, 2, 1, 3)

    _require_both_segs(q_segment_ids, k_segment_ids)
    _check_window(window, causal)
    q_seg = k_seg = None
    if q_segment_ids is not None:
        q_seg = _tile_seg(q_segment_ids, H)
        k_seg = _tile_seg(k_segment_ids, H)

    use_pallas, interpret = _resolve_dispatch(use_pallas)
    if not use_pallas:
        out = _xla_flash(_merge_heads(q), _merge_heads(k), _merge_heads(v),
                         q_off, k_off, causal, q_seg=q_seg, k_seg=k_seg,
                         window=window)
        return split(out, Tq)
    out = _flash_core(_merge_heads(q), _merge_heads(k), _merge_heads(v),
                      q_seg, k_seg, q_off, k_off, causal, interpret,
                      window)
    return split(out, Tq)
