from . import xla  # noqa: F401
from .xla import Adasum, Average, Max, Min, ReduceOp, Sum  # noqa: F401
