"""Eager collective engine: Horovod's dynamic-enqueue API on top of XLA.

The reference's eager contract (``EnqueueTensorAllreduce`` et al.,
``operations.cc:810-961``) is "any rank may submit any named tensor at any
time; a handle resolves when the collective completes". On TPU, execution is
compiled, so the engine re-creates that contract with a *compile cache*: each
(op, shape, dtype, params) signature lazily builds one jitted
``jax.shard_map`` program over the global mesh, cached forever after —
the analog of the reference's lazy NCCL communicator/plan init
(``nccl_operations.cc:60-93``), with compile-cache misses as the new
"INIT_NCCL" one-time stall (SURVEY §7 "hard parts").

Asynchrony comes from XLA's own async dispatch: launching a compiled program
returns immediately with futures (jax.Array), so handles are genuine
futures — the role of the reference's HandleManager
(``torch/handle_manager.{h,cc}``) — with no extra background thread needed
for the single-controller fast path.

Input convention (TPU-first): a single process drives ``local_size`` chips,
so eager calls carry a leading per-participant axis of length
``local_size`` (or a list of that length). When ``local_size == 1`` the
plain unstacked tensor is accepted, which makes one-chip-per-process
scripts read exactly like reference Horovod scripts. A replicated
(unstacked) input on a multi-chip world is treated as "same tensor on every
chip".
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..common import logging as _log
from ..common.exceptions import DuplicateTensorNameError, HorovodInternalError
from ..common.state import AXIS_GLOBAL
from . import xla as _xla


def _shard_map(fn, mesh, in_specs, out_specs):
    # check_vma=False: collective outputs (e.g. all_gather) are replicated
    # by construction, which the static VMA checker cannot always infer.
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


class _Handle:
    """A future for an in-flight eager collective."""

    __slots__ = ("result", "name", "postprocess", "error")

    def __init__(self, result, name, postprocess=None, error=None):
        self.result = result
        self.name = name
        self.postprocess = postprocess
        self.error = error


class EagerEngine:
    """Per-process engine: compile cache + handle table + name registry."""

    def __init__(self, state):
        self._state = state
        self._mesh = state.mesh
        self._lock = threading.Lock()
        self._program_cache: Dict[Tuple, Any] = {}
        self._handles: Dict[int, _Handle] = {}
        self._next_handle = 0
        self._inflight_names: set = set()
        self._name_counter = 0

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self):
        with self._lock:
            self._handles.clear()
            self._program_cache.clear()
            self._inflight_names.clear()

    # -- helpers -------------------------------------------------------------

    def _auto_name(self, prefix: str) -> str:
        with self._lock:
            self._name_counter += 1
            return f"{prefix}.noname.{self._name_counter}"

    def _register_name(self, name: str):
        with self._lock:
            if name in self._inflight_names:
                raise DuplicateTensorNameError(
                    f"tensor name '{name}' already submitted and not yet complete"
                )
            self._inflight_names.add(name)

    def _release_name(self, name: str):
        with self._lock:
            self._inflight_names.discard(name)

    def _normalize(self, tensor) -> Tuple[jnp.ndarray, bool, bool]:
        """Returns (stacked [local_size, ...] host/jax array, was_list,
        was_unstacked)."""
        L = self._state.local_size
        if isinstance(tensor, (list, tuple)):
            if len(tensor) != L:
                raise ValueError(
                    f"eager collective got a list of {len(tensor)} tensors; "
                    f"expected local_size={L} (one per locally-driven chip)"
                )
            return jnp.stack([jnp.asarray(t) for t in tensor]), True, False
        t = jnp.asarray(tensor)
        if L == 1:
            return t[None], False, True
        if t.ndim >= 1 and t.shape[0] == L:
            return t, False, False
        # Replicated convenience: same tensor on every local participant.
        return jnp.broadcast_to(t[None], (L,) + t.shape), False, True

    def _to_global(self, stacked):
        """Build the global (size, ...) array sharded one-slice-per-chip."""
        sharding = NamedSharding(self._mesh, P(AXIS_GLOBAL))
        if self._state.process_count == 1:
            return jax.device_put(stacked, sharding)
        global_shape = (self._state.size,) + tuple(stacked.shape[1:])
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(stacked), global_shape
        )

    def _from_global_sharded(self, arr, was_list, was_unstacked):
        """Extract this process's local slices of a P('hvd')-sharded result."""
        shards = sorted(arr.addressable_shards, key=lambda s: s.index[0].start)
        local = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
        if was_list:
            return [local[i] for i in range(local.shape[0])]
        if was_unstacked:
            return local[0]
        return local

    def _program(self, key, builder):
        prog = self._program_cache.get(key)
        if prog is None:
            _log.debug(f"compiling eager collective program {key}")
            prog = builder()
            self._program_cache[key] = prog
        return prog

    def _new_handle(self, result, name, postprocess=None, error=None) -> int:
        with self._lock:
            h = self._next_handle
            self._next_handle += 1
            self._handles[h] = _Handle(result, name, postprocess, error)
            return h

    # -- collectives ---------------------------------------------------------

    def allreduce_async(self, tensor, name: Optional[str] = None,
                        op: int = _xla.ReduceOp.SUM,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0) -> int:
        name = name or self._auto_name("allreduce")
        # Input validation raises synchronously (ValueError etc.); only
        # execution failures are deferred to the handle and surface as
        # HorovodInternalError at synchronize() time, matching the
        # reference's callback-status contract (torch/mpi_ops.py:126-127).
        stacked, was_list, was_unstacked = self._normalize(tensor)
        self._register_name(name)
        try:
            if op == _xla.ReduceOp.ADASUM and not _is_pow2(self._state.size):
                _log.warning(
                    "Adasum requested with non-power-of-two size; "
                    "falling back to Average"
                )
                op = _xla.ReduceOp.AVERAGE
            key = ("allreduce", stacked.shape[1:], str(stacked.dtype), op,
                   prescale_factor, postscale_factor)
            mesh = self._mesh

            def build():
                def fn(x):
                    y = _xla.allreduce(
                        x[0], axis_name=AXIS_GLOBAL, op=op,
                        prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor,
                    )
                    return y[None]

                return jax.jit(
                    _shard_map(fn, mesh, in_specs=P(AXIS_GLOBAL),
                               out_specs=P(AXIS_GLOBAL))
                )

            prog = self._program(key, build)
            out = prog(self._to_global(stacked))
            post = lambda a: self._from_global_sharded(a, was_list, was_unstacked)
            return self._new_handle(out, name, post)
        except Exception as e:  # surface as HorovodInternalError at sync time
            self._release_name(name)
            if isinstance(e, DuplicateTensorNameError):
                raise
            return self._new_handle(None, name, None, error=e)

    def grouped_allreduce_async(self, tensors: List, name: Optional[str] = None,
                                op: int = _xla.ReduceOp.SUM,
                                prescale_factor: float = 1.0,
                                postscale_factor: float = 1.0) -> int:
        """Fused allreduce of multiple named tensors in one compiled program —
        the eager face of tensor fusion (reference ``FuseResponses``,
        ``controller.cc:640-761``)."""
        name = name or self._auto_name("grouped_allreduce")
        norm = [self._normalize(t) for t in tensors]
        self._register_name(name)
        stacked = [n[0] for n in norm]
        key = ("grouped_allreduce",
               tuple((s.shape[1:], str(s.dtype)) for s in stacked), op,
               prescale_factor, postscale_factor)
        mesh = self._mesh

        def build():
            def fn(*xs):
                ys = _xla.grouped_allreduce(
                    [x[0] for x in xs], axis_name=AXIS_GLOBAL, op=op,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                )
                return tuple(y[None] for y in ys)

            return jax.jit(
                _shard_map(fn, mesh,
                           in_specs=tuple(P(AXIS_GLOBAL) for _ in stacked),
                           out_specs=tuple(P(AXIS_GLOBAL) for _ in stacked))
            )

        prog = self._program(key, build)
        outs = prog(*[self._to_global(s) for s in stacked])

        def post(arrs):
            return [
                self._from_global_sharded(a, wl, wu)
                for a, (_, wl, wu) in zip(arrs, norm)
            ]

        return self._new_handle(outs, name, post)

    def allgather_async(self, tensor, name: Optional[str] = None) -> int:
        name = name or self._auto_name("allgather")
        stacked, _, _ = self._normalize(tensor)
        self._register_name(name)
        key = ("allgather", stacked.shape[1:], str(stacked.dtype))
        mesh = self._mesh

        def build():
            def fn(x):
                return _xla.allgather(x[0], axis_name=AXIS_GLOBAL)

            # Output is identical on every chip -> replicate.
            return jax.jit(
                _shard_map(fn, mesh, in_specs=P(AXIS_GLOBAL), out_specs=P())
            )

        prog = self._program(key, build)
        out = prog(self._to_global(stacked))
        return self._new_handle(out, name, lambda a: a)

    def broadcast_async(self, tensor, root_rank: int,
                        name: Optional[str] = None) -> int:
        name = name or self._auto_name("broadcast")
        stacked, was_list, was_unstacked = self._normalize(tensor)
        self._register_name(name)
        key = ("broadcast", stacked.shape[1:], str(stacked.dtype), root_rank)
        mesh = self._mesh

        def build():
            def fn(x):
                return _xla.broadcast(x[0], root_rank, axis_name=AXIS_GLOBAL)[None]

            return jax.jit(
                _shard_map(fn, mesh, in_specs=P(AXIS_GLOBAL),
                           out_specs=P(AXIS_GLOBAL))
            )

        prog = self._program(key, build)
        out = prog(self._to_global(stacked))
        post = lambda a: self._from_global_sharded(a, was_list, was_unstacked)
        return self._new_handle(out, name, post)

    def reducescatter_async(self, tensor, name: Optional[str] = None,
                            op: int = _xla.ReduceOp.SUM) -> int:
        name = name or self._auto_name("reducescatter")
        stacked, was_list, was_unstacked = self._normalize(tensor)
        if stacked.shape[1] % self._state.size != 0:
            raise ValueError(
                "reducescatter requires dim 0 divisible by size "
                f"({stacked.shape[1]} % {self._state.size})"
            )
        self._register_name(name)
        key = ("reducescatter", stacked.shape[1:], str(stacked.dtype), op)
        mesh = self._mesh

        def build():
            def fn(x):
                return _xla.reducescatter(x[0], axis_name=AXIS_GLOBAL, op=op)[None]

            return jax.jit(
                _shard_map(fn, mesh, in_specs=P(AXIS_GLOBAL),
                           out_specs=P(AXIS_GLOBAL))
            )

        prog = self._program(key, build)
        out = prog(self._to_global(stacked))
        post = lambda a: self._from_global_sharded(a, was_list, was_unstacked)
        return self._new_handle(out, name, post)

    def alltoall_async(self, tensor, name: Optional[str] = None) -> int:
        name = name or self._auto_name("alltoall")
        stacked, was_list, was_unstacked = self._normalize(tensor)
        if stacked.shape[1] % self._state.size != 0:
            raise ValueError("alltoall requires dim 0 divisible by size")
        self._register_name(name)
        key = ("alltoall", stacked.shape[1:], str(stacked.dtype))
        mesh = self._mesh

        def build():
            def fn(x):
                return _xla.alltoall(x[0], axis_name=AXIS_GLOBAL)[None]

            return jax.jit(
                _shard_map(fn, mesh, in_specs=P(AXIS_GLOBAL),
                           out_specs=P(AXIS_GLOBAL))
            )

        prog = self._program(key, build)
        out = prog(self._to_global(stacked))
        post = lambda a: self._from_global_sharded(a, was_list, was_unstacked)
        return self._new_handle(out, name, post)

    def barrier(self):
        key = ("barrier",)
        mesh = self._mesh

        def build():
            def fn():
                return _xla.barrier(axis_name=AXIS_GLOBAL)[None]

            return jax.jit(_shard_map(fn, mesh, in_specs=(),
                                      out_specs=P(AXIS_GLOBAL)))

        prog = self._program(key, build)
        jax.block_until_ready(prog())

    # -- handle management (parity: HandleManager + poll/synchronize) --------

    def poll(self, handle: int) -> bool:
        h = self._handles.get(handle)
        if h is None:
            raise ValueError(f"unknown handle {handle}")
        if h.error is not None:
            return True
        try:
            leaves = jax.tree_util.tree_leaves(h.result)
            return all(leaf.is_ready() for leaf in leaves)
        except AttributeError:
            return True

    def synchronize(self, handle: int):
        with self._lock:
            h = self._handles.pop(handle, None)
        if h is None:
            raise ValueError(f"unknown or already-synchronized handle {handle}")
        self._release_name(h.name)
        if h.error is not None:
            raise HorovodInternalError(str(h.error)) from h.error
        try:
            result = jax.block_until_ready(h.result)
        except Exception as e:
            raise HorovodInternalError(str(e)) from e
        return h.postprocess(result) if h.postprocess else result


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0
