"""Eager collective engine: Horovod's dynamic-enqueue API on top of XLA.

Two cooperating planes (SURVEY §7 design stance):

- **Control plane (native, C++)**: ``libhvdtpu.so`` owns the background
  cycle thread, tensor queue, controller negotiation (local or TCP star
  across processes), tensor fusion planning, response cache, and stall
  inspection (``horovod_tpu/csrc/hvd``) — the reference's
  BackgroundThreadLoop/Controller machinery (operations.cc:338,
  controller.cc:62) rebuilt natively.
- **Execution plane (XLA)**: fused responses come back to Python through a
  registered callback; a dedicated executor thread launches one compiled
  ``shard_map`` program per response signature, cached forever — the analog
  of lazy NCCL communicator/plan init (nccl_operations.cc:60-93), with
  compile-cache misses as the one-time "INIT" stall.

Handles are futures resolved by the native handle table
(``hvd_wait``/``hvd_test``, the HandleManager role,
torch/handle_manager.{h,cc}). If the native library is unavailable
(``HOROVOD_NATIVE=0`` or no compiler), the engine degrades to direct
execution with identical semantics minus cycle batching.

Input convention (TPU-first): a single process drives ``local_size`` chips,
so eager calls carry a leading per-participant axis of length
``local_size`` (or a list of that length). When ``local_size == 1`` the
plain unstacked tensor is accepted, which makes one-chip-per-process
scripts read exactly like reference Horovod scripts. A replicated
(unstacked) input on a multi-chip world is treated as "same tensor on every
chip".
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..common import config as _hvd_config
from ..common import faults as _faults
from ..common import logging as _log
from ..common import native as _native
from ..common.exceptions import DuplicateTensorNameError, HorovodInternalError
from ..common.state import AXIS_CROSS, AXIS_GLOBAL, AXIS_LOCAL
from . import xla as _xla

_OP_TO_NATIVE = {
    "allreduce": _native.OP_ALLREDUCE,
    "allgather": _native.OP_ALLGATHER,
    "broadcast": _native.OP_BROADCAST,
    "reducescatter": _native.OP_REDUCESCATTER,
    "alltoall": _native.OP_ALLTOALL,
}

_DTYPE_FROM_CODE = {v: k for k, v in _native.DTYPE_CODES.items()}
_KIND_FROM_OP = {v: k for k, v in _OP_TO_NATIVE.items()}


def _shard_map(fn, mesh, in_specs, out_specs):
    # check_vma=False: collective outputs (e.g. all_gather) are replicated
    # by construction, which the static VMA checker cannot always infer.
    from ..common.compat import shard_map

    return shard_map(fn, mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)


class _Pending:
    """A tensor submitted to the native queue, awaiting execution."""

    __slots__ = ("stacked", "was_list", "was_unstacked", "was_device",
                 "kind", "op", "prescale", "postscale", "root", "result",
                 "error")

    def __init__(self, stacked, was_list, was_unstacked, kind, op=None,
                 prescale=1.0, postscale=1.0, root=-1, was_device=False):
        self.stacked = stacked
        self.was_list = was_list
        self.was_unstacked = was_unstacked
        self.was_device = was_device
        self.kind = kind
        self.op = op
        self.prescale = prescale
        self.postscale = postscale
        self.root = root
        self.result = None
        self.error = None


class EagerEngine:
    """Per-process engine: native control plane + XLA execution plane."""

    def __init__(self, state):
        self._state = state
        self._mesh = state.mesh
        self._lock = threading.Lock()
        self._program_cache: Dict[Tuple, Any] = {}
        self._name_counter = 0
        self._pending: Dict[str, _Pending] = {}
        self._handle_names: Dict[int, str] = {}
        # Direct-mode handle table. Direct handles are NEGATIVE so they can
        # never collide with native handles (which count up from 0) — the
        # two tables coexist when grouped ops run directly in native mode.
        self._direct_handles: Dict[int, Tuple[Any, Any, str]] = {}
        self._next_direct = -1

        self._core = _native.NativeCore()
        self._native = False
        self._joined = False
        if self._core.available:
            self._exec_q: "queue.SimpleQueue" = queue.SimpleQueue()
            cfg = state.config
            coordinator_addr = _hvd_config.controller_addr()
            my_host = _hvd_config.hostname("127.0.0.1")
            ok = self._core.init(
                rank=state.process_index, size=state.process_count,
                local_rank=0, local_size=state.local_size,
                cross_rank=state.cross_rank, cross_size=state.cross_size,
                coordinator_addr=coordinator_addr,
                coordinator_port=_hvd_config.native_controller_port(),
                my_host=my_host,
                cycle_time_ms=cfg.cycle_time_ms,
                fusion_threshold=cfg.fusion_threshold_bytes,
                cache_capacity=cfg.cache_capacity,
                stall_warning_sec=cfg.stall_warning_seconds,
                stall_shutdown_sec=cfg.stall_shutdown_seconds,
                stall_check_enabled=not cfg.stall_check_disable,
                exec_callback=self._on_responses,
                heartbeat_ms=_hvd_config.heartbeat_ms(),
                liveness_timeout_ms=_hvd_config.liveness_timeout_ms())
            if ok:
                self._native = True
                self._executor = threading.Thread(
                    target=self._executor_loop, daemon=True,
                    name="hvd-xla-executor")
                self._executor.start()
            else:
                _log.warning("native core init failed; using direct mode")

    # -- lifecycle -----------------------------------------------------------

    @property
    def native_core(self):
        """The shared NativeCore when the native control plane is live
        (autotuner hook), else None."""
        return self._core if self._native else None

    def _record_autotune(self, stacks) -> None:
        tuner = self._state.autotuner
        if tuner is None or not tuner.active:
            return
        nbytes = sum(int(np.prod(s.shape)) * s.dtype.itemsize
                     for s in stacks)
        tuner.update(nbytes)

    def shutdown(self):
        if self._native:
            self._core.shutdown()
            self._exec_q.put(None)
            self._executor.join(timeout=10.0)
            self._native = False
        with self._lock:
            self._pending.clear()
            self._handle_names.clear()
            self._direct_handles.clear()
            self._program_cache.clear()

    # -- native callback + executor ------------------------------------------

    def _on_responses(self, responses, response_id):
        """Called on the native background thread; stay quick."""
        self._exec_q.put((responses, response_id))

    def _executor_loop(self):
        while True:
            item = self._exec_q.get()
            if item is None:
                return
            responses, response_id = item
            try:
                for resp in responses:
                    self._execute_response(resp)
                self._core.response_done(response_id, True)
            # hvdlint: ignore[exception-discipline] -- not swallowed: the
            # error lands in every pending handle (raised at wait) AND in
            # response_done(ok=False), the collective error channel
            except Exception as e:
                _log.error(f"XLA executor failure: {e}")
                for resp in responses:
                    for name in resp.names:
                        p = self._pending.get(name)
                        if p is not None:
                            p.error = e
                self._core.response_done(response_id, False, str(e))

    def _execute_response(self, resp: "_native.NativeResponse"):
        # Chaos seam for the XLA execution plane (docs/fault-injection.md):
        # a fault here surfaces exactly like a real executor failure —
        # response_done(False) and every pending entry errors. Its own
        # point name (not "ring.exec"): this runs on the engine's
        # executor thread, and sharing a hit counter with HostWorld.wait
        # would make step= targeting depend on thread interleaving when
        # both planes are live in one process.
        _faults.point("xla.exec", rank=self._state.process_index)
        timeline = self._state.timeline
        if timeline and self._native:
            # Per-rank negotiation ticks recorded by the coordinator
            # (reference NegotiateRankReady, controller.cc:797-809).
            for rank, mono_ns, tname in self._core.drain_negotiation():
                timeline.rank_ready(tname, rank, mono_ns)
        names = resp.names
        found = {n: self._pending[n] for n in names if n in self._pending}
        entries = list(found.values())
        if not entries and not self._joined:
            return
        kind = _KIND_FROM_OP.get(resp.op)
        if kind is None:
            return
        if timeline:
            for n in found:
                timeline.end_activity(n, f"NEGOTIATE_{kind.upper()}")
                timeline.start_activity(n, f"XLA_{kind.upper()}")
        # Autotuned hierarchical dispatch, frame-exact across ranks: the
        # flags stamped into this response frame supersede the env config
        # (None = untuned).
        hf = getattr(resp, "hier_flags", -1)
        hier_ar = None if hf < 0 else bool(hf & 1)
        hier_ag = None if hf < 0 else bool(hf & 2)
        if kind == "allreduce":
            # Build stacks in the response's canonical order. A joined
            # process may hold entries for only some (or none) of the fused
            # tensors; zero stacks stand in for the rest so every process
            # compiles and runs the same SPMD program (reference
            # tensor_queue.cc:88-113 AllocateZeros join path).
            dtype = _DTYPE_FROM_CODE.get(resp.dtype, "float32")
            L = self._state.local_size
            stacks = [
                found[n].stacked if n in found
                else jnp.zeros((L,) + tuple(resp.shapes[i]), dtype=dtype)
                for i, n in enumerate(names)
            ]
            results = self._exec_grouped_allreduce(
                stacks, resp.reduce_op, resp.prescale, resp.postscale,
                hier_override=hier_ar)
            for n, r in zip(names, results):
                p = found.get(n)
                if p is not None:
                    p.result = self._from_global_sharded(
                        r, p.was_list, p.was_unstacked, p.was_device)
        elif kind == "allgather":
            L = self._state.local_size
            size = self._state.size
            for i, n in enumerate(names):
                p = found.get(n)
                if p is None:
                    continue
                fd = (resp.first_dims[i]
                      if i < len(resp.first_dims) else ())
                if fd and len(set(fd)) > 1:
                    # Ragged across chips/processes: every process pads
                    # its stack to the global max (so all compile the
                    # same program), gathers, then slices per the
                    # response's dim table (the NCCL unequal-shape
                    # fallback's pad+slice, nccl_operations.cc:402-523).
                    # fd is rank-major per CHIP when every request
                    # carried chip_dims (XLA plane, the multi-process
                    # path does); a host-plane rank contributes exactly
                    # one entry, so per-chip and per-rank coincide there.
                    max0 = max(fd)
                    pad = [(0, 0), (0, max0 - p.stacked.shape[1])] + \
                        [(0, 0)] * (p.stacked.ndim - 2)
                    out = np.asarray(
                        self._exec_allgather(jnp.pad(p.stacked, pad),
                                             hier_override=hier_ag))
                    views = out.reshape((size, max0) + out.shape[1:])
                    idx = (lambda c: c) if len(fd) == size \
                        else (lambda c: c // L)
                    p.result = np.concatenate(
                        [views[c, : fd[idx(c)]] for c in range(size)],
                        axis=0)
                elif p.was_device:
                    p.result = self._exec_allgather(
                        p.stacked, hier_override=hier_ag)
                else:
                    p.result = np.asarray(self._exec_allgather(
                        p.stacked, hier_override=hier_ag))
        elif kind == "broadcast":
            for p in entries:
                out = self._exec_broadcast(p.stacked, p.root)
                p.result = self._from_global_sharded(
                    out, p.was_list, p.was_unstacked, p.was_device)
        elif kind == "reducescatter":
            for p in entries:
                out = self._exec_reducescatter(p.stacked, p.op)
                p.result = self._from_global_sharded(
                    out, p.was_list, p.was_unstacked, p.was_device)
        elif kind == "alltoall":
            for p in entries:
                out = self._exec_alltoall(p.stacked)
                p.result = self._from_global_sharded(
                    out, p.was_list, p.was_unstacked, p.was_device)
        else:
            raise ValueError(f"unknown response kind {kind}")
        if timeline:
            for n in found:
                timeline.end_activity(n, f"XLA_{kind.upper()}")
        self._record_autotune([p.stacked for p in entries])

    # -- helpers -------------------------------------------------------------

    def _auto_name(self, prefix: str) -> str:
        with self._lock:
            self._name_counter += 1
            return f"{prefix}.noname.{self._name_counter}"

    def _stage_same_device(self, ts, device_inputs: bool):
        """Chained collectives hand back per-chip views committed to
        different devices; stacking those is illegal in jax, so stage
        them on one device first (a device-to-device move, no host hop).
        No-op for host inputs or single-device lists."""
        if device_inputs and \
                len({next(iter(t.devices())) for t in ts}) > 1:
            target = self._state.local_devices[0]
            ts = [jax.device_put(t, target) for t in ts]
        return ts

    def _normalize(self, tensor) -> Tuple[jnp.ndarray, bool, bool, bool]:
        """Returns (stacked [local_size, ...] array, was_list,
        was_unstacked, was_device). ``was_device`` marks inputs that were
        already jax Arrays: their results stay device-resident (no host
        round-trip in ``_from_global_sharded``)."""
        L = self._state.local_size
        if isinstance(tensor, (list, tuple)):
            if len(tensor) != L:
                raise ValueError(
                    f"eager collective got a list of {len(tensor)} tensors; "
                    f"expected local_size={L} (one per locally-driven chip)")
            dev = all(isinstance(t, jax.Array) for t in tensor)
            ts = self._stage_same_device([jnp.asarray(t) for t in tensor],
                                         dev)
            return jnp.stack(ts), True, False, dev
        dev = isinstance(tensor, jax.Array)
        t = jnp.asarray(tensor)
        if L == 1:
            return t[None], False, True, dev
        if t.ndim >= 1 and t.shape[0] == L:
            return t, False, False, dev
        # Replicated convenience: same tensor on every local participant.
        return jnp.broadcast_to(t[None], (L,) + t.shape), False, True, dev

    def _to_global(self, stacked, mesh=None, spec=None):
        """Build the global (size, ...) array sharded one-slice-per-chip.

        ``mesh``/``spec`` default to the flat hvd mesh; the hierarchical
        dispatch passes the (cross, local) mesh with dim 0 split over both
        axes (same device order, so the layout is identical on-chip)."""
        sharding = NamedSharding(mesh if mesh is not None else self._mesh,
                                 spec if spec is not None else P(AXIS_GLOBAL))
        if self._state.process_count == 1:
            return jax.device_put(stacked, sharding)
        global_shape = (self._state.size,) + tuple(stacked.shape[1:])
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(stacked), global_shape)

    def _from_global_sharded(self, arr, was_list, was_unstacked,
                             device=False):
        """Extract this process's local slices of a P('hvd')-sharded
        result.

        ``device=True`` (inputs were device-resident jax Arrays) keeps the
        result on-device: per-shard views are returned directly with no
        host round-trip, so chained eager collectives stay at device
        bandwidth. Host inputs (numpy/torch) keep returning numpy — the
        reference API contract (and the concatenate below is the one host
        hop the eager API performs for them)."""
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start)
        if device:
            if was_list:
                return [s.data[0] for s in shards]
            if was_unstacked:
                return shards[0].data[0]
            if len(shards) == 1:
                return shards[0].data
            # Stacked convention with multiple local chips: the per-shard
            # views are committed to different devices, so stage them on
            # one device before concatenating (device-to-device, no host
            # hop) — concatenating committed mixed-device arrays is an
            # error in jax.
            target = self._state.local_devices[0]
            return jnp.concatenate(
                [jax.device_put(s.data, target) for s in shards], axis=0)
        local = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
        if was_list:
            return [local[i] for i in range(local.shape[0])]
        if was_unstacked:
            return local[0]
        return local

    def _program(self, key, builder):
        prog = self._program_cache.get(key)
        if prog is None:
            _log.debug(f"compiling eager collective program {key}")
            timeline = self._state.timeline
            if timeline:
                timeline.start_activity(str(key), "COMPILE")
            prog = builder()
            if timeline:
                timeline.end_activity(str(key), "COMPILE")
            self._program_cache[key] = prog
        return prog

    @staticmethod
    def _dtype_code(stacked) -> int:
        return _native.DTYPE_CODES.get(str(stacked.dtype), 7)

    # -- XLA execution primitives (shared by native executor + direct mode) --

    def _use_hierarchical(self, flag: bool, op=None, override=None) -> bool:
        """HOROVOD_HIERARCHICAL_* dispatch (reference: OperationManager
        priority + ParameterManager::HierarchicalAllreduce gating,
        operations.cc:142-233): the env/CLI flag routes eager traffic to the
        ICI×DCN variants when the (cross, local) mesh exists; the
        autotuner's synced categorical decision (``override``, stamped into
        each response frame) supersedes the static flag so every rank
        dispatches identically. Hierarchical reduction is expressible for
        SUM/AVERAGE (pure routing — same numbers either way) and ADASUM
        (reference AdasumGpu semantics: intra-group sum, Adasum across).
        For ADASUM the autotuner override is deliberately ignored: flat
        vs hierarchical Adasum are different *math*, not different
        routing, so only the user's static flag may pick between them."""
        if op == _xla.ReduceOp.ADASUM:
            return bool(flag) and self._state.hier_mesh is not None
        if override is not None:
            flag = override
        if not flag or self._state.hier_mesh is None:
            return False
        return op is None or op in (_xla.ReduceOp.SUM, _xla.ReduceOp.AVERAGE)

    def _exec_grouped_allreduce(self, stacks: List, op, prescale, postscale,
                                hier_override=None):
        hier = self._use_hierarchical(
            self._state.config.hierarchical_allreduce, op,
            override=hier_override)
        # On-wire compression (common/compression.py): the live "auto"
        # mode — HOROVOD_COMPRESSION, or the autotuner's published pick —
        # compresses the device-plane collective. Error feedback needs
        # per-parameter state the eager API has nowhere to keep, so ef16
        # degrades to its fp16 wire here (the optimizer plane carries
        # the residuals). The mode rides the program-cache key, so a
        # tuner flip recompiles — which is exactly what makes the
        # tuner's compression grid measure real compressed collectives
        # rather than two identical programs.
        from ..common.compression import resolve_compression

        comp = resolve_compression("auto")
        if comp is not None and comp.error_feedback:
            comp = comp.inner
        # Key order contract: the hier flag stays the LAST element
        # (test_autotune's frame-sync proof reads it there).
        key = ("grouped_allreduce",
               tuple((s.shape[1:], str(s.dtype)) for s in stacks), op,
               prescale, postscale,
               comp.name if comp is not None else None, hier)
        mesh = self._state.hier_mesh if hier else self._mesh
        spec = P((AXIS_CROSS, AXIS_LOCAL)) if hier else P(AXIS_GLOBAL)

        def build():
            def fn(*xs):
                if hier:
                    ys = _xla.grouped_hierarchical_allreduce(
                        [x[0] for x in xs], op=op, prescale_factor=prescale,
                        postscale_factor=postscale, compression=comp)
                else:
                    ys = _xla.grouped_allreduce(
                        [x[0] for x in xs], axis_name=AXIS_GLOBAL, op=op,
                        prescale_factor=prescale, postscale_factor=postscale,
                        compression=comp)
                return tuple(y[None] for y in ys)

            return jax.jit(_shard_map(
                fn, mesh, in_specs=tuple(spec for _ in stacks),
                out_specs=tuple(spec for _ in stacks)))

        prog = self._program(key, build)
        outs = prog(*[self._to_global(s, mesh, spec) for s in stacks])
        return list(outs) if isinstance(outs, tuple) else [outs]

    def _exec_allgather(self, stacked, hier_override=None):
        hier = self._use_hierarchical(
            self._state.config.hierarchical_allgather,
            override=hier_override)
        key = ("allgather", stacked.shape[1:], str(stacked.dtype), hier)
        mesh = self._state.hier_mesh if hier else self._mesh
        spec = P((AXIS_CROSS, AXIS_LOCAL)) if hier else P(AXIS_GLOBAL)

        def build():
            def fn(x):
                if hier:
                    return _xla.hierarchical_allgather(x[0])
                return _xla.allgather(x[0], axis_name=AXIS_GLOBAL)

            return jax.jit(_shard_map(fn, mesh, in_specs=spec,
                                      out_specs=P()))

        return self._program(key, build)(
            self._to_global(stacked, mesh, spec))

    def _exec_broadcast(self, stacked, root):
        key = ("broadcast", stacked.shape[1:], str(stacked.dtype), root)
        mesh = self._mesh

        def build():
            def fn(x):
                return _xla.broadcast(x[0], root,
                                      axis_name=AXIS_GLOBAL)[None]

            return jax.jit(_shard_map(fn, mesh, in_specs=P(AXIS_GLOBAL),
                                      out_specs=P(AXIS_GLOBAL)))

        return self._program(key, build)(self._to_global(stacked))

    def _exec_reducescatter(self, stacked, op):
        key = ("reducescatter", stacked.shape[1:], str(stacked.dtype), op)
        mesh = self._mesh

        def build():
            def fn(x):
                return _xla.reducescatter(x[0], axis_name=AXIS_GLOBAL,
                                          op=op)[None]

            return jax.jit(_shard_map(fn, mesh, in_specs=P(AXIS_GLOBAL),
                                      out_specs=P(AXIS_GLOBAL)))

        return self._program(key, build)(self._to_global(stacked))

    def _exec_alltoall(self, stacked):
        key = ("alltoall", stacked.shape[1:], str(stacked.dtype))
        mesh = self._mesh

        def build():
            def fn(x):
                return _xla.alltoall(x[0], axis_name=AXIS_GLOBAL)[None]

            return jax.jit(_shard_map(fn, mesh, in_specs=P(AXIS_GLOBAL),
                                      out_specs=P(AXIS_GLOBAL)))

        return self._program(key, build)(self._to_global(stacked))

    # -- submission ----------------------------------------------------------

    def _submit(self, kind: str, name: Optional[str], stacked, was_list,
                was_unstacked, op=None, prescale=1.0, postscale=1.0,
                root=-1, was_device=False, chip_dims=None) -> int:
        name = name or self._auto_name(kind)
        timeline = self._state.timeline
        if self._native:
            if timeline:
                timeline.start_activity(name, f"NEGOTIATE_{kind.upper()}")
            with self._lock:
                if name in self._pending:
                    raise DuplicateTensorNameError(
                        f"tensor name '{name}' already submitted and not "
                        "yet complete")
                self._pending[name] = _Pending(
                    stacked, was_list, was_unstacked, kind, op, prescale,
                    postscale, root, was_device)
            handle = self._core.enqueue(
                name, _OP_TO_NATIVE[kind], op if op is not None else 1,
                self._dtype_code(stacked), tuple(stacked.shape[1:]),
                root_rank=root, prescale=prescale, postscale=postscale,
                plane=_native.PLANE_XLA, chip_dims=chip_dims)
            if handle < 0:
                # Negative returns are error codes, not handles — they would
                # collide with the direct-handle namespace below.
                with self._lock:
                    self._pending.pop(name, None)
                raise HorovodInternalError(
                    "native enqueue failed (runtime not initialized or "
                    "shutting down)")
            # Duplicate detection also lives in the native queue; surface
            # its synchronous rejection as the parity exception.
            r, reason = self._core.test(handle)
            if r < 0 and "Duplicate tensor name" in reason:
                with self._lock:
                    self._pending.pop(name, None)
                raise DuplicateTensorNameError(reason)
            with self._lock:
                self._handle_names[handle] = name
            return handle
        # direct mode: execute immediately (XLA dispatch is still async).
        # Duplicate-name rejection must precede execution so an erroring
        # caller never participates in a collective.
        self._check_direct_duplicate(name)
        try:
            if kind == "allreduce":
                out = self._exec_grouped_allreduce([stacked], op, prescale,
                                                   postscale)[0]
                post = lambda a: self._from_global_sharded(  # noqa: E731
                    a, was_list, was_unstacked, was_device)
            elif kind == "allgather":
                out = self._exec_allgather(stacked)
                post = (  # noqa: E731
                    (lambda a: a) if was_device else
                    (lambda a: np.asarray(a)))
            elif kind == "broadcast":
                out = self._exec_broadcast(stacked, root)
                post = lambda a: self._from_global_sharded(  # noqa: E731
                    a, was_list, was_unstacked, was_device)
            elif kind == "reducescatter":
                out = self._exec_reducescatter(stacked, op)
                post = lambda a: self._from_global_sharded(  # noqa: E731
                    a, was_list, was_unstacked, was_device)
            elif kind == "alltoall":
                out = self._exec_alltoall(stacked)
                post = lambda a: self._from_global_sharded(  # noqa: E731
                    a, was_list, was_unstacked, was_device)
            else:
                raise ValueError(kind)
            self._record_autotune([stacked])
            err = None
        # hvdlint: ignore[exception-discipline] -- deferred, not
        # swallowed: the handle stores the exception and synchronize()
        # re-raises it on the caller's thread
        except Exception as e:
            out, post, err = None, None, e
        return self._new_direct_handle(out if err is None else err,
                                       post if err is None else None, name)

    def _check_direct_duplicate(self, name: str):
        with self._lock:
            if name in {m[2] for m in self._direct_handles.values()}:
                raise DuplicateTensorNameError(
                    f"tensor name '{name}' already submitted and not yet "
                    "complete")

    def _new_direct_handle(self, out, post, name) -> int:
        with self._lock:
            h = self._next_direct
            self._next_direct -= 1
            self._direct_handles[h] = (out, post, name)
            return h

    # -- public API ----------------------------------------------------------

    def allreduce_async(self, tensor, name: Optional[str] = None,
                        op: int = _xla.ReduceOp.AVERAGE,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0) -> int:
        stacked, was_list, was_unstacked, was_device = \
            self._normalize(tensor)
        if op == _xla.ReduceOp.ADASUM:
            # Hierarchical Adasum only needs a power-of-two CROSS size
            # (the LOCAL leg is a plain reduce-scatter); flat Adasum
            # needs a power-of-two world.
            hier = self._use_hierarchical(
                self._state.config.hierarchical_allreduce, op)
            n = self._state.cross_size if hier else self._state.size
            if not _is_pow2(n):
                _log.warning("Adasum requested with non-power-of-two "
                             "participant count; falling back to Average")
                op = _xla.ReduceOp.AVERAGE
        return self._submit("allreduce", name, stacked, was_list,
                            was_unstacked, op=op, prescale=prescale_factor,
                            postscale=postscale_factor,
                            was_device=was_device)

    def grouped_allreduce_async(self, tensors: List,
                                name: Optional[str] = None,
                                op: int = _xla.ReduceOp.AVERAGE,
                                prescale_factor: float = 1.0,
                                postscale_factor: float = 1.0) -> int:
        """Explicitly-fused allreduce: submitted as one unit so the result
        is one compiled program regardless of cycle timing.

        Deliberately follows the STATIC hierarchical config, not the
        autotuner's synced flags: grouped/direct calls execute outside
        the response-frame protocol that guarantees every rank applies a
        flag flip at the same boundary, and a mid-tune flip here would
        compile divergent SPMD programs across ranks (see
        docs/autotune.md)."""
        name = name or self._auto_name("grouped_allreduce")
        norm = [self._normalize(t) for t in tensors]
        stacks = [n[0] for n in norm]
        self._check_direct_duplicate(name)
        try:
            outs = self._exec_grouped_allreduce(stacks, op, prescale_factor,
                                                postscale_factor)
            err = None
        # hvdlint: ignore[exception-discipline] -- deferred, not
        # swallowed: the handle stores the exception and synchronize()
        # re-raises it on the caller's thread
        except Exception as e:
            outs, err = None, e

        def post(arrs):
            return [self._from_global_sharded(a, wl, wu, dev)
                    for a, (_, wl, wu, dev) in zip(arrs, norm)]

        return self._new_direct_handle(outs if err is None else err,
                                       post if err is None else None, name)

    def allgather_async(self, tensor, name: Optional[str] = None) -> int:
        if isinstance(tensor, (list, tuple)) and \
                len(tensor) == self._state.local_size:
            ts = [jnp.asarray(t) for t in tensor]
            if all(t.ndim > 0 for t in ts) and \
                    len({t.shape[0] for t in ts}) > 1:
                if self._state.process_count > 1:
                    if not self._native:
                        # Direct mode has no negotiated dim table: the
                        # padded stacks would gather with their zero pad
                        # rows silently included.
                        raise ValueError(
                            "ragged allgather with multiple local chips "
                            "per process requires the native runtime "
                            "across processes (chip-dim negotiation); "
                            "build libhvdtpu.so or use equal first "
                            "dimensions")
                    # Ragged across locally-driven chips AND processes:
                    # pad the local chips to the local max, negotiate with
                    # the true per-chip dims riding the request
                    # (chip_dims), and let the response's rank-major
                    # per-chip dim table drive the global pad+slice
                    # (parity: the NCCL unequal-shape fallback,
                    # nccl_operations.cc:402-523).
                    sizes = tuple(t.shape[0] for t in ts)
                    max0 = max(sizes)
                    ts = self._stage_same_device(
                        ts, all(isinstance(t, jax.Array) for t in tensor))
                    padded = jnp.stack([
                        jnp.pad(t, [(0, max0 - t.shape[0])] +
                                [(0, 0)] * (t.ndim - 1)) for t in ts])
                    return self._submit("allgather", name, padded, True,
                                        False, chip_dims=sizes)
                # Single process: per-chip sizes are all local knowledge,
                # so pad+gather+slice runs directly (parity:
                # MPI_Allgatherv, mpi_operations.cc:140-175).
                return self._ragged_local_allgather(ts, name)
        stacked, wl, wu, dev = self._normalize(tensor)
        chip_dims = None
        if self._state.process_count > 1 and stacked.ndim > 1:
            # Per-chip dims always ride multi-process allgathers so the
            # response's dim table is per-chip regardless of which
            # processes turn out to be ragged.
            chip_dims = (stacked.shape[1],) * self._state.local_size
        return self._submit("allgather", name, stacked, wl, wu,
                            was_device=dev, chip_dims=chip_dims)

    def _ragged_local_allgather(self, ts: List, name: Optional[str]) -> int:
        name = name or self._auto_name("allgather")
        self._check_direct_duplicate(name)
        sizes = [t.shape[0] for t in ts]
        max0 = max(sizes)
        padded = jnp.stack([
            jnp.pad(t, [(0, max0 - t.shape[0])] + [(0, 0)] * (t.ndim - 1))
            for t in ts])
        try:
            out = self._exec_allgather(padded)
            err = None
        # hvdlint: ignore[exception-discipline] -- deferred, not
        # swallowed: the handle stores the exception and synchronize()
        # re-raises it on the caller's thread
        except Exception as e:
            out, err = None, e

        def post(a):
            a = np.asarray(a)
            views = a.reshape((len(ts), max0) + a.shape[1:])
            return np.concatenate(
                [views[i, : sizes[i]] for i in range(len(ts))], axis=0)

        return self._new_direct_handle(out if err is None else err,
                                       post if err is None else None, name)

    def broadcast_async(self, tensor, root_rank: int,
                        name: Optional[str] = None) -> int:
        stacked, wl, wu, dev = self._normalize(tensor)
        return self._submit("broadcast", name, stacked, wl, wu,
                            root=root_rank, was_device=dev)

    def reducescatter_async(self, tensor, name: Optional[str] = None,
                            op: int = _xla.ReduceOp.SUM) -> int:
        stacked, wl, wu, dev = self._normalize(tensor)
        if stacked.shape[1] % self._state.size != 0:
            raise ValueError(
                "reducescatter requires dim 0 divisible by size "
                f"({stacked.shape[1]} % {self._state.size})")
        return self._submit("reducescatter", name, stacked, wl, wu, op=op,
                            was_device=dev)

    def alltoall_async(self, tensor, name: Optional[str] = None) -> int:
        stacked, wl, wu, dev = self._normalize(tensor)
        if stacked.shape[1] % self._state.size != 0:
            raise ValueError("alltoall requires dim 0 divisible by size")
        return self._submit("alltoall", name, stacked, wl, wu,
                            was_device=dev)

    def join(self) -> int:
        """Graceful departure (parity: hvd.join(), operations.cc:937-961).

        Blocks until every process has joined; while waiting, this process
        contributes zeros to the other processes' reductions (host plane in
        C++, XLA plane via the zero-fill branch of ``_execute_response``).
        Returns the global rank of the last participant to join.
        """
        st = self._state
        if not self._native or st.process_count == 1:
            # Single controller (or direct mode): every rank this process
            # drives joins at once, so join degenerates to a barrier.
            self.barrier()
            return st.size - 1
        self._joined = True
        try:
            handle = self._core.join()
            if handle < 0:
                raise HorovodInternalError("join enqueue failed")
            r, reason = self._core.wait(handle)
            if r < 0:
                raise HorovodInternalError(reason)
        finally:
            self._joined = False
        # last_joined is a process index; report the last global rank that
        # process drives (== the process rank when local_size == 1).
        p = self._core.last_joined()
        return (p + 1) * st.local_size - 1

    def barrier(self):
        if self._native and self._state.process_count > 1:
            # Negotiated control-plane barrier: completes among active
            # ranks even while another process is blocked in join() (a
            # direct SPMD program would wait forever for the joined
            # process to launch it).
            z = np.zeros(1, np.uint8)
            h = self._core.enqueue(
                self._auto_name("eager.barrier"), _native.OP_BARRIER, 1, 0,
                tuple(z.shape), data_ptr=z.ctypes.data,
                output_ptr=z.ctypes.data, plane=_native.PLANE_HOST)
            if h < 0:
                raise HorovodInternalError("barrier enqueue failed")
            r, reason = self._core.wait(h)
            if r < 0:
                raise HorovodInternalError(reason)
            return
        key = ("barrier",)
        mesh = self._mesh

        def build():
            def fn():
                return _xla.barrier(axis_name=AXIS_GLOBAL)[None]

            return jax.jit(_shard_map(fn, mesh, in_specs=(),
                                      out_specs=P(AXIS_GLOBAL)))

        prog = self._program(key, build)
        jax.block_until_ready(prog())

    # -- handle management (parity: HandleManager + poll/synchronize) --------

    def poll(self, handle: int) -> bool:
        if self._native and handle in self._handle_names:
            r, _ = self._core.test(handle)
            return r != 0
        with self._lock:
            entry = self._direct_handles.get(handle)
        if entry is None:
            raise ValueError(f"unknown handle {handle}")
        out = entry[0]
        if isinstance(out, Exception):
            return True
        try:
            leaves = jax.tree_util.tree_leaves(out)
            return all(leaf.is_ready() for leaf in leaves)
        except AttributeError:
            return True

    def synchronize(self, handle: int):
        if self._native and handle in self._handle_names:
            r, reason = self._core.wait(handle)
            with self._lock:
                name = self._handle_names.pop(handle)
                pending = self._pending.pop(name, None)
            if r < 0:
                # Coordinator-error responses resolve entirely in C++ and
                # never reach _execute_response, so close the open
                # negotiation span here.
                timeline = self._state.timeline
                if timeline and pending is not None:
                    timeline.end_activity(
                        name, f"NEGOTIATE_{pending.kind.upper()}")
                raise HorovodInternalError(reason)
            if pending is None or (pending.result is None
                                   and pending.error is None):
                raise HorovodInternalError(
                    f"no result recorded for '{name}'")
            if pending.error is not None:
                raise HorovodInternalError(str(pending.error)) \
                    from pending.error
            return pending.result
        with self._lock:
            entry = self._direct_handles.pop(handle, None)
        if entry is None:
            raise ValueError(
                f"unknown or already-synchronized handle {handle}")
        out, post, _name = entry
        if isinstance(out, Exception):
            raise HorovodInternalError(str(out)) from out
        try:
            result = jax.block_until_ready(out)
        except Exception as e:
            raise HorovodInternalError(str(e)) from e
        return post(result) if post else result


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0
