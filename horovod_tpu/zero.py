"""ZeRO-1–style sharded optimizer for the JAX-native API.

Beyond the reference's capability set (its DistributedOptimizer keeps the
full optimizer state on every worker): here each device holds only its
1/d slice of the optimizer state and of the fp32 master weights, cutting
optimizer memory by the mesh-axis size — the partitioning of
Rajbhandari et al.'s ZeRO stage 1, expressed TPU-natively. Per step,
inside one compiled program:

    grads  --psum_scatter-->  grad shard        (ICI reduce-scatter)
    shard update (optax on the persistent fp32 master shard)
    masters --all_gather----> full params       (ICI all-gather)

For fp32 models the reduce-scatter + all-gather pair moves exactly the
same bytes as the allreduce it replaces (an allreduce IS a
reduce-scatter + all-gather), so the memory saving is
communication-neutral. For reduced-precision models (uniform bf16/fp16
params) the gather leg runs at the model dtype — master shards are cast
before the all-gather — so the gathered flat buffer is model-sized, and
only the scatter leg pays fp32 width (for reduction precision): total
wire traffic is 1.5x a bf16 allreduce, and the transient flat buffers
are one fp32 gradient flat (pre-scatter) and one model-dtype param flat
(post-gather). The fp32 master shard itself stays 1/d per device across
steps, so updates still accumulate at fp32 precision.

Works with any *elementwise* optax transformation (sgd, momentum, adam,
adamw, rmsprop, ...): the update runs on a flat concatenated shard, which
is elementwise-equivalent to running on the structured pytree. Transforms
that need global structure (global-norm clipping, layerwise LARS) must
stay outside or be re-derived with a psum — documented limitation.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .common.state import AXIS_GLOBAL


class ZeroTrainState(NamedTuple):
    params: Any       # full pytree, replicated (model dtype)
    pshard: Any       # this device's flat fp32 master-weight shard
    opt_shard: Any    # optimizer state over the master shard
    gaccum: Any       # accumulated gradient shard (None unless accumulating)
    batch_stats: Any
    step: Any


def _flat_spec(params):
    """Static flattening plan: (leaves treedef, shapes, sizes, total)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    return treedef, shapes, dtypes, sizes, int(sum(sizes))


def _shard_len(total: int, d: int) -> int:
    """One source of truth for the padding arithmetic: flat length padded
    up to a multiple of d, divided across the d shards."""
    return ((total + d - 1) // d * d) // d


def _opt_state_specs(optimizer, shard_len, axis_name):
    """Per-leaf partition specs for the optimizer state over a flat
    shard: vector leaves (mu/nu/momentum, one element per parameter
    element) shard along the axis; scalar leaves (step counts) are
    replicated — identical on every device by construction."""
    shapes = jax.eval_shape(
        optimizer.init, jnp.zeros((shard_len,), jnp.float32))
    return jax.tree_util.tree_map(
        lambda s: P(axis_name) if len(s.shape) >= 1 else P(), shapes)


def _flatten_f32(params, total, padded):
    leaves = jax.tree_util.tree_leaves(params)
    flat = jnp.concatenate(
        [l.astype(jnp.float32).reshape(-1) for l in leaves])
    return jnp.pad(flat, (0, padded - total))


def _unflatten(flat, treedef, shapes, dtypes, sizes, total):
    parts = jnp.split(flat[:total], np.cumsum(sizes)[:-1])
    leaves = [p.reshape(s).astype(dt)
              for p, s, dt in zip(parts, shapes, dtypes)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def init_zero_train_state(model, optimizer: optax.GradientTransformation,
                          rng, sample_input, mesh,
                          axis_name: str = AXIS_GLOBAL,
                          accumulate_steps: int = 1) -> ZeroTrainState:
    """Initialize params (replicated) + the sharded fp32 master weights
    and optimizer state.

    Masters and optimizer state are created per-device on that device's
    flat shard inside a shard_mapped init, so they are born sharded — no
    full fp32 copy ever exists on any one device. With
    ``accumulate_steps > 1`` a sharded gradient accumulator is added (the
    ``backward_passes_per_step`` role, still 1/d memory)."""
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats")

    d = int(mesh.shape[axis_name])
    _, _, _, _, total = _flat_spec(params)
    shard_len = _shard_len(total, d)
    padded = shard_len * d

    def init_shard(p):
        flat = _flatten_f32(p, total, padded)
        idx = lax.axis_index(axis_name)
        my = lax.dynamic_slice(flat, (idx * shard_len,), (shard_len,))
        return my, optimizer.init(my)

    sharded_init = jax.jit(jax.shard_map(
        init_shard, mesh=mesh, in_specs=(P(),),
        out_specs=(P(axis_name),
                   _opt_state_specs(optimizer, shard_len, axis_name)),
        check_vma=False))

    replicated = NamedSharding(mesh, P())
    params = jax.device_put(params, replicated)
    if batch_stats is not None:
        batch_stats = jax.device_put(batch_stats, replicated)
    pshard, opt_shard = sharded_init(params)
    gaccum = None
    if accumulate_steps > 1:
        # Born sharded, like pshard/opt_shard: materializing the full
        # padded fp32 buffer on one device first would break the "no full
        # fp32 copy on any one device" invariant exactly when it matters.
        gaccum = jax.jit(
            lambda: jnp.zeros((padded,), jnp.float32),
            out_shardings=NamedSharding(mesh, P(axis_name)))()
    return ZeroTrainState(params, pshard, opt_shard, gaccum, batch_stats,
                          jax.device_put(jnp.zeros((), jnp.int32),
                                         replicated))


def make_zero_train_step(model, optimizer: optax.GradientTransformation,
                         mesh, axis_name: str = AXIS_GLOBAL,
                         donate: bool = True, accumulate_steps: int = 1):
    """Build the jitted SPMD train step with ZeRO-1 optimizer sharding.

    Drop-in alternative to ``training.make_train_step`` (same call
    signature on the state it builds); the loss/batch-stats semantics
    match it exactly.

    ``accumulate_steps=k`` plays the reference's
    ``backward_passes_per_step`` role: k micro-batches accumulate before
    one optimizer update. The accumulator is the already-scattered
    gradient shard, so accumulation memory stays 1/d (each micro-step
    pays one reduce-scatter — half an allreduce's bytes — and the
    all-gather only runs on update steps, when params actually change).
    Micro-batch gradients are AVERAGED (matching this framework's
    DistributedOptimizer accumulation), not summed as the reference's
    hook accumulation effectively does — multiply the learning rate by k
    when porting a reference config that relied on summed accumulation.
    Requires a state built with the same ``accumulate_steps``."""
    from .training import cross_entropy_loss

    d = int(mesh.shape[axis_name])
    k = accumulate_steps

    def step_fn(state: ZeroTrainState, images, labels):
        treedef, shapes, dtypes, sizes, total = _flat_spec(state.params)
        padded = _shard_len(total, d) * d
        # Uniform-dtype models gather at the model dtype (halving gather
        # bytes and the transient flat buffer for bf16); mixed-dtype trees
        # gather at fp32 and let _unflatten cast per leaf.
        gather_dtype = (dtypes[0] if all(dt == dtypes[0] for dt in dtypes)
                        else jnp.float32)

        def loss_fn(p):
            variables = {"params": p}
            if state.batch_stats is not None:
                variables["batch_stats"] = state.batch_stats
                logits, updated = model.apply(
                    variables, images, train=True, mutable=["batch_stats"])
                return (cross_entropy_loss(logits, labels),
                        updated["batch_stats"])
            logits = model.apply(variables, images, train=True)
            return cross_entropy_loss(logits, labels), None

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)

        # Mean-reduce and scatter in one collective: each device leaves
        # with its shard of the global-mean gradient.
        flat_g = _flatten_f32(grads, total, padded)
        gshard = lax.psum_scatter(flat_g, axis_name, tiled=True) / d

        def apply_update(gshard, opt_shard, pshard):
            updates, new_opt = optimizer.update(gshard, opt_shard, pshard)
            new_pshard = optax.apply_updates(pshard, updates)
            new_flat = lax.all_gather(new_pshard.astype(gather_dtype),
                                      axis_name, tiled=True)
            return (_unflatten(new_flat, treedef, shapes, dtypes, sizes,
                               total), new_pshard, new_opt)

        step = state.step + 1
        if k <= 1:
            new_params, new_pshard, new_opt = apply_update(
                gshard, state.opt_shard, state.pshard)
            new_gaccum = state.gaccum
        else:
            acc = state.gaccum + gshard
            do_update = (step % k) == 0

            def update_branch(operand):
                acc, opt_shard, pshard = operand
                p, ps, op_ = apply_update(acc / k, opt_shard, pshard)
                return p, ps, op_, jnp.zeros_like(acc)

            def skip_branch(operand):
                acc, opt_shard, pshard = operand
                return state.params, pshard, opt_shard, acc

            new_params, new_pshard, new_opt, new_gaccum = lax.cond(
                do_update, update_branch, skip_branch,
                (acc, state.opt_shard, state.pshard))

        if new_stats is not None:
            new_stats = jax.tree_util.tree_map(
                lambda x: lax.pmean(x, axis_name), new_stats)
        loss = lax.pmean(loss, axis_name)
        return ZeroTrainState(new_params, new_pshard, new_opt, new_gaccum,
                              new_stats, step), loss

    cache = {}

    def step(state: ZeroTrainState, images, labels):
        if (state.gaccum is None) != (k <= 1):
            raise ValueError(
                "state/step accumulate_steps mismatch: build the state "
                "with init_zero_train_state(..., accumulate_steps=k) "
                "matching make_zero_train_step's")
        # The optimizer-state specs depend on the shard length, which
        # depends on the parameter count — resolve per parameter-tree
        # structure and cache the compiled step under that key, so a
        # state with a different pytree (e.g. after model surgery) gets
        # its own compilation instead of an opaque shape error from a
        # stale spec.
        treedef, shapes, dtypes, _, total = _flat_spec(state.params)
        # Surgery on params without rebuilding the state leaves master/
        # optimizer shards sized for the OLD tree; catch that here with a
        # descriptive error instead of an opaque shard_map shape failure
        # (round-2 advisor finding).
        expected_padded = _shard_len(total, d) * d
        actual_padded = int(np.prod(state.pshard.shape))
        if actual_padded != expected_padded:
            raise ValueError(
                f"ZeroTrainState shards were built for a different "
                f"parameter tree: params flatten to {total} elements "
                f"(padded {expected_padded}) but pshard holds "
                f"{actual_padded}. After changing the model's parameter "
                f"structure, rebuild the state with "
                f"init_zero_train_state(...) instead of reusing the old "
                f"one.")
        key = (treedef, tuple(shapes), tuple(str(dt) for dt in dtypes),
               state.gaccum is None)
        if key not in cache:
            opt_specs = _opt_state_specs(optimizer, _shard_len(total, d),
                                         axis_name)
            gaccum_spec = P() if state.gaccum is None else P(axis_name)
            state_specs = ZeroTrainState(P(), P(axis_name), opt_specs,
                                         gaccum_spec, P(), P())
            sharded = jax.shard_map(
                step_fn, mesh=mesh,
                in_specs=(state_specs, P(axis_name), P(axis_name)),
                out_specs=(state_specs, P()),
                check_vma=False)
            cache[key] = jax.jit(
                sharded, donate_argnums=(0,) if donate else ())
        return cache[key](state, images, labels)

    return step
