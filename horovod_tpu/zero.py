"""ZeRO-1–style sharded optimizer for the JAX-native API.

Beyond the reference's capability set (its DistributedOptimizer keeps the
full optimizer state on every worker): here each device holds only its
1/d slice of the optimizer state and of the fp32 master weights, cutting
optimizer memory by the mesh-axis size — the partitioning of
Rajbhandari et al.'s ZeRO stage 1, expressed TPU-natively. Per step,
inside one compiled program:

    grads  --psum_scatter-->  grad shard        (ICI reduce-scatter)
    shard update (optax on the persistent fp32 master shard)
    masters --all_gather----> full params       (ICI all-gather)

For fp32 models the reduce-scatter + all-gather pair moves exactly the
same bytes as the allreduce it replaces (an allreduce IS a
reduce-scatter + all-gather), so the memory saving is
communication-neutral. For reduced-precision models (uniform bf16/fp16
params) the gather leg runs at the model dtype — master shards are cast
before the all-gather — so the gathered flat buffer is model-sized, and
only the scatter leg pays fp32 width (for reduction precision): total
wire traffic is 1.5x a bf16 allreduce, and the transient flat buffers
are one fp32 gradient flat (pre-scatter) and one model-dtype param flat
(post-gather). The fp32 master shard itself stays 1/d per device across
steps, so updates still accumulate at fp32 precision.

Works with any *elementwise* optax transformation (sgd, momentum, adam,
adamw, rmsprop, ...): the update runs on a flat concatenated shard, which
is elementwise-equivalent to running on the structured pytree. Transforms
that need global structure (global-norm clipping, layerwise LARS) must
stay outside or be re-derived with a psum — documented limitation.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .common.compat import shard_map as _shard_map
from .common.state import AXIS_GLOBAL


class ZeroTrainState(NamedTuple):
    params: Any       # full pytree, replicated (model dtype)
    pshard: Any       # this device's flat fp32 master-weight shard
    opt_shard: Any    # optimizer state over the master shard
    gaccum: Any       # accumulated gradient shard (None unless accumulating)
    batch_stats: Any
    step: Any
    # Fusion-bucket cap (bytes) the shard layout was built under, as a
    # replicated int32 scalar (-1 = monolithic). THE STATE OWNS THE
    # LAYOUT: make_zero_train_step reads the cap from here, so an
    # "auto"-resolved cap can never drift between init and step (e.g.
    # when the autotuner publishes a new threshold in between) — total
    # padded size alone cannot detect such drift when leaf sizes align
    # with the mesh (zero per-bucket padding).
    bucket_cap: Any = None
    # Error-feedback residuals for the compressed reduce-scatter
    # ("ef16"), sharded like gaccum (1/d per device, fp32, padded flat
    # layout). Each device keeps the quantization error of ITS OWN
    # contribution to ITS OWN output shard and re-injects it there next
    # step — the sharded-residual scheme (full per-rank residuals would
    # cost a persistent fp32 gradient copy per device, forfeiting ZeRO's
    # memory scaling; see docs/compression.md). None when the state was
    # built without error feedback; like bucket_cap, the state owns it —
    # a step resolving a different mode is rejected.
    residual: Any = None


def _shard_len(total: int, d: int) -> int:
    """One source of truth for the padding arithmetic: flat length padded
    up to a multiple of d, divided across the d shards."""
    return ((total + d - 1) // d * d) // d


class _ZeroPlan(NamedTuple):
    """Static flattening plan, generalized over fusion buckets.

    The device shard is the concatenation of per-bucket shards: bucket j
    flattens its leaves (fp32), pads to a multiple of d, reduce-scatters,
    and contributes ``bucket_padded[j] // d`` elements. With no bucket
    cap there is exactly one bucket holding every leaf in parameter
    order — the layout (and therefore every checkpointed shard) is
    bit-identical to the pre-bucketing monolithic flat. With a cap,
    buckets come from ``common/fusion.plan_buckets`` in reverse parameter
    (≈ backward-production) order, so each bucket's reduce-scatter
    depends only on its own gradients and overlaps the rest of backprop.
    States built under different caps have different shard layouts and
    are not interchangeable — rebuild (or restore via the pytree
    checkpoint path) when changing the cap.
    """

    treedef: Any
    shapes: tuple
    dtypes: tuple
    sizes: tuple          # per-leaf element counts
    total: int            # sum(sizes)
    buckets: tuple        # tuple[tuple[int, ...]]: leaf indices per bucket
    bucket_elems: tuple   # unpadded element count per bucket
    bucket_padded: tuple  # padded element count per bucket (multiple of d)
    shard_len: int        # per-device shard length

    @property
    def padded(self) -> int:
        return sum(self.bucket_padded)


def _make_plan(params, d: int, bucket_cap_bytes=None) -> _ZeroPlan:
    from .common.fusion import plan_buckets

    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = tuple(l.shape for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    total = int(sum(sizes))
    if bucket_cap_bytes:
        # The wire format is fp32 regardless of model dtype (reduction
        # precision), so the planner sees fp32 byte sizes and one dtype —
        # buckets close on the byte cap only.
        buckets = tuple(
            b.indices for b in plan_buckets(
                [s * 4 for s in sizes], [jnp.float32] * len(sizes),
                bucket_cap_bytes))
    else:
        buckets = (tuple(range(len(sizes))),) if sizes else ()
    bucket_elems = tuple(sum(sizes[i] for i in idxs) for idxs in buckets)
    bucket_padded = tuple(_shard_len(n, d) * d for n in bucket_elems)
    shard_len = sum(p // d for p in bucket_padded)
    return _ZeroPlan(treedef, shapes, dtypes, sizes, total, buckets,
                     bucket_elems, bucket_padded, shard_len)


def _bucket_flat_f32(leaves, plan: _ZeroPlan, j: int):
    """Bucket j's leaves as one padded fp32 flat (the scatter payload)."""
    idxs = plan.buckets[j]
    flat = (jnp.concatenate([leaves[i].astype(jnp.float32).reshape(-1)
                             for i in idxs])
            if len(idxs) > 1
            else leaves[idxs[0]].astype(jnp.float32).reshape(-1))
    pad = plan.bucket_padded[j] - plan.bucket_elems[j]
    return jnp.pad(flat, (0, pad)) if pad else flat


def _unflatten_plan(bucket_flats, plan: _ZeroPlan):
    """Rebuild the parameter pytree from per-bucket gathered flats."""
    leaves = [None] * len(plan.sizes)
    for j, idxs in enumerate(plan.buckets):
        flat = bucket_flats[j]
        off = 0
        for i in idxs:
            n = plan.sizes[i]
            leaves[i] = (flat[off:off + n].reshape(plan.shapes[i])
                         .astype(plan.dtypes[i]))
            off += n
    return jax.tree_util.tree_unflatten(plan.treedef, leaves)


def _opt_state_specs(optimizer, shard_len, axis_name):
    """Per-leaf partition specs for the optimizer state over a flat
    shard: vector leaves (mu/nu/momentum, one element per parameter
    element) shard along the axis; scalar leaves (step counts) are
    replicated — identical on every device by construction."""
    shapes = jax.eval_shape(
        optimizer.init, jnp.zeros((shard_len,), jnp.float32))
    return jax.tree_util.tree_map(
        lambda s: P(axis_name) if len(s.shape) >= 1 else P(), shapes)


def init_zero_train_state(model, optimizer: optax.GradientTransformation,
                          rng, sample_input, mesh,
                          axis_name: str = AXIS_GLOBAL,
                          accumulate_steps: int = 1,
                          bucket_cap_bytes="auto",
                          compression="auto") -> ZeroTrainState:
    """Initialize params (replicated) + the sharded fp32 master weights
    and optimizer state.

    Masters and optimizer state are created per-device on that device's
    flat shard inside a shard_mapped init, so they are born sharded — no
    full fp32 copy ever exists on any one device. With
    ``accumulate_steps > 1`` a sharded gradient accumulator is added (the
    ``backward_passes_per_step`` role, still 1/d memory).

    ``bucket_cap_bytes`` defines the shard layout (see ``_ZeroPlan``)
    and is recorded IN the state (``bucket_cap``); the step built by
    ``make_zero_train_step`` reads it from there, so an "auto"-resolved
    cap cannot drift between init and step even if the autotuner
    publishes a new threshold in between.

    ``compression`` (on-wire gradient format, ``common/compression.py``)
    only shapes the state through its error-feedback variant: "ef16"
    adds a sharded fp32 residual (``ZeroTrainState.residual``); fp16 and
    bf16 are stateless wire casts, so their states are identical to the
    uncompressed one. "auto" (default) follows ``HOROVOD_COMPRESSION``."""
    from .common.compression import resolve_compression
    from .common.fusion import resolve_bucket_cap

    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats")

    d = int(mesh.shape[axis_name])
    cap = resolve_bucket_cap(bucket_cap_bytes)
    if cap is not None and cap >= 2 ** 31:
        # The cap is stamped into the state as int32 (x64-safe); a >=2GiB
        # bucket cap is indistinguishable from monolithic in practice —
        # reject it instead of overflowing deep inside init.
        raise ValueError(
            f"bucket_cap_bytes={cap} does not fit int32; use a smaller "
            f"cap (or None for monolithic fusion)")
    plan = _make_plan(params, d, cap)
    shard_len = plan.shard_len

    def init_shard(p):
        leaves = jax.tree_util.tree_leaves(p)
        idx = lax.axis_index(axis_name)
        segs = []
        for j in range(len(plan.buckets)):
            slen = plan.bucket_padded[j] // d
            segs.append(lax.dynamic_slice(
                _bucket_flat_f32(leaves, plan, j), (idx * slen,), (slen,)))
        my = jnp.concatenate(segs) if len(segs) > 1 else segs[0]
        return my, optimizer.init(my)

    sharded_init = jax.jit(_shard_map(
        init_shard, mesh, in_specs=(P(),),
        out_specs=(P(axis_name),
                   _opt_state_specs(optimizer, shard_len, axis_name)),
        check_vma=False))

    replicated = NamedSharding(mesh, P())
    params = jax.device_put(params, replicated)
    if batch_stats is not None:
        batch_stats = jax.device_put(batch_stats, replicated)
    pshard, opt_shard = sharded_init(params)

    def _born_sharded_zeros():
        # Born sharded, like pshard/opt_shard: materializing the full
        # padded fp32 buffer on one device first would break the "no full
        # fp32 copy on any one device" invariant exactly when it matters.
        return jax.jit(
            lambda: jnp.zeros((plan.padded,), jnp.float32),
            out_shardings=NamedSharding(mesh, P(axis_name)))()

    gaccum = None
    if accumulate_steps > 1:
        gaccum = _born_sharded_zeros()
    comp = resolve_compression(compression)
    residual = None
    if comp is not None and comp.error_feedback:
        residual = _born_sharded_zeros()
    return ZeroTrainState(params, pshard, opt_shard, gaccum, batch_stats,
                          jax.device_put(jnp.zeros((), jnp.int32),
                                         replicated),
                          jax.device_put(
                              jnp.asarray(-1 if cap is None else cap,
                                          jnp.int32), replicated),
                          residual)


def make_zero_train_step(model, optimizer: optax.GradientTransformation,
                         mesh, axis_name: str = AXIS_GLOBAL,
                         donate: bool = True, accumulate_steps: int = 1,
                         bucket_cap_bytes="auto", compression="auto"):
    """Build the jitted SPMD train step with ZeRO-1 optimizer sharding.

    Drop-in alternative to ``training.make_train_step`` (same call
    signature on the state it builds); the loss/batch-stats semantics
    match it exactly.

    ``accumulate_steps=k`` plays the reference's
    ``backward_passes_per_step`` role: k micro-batches accumulate before
    one optimizer update. The accumulator is the already-scattered
    gradient shard, so accumulation memory stays 1/d (each micro-step
    pays one reduce-scatter — half an allreduce's bytes — and the
    all-gather only runs on update steps, when params actually change).
    Micro-batch gradients are AVERAGED (matching this framework's
    DistributedOptimizer accumulation), not summed as the reference's
    hook accumulation effectively does — multiply the learning rate by k
    when porting a reference config that relied on summed accumulation.
    Requires a state built with the same ``accumulate_steps``.

    ``compression`` compresses the reduce-scatter leg: with fp16/bf16
    the scatter payload travels at the 16-bit wire dtype (half the
    scatter bytes of the fp32 wire) and the reduced shard is upcast to
    fp32 before the ``/d`` averaging and the optimizer update; the
    gather leg already runs at the model dtype and is unchanged. "ef16"
    additionally keeps a sharded fp32 residual in the state (see
    ``ZeroTrainState.residual``) — states with/without residuals are not
    interchangeable, and like the bucket cap, a mismatched state/step
    pair is rejected. "auto" (default) follows ``HOROVOD_COMPRESSION``
    and, for error feedback, the state: a state carrying residuals gets
    the ef16 step."""
    from .common.compression import Compression, resolve_compression
    from .common.fusion import resolve_bucket_cap
    from .training import cross_entropy_loss

    d = int(mesh.shape[axis_name])
    k = accumulate_steps
    # THE STATE OWNS THE LAYOUT: the effective cap is read from
    # state.bucket_cap at call time. An explicit (non-"auto") argument
    # here is only a cross-check against the state; "auto" simply
    # follows whatever the state was built under.
    _auto = isinstance(bucket_cap_bytes, str) and bucket_cap_bytes == "auto"
    _requested_cap = None if _auto else resolve_bucket_cap(bucket_cap_bytes)
    _auto_comp = isinstance(compression, str) and compression == "auto"
    _requested_comp = None if _auto_comp else resolve_compression(compression)

    def _build_step_fn(cap, comp):
        wire = comp.wire_dtype(jnp.float32) if comp is not None else None
        ef = comp is not None and comp.error_feedback
        def step_fn(state: ZeroTrainState, images, labels):
            plan = _make_plan(state.params, d, cap)
            dtypes = plan.dtypes
            # Uniform-dtype models gather at the model dtype (halving gather
            # bytes and the transient flat buffer for bf16); mixed-dtype trees
            # gather at fp32 and let _unflatten_plan cast per leaf.
            gather_dtype = (dtypes[0] if all(dt == dtypes[0] for dt in dtypes)
                            else jnp.float32)

            def loss_fn(p):
                variables = {"params": p}
                if state.batch_stats is not None:
                    variables["batch_stats"] = state.batch_stats
                    logits, updated = model.apply(
                        variables, images, train=True, mutable=["batch_stats"])
                    return (cross_entropy_loss(logits, labels),
                            updated["batch_stats"])
                logits = model.apply(variables, images, train=True)
                return cross_entropy_loss(logits, labels), None

            (loss, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)

            # Mean-reduce and scatter per fusion bucket: each device leaves
            # with its shard of the global-mean gradient. One bucket (no cap)
            # = one collective, the original monolithic layout; with a cap,
            # bucket k's psum_scatter depends only on bucket k's gradients —
            # produced *early* in backprop (reverse parameter order) — so XLA
            # overlaps the shard exchange with the rest of the backward pass.
            # With compression the scatter payload is cast to the 16-bit
            # wire dtype (that halving is the on-wire saving; the flats
            # are fp32 by construction, so one wire dtype covers every
            # bucket) and the reduced shard upcast to fp32 before the /d
            # averaging — fp32 accumulation on the reduced value.
            gleaves = jax.tree_util.tree_leaves(grads)
            idx = lax.axis_index(axis_name) if ef else None
            segs = []
            res_segs = []
            off = 0
            for j in range(len(plan.buckets)):
                flat = _bucket_flat_f32(gleaves, plan, j)
                slen = plan.bucket_padded[j] // d
                if ef:
                    # Sharded error feedback: this device's residual
                    # covers its own contribution to its own output
                    # shard — add it back into that segment before
                    # quantizing (ZeroTrainState.residual docstring).
                    my = (lax.dynamic_slice(flat, (idx * slen,), (slen,))
                          + lax.slice_in_dim(state.residual, off, off + slen))
                    flat = lax.dynamic_update_slice(flat, my, (idx * slen,))
                payload = flat.astype(wire) if wire is not None else flat
                seg = lax.psum_scatter(payload, axis_name, tiled=True)
                if wire is not None:
                    seg = seg.astype(jnp.float32)
                segs.append(seg / d)
                if ef:
                    sent = lax.dynamic_slice(payload, (idx * slen,), (slen,))
                    res_segs.append(my - sent.astype(jnp.float32))
                off += slen
            gshard = jnp.concatenate(segs) if len(segs) > 1 else segs[0]
            new_residual = ((jnp.concatenate(res_segs)
                             if len(res_segs) > 1 else res_segs[0])
                            if ef else state.residual)

            def apply_update(gshard, opt_shard, pshard):
                updates, new_opt = optimizer.update(gshard, opt_shard, pshard)
                new_pshard = optax.apply_updates(pshard, updates)
                flats = []
                off = 0
                for j in range(len(plan.buckets)):
                    slen = plan.bucket_padded[j] // d
                    seg = lax.slice_in_dim(new_pshard, off, off + slen)
                    flats.append(lax.all_gather(seg.astype(gather_dtype),
                                                axis_name, tiled=True))
                    off += slen
                return (_unflatten_plan(flats, plan), new_pshard, new_opt)

            step = state.step + 1
            if k <= 1:
                new_params, new_pshard, new_opt = apply_update(
                    gshard, state.opt_shard, state.pshard)
                new_gaccum = state.gaccum
            else:
                acc = state.gaccum + gshard
                do_update = (step % k) == 0

                def update_branch(operand):
                    acc, opt_shard, pshard = operand
                    p, ps, op_ = apply_update(acc / k, opt_shard, pshard)
                    return p, ps, op_, jnp.zeros_like(acc)

                def skip_branch(operand):
                    acc, opt_shard, pshard = operand
                    return state.params, pshard, opt_shard, acc

                new_params, new_pshard, new_opt, new_gaccum = lax.cond(
                    do_update, update_branch, skip_branch,
                    (acc, state.opt_shard, state.pshard))

            if new_stats is not None:
                new_stats = jax.tree_util.tree_map(
                    lambda x: lax.pmean(x, axis_name), new_stats)
            loss = lax.pmean(loss, axis_name)
            return ZeroTrainState(new_params, new_pshard, new_opt, new_gaccum,
                                  new_stats, step, state.bucket_cap,
                                  new_residual), loss

        return step_fn

    cache = {}

    def step(state: ZeroTrainState, images, labels):
        if (state.gaccum is None) != (k <= 1):
            raise ValueError(
                "state/step accumulate_steps mismatch: build the state "
                "with init_zero_train_state(..., accumulate_steps=k) "
                "matching make_zero_train_step's")
        # The layout-defining cap rides the state (init stamped it);
        # an explicit cap passed to make_zero_train_step must agree.
        # The fetch never blocks the train loop: bucket_cap is the
        # init-time array carried OUTSIDE the jitted program (stripped
        # below), so it is always ready — never an output of the
        # in-flight step.
        if state.bucket_cap is None:
            raise ValueError(
                "ZeroTrainState has no bucket_cap stamp — it was built "
                "by hand or restored without the field. Rebuild it with "
                "init_zero_train_state(...), or _replace(bucket_cap="
                "jnp.int32(-1)) if the layout is known-monolithic.")
        try:
            cap_raw = int(np.asarray(state.bucket_cap))
        except jax.errors.TracerArrayConversionError:
            raise ValueError(
                "make_zero_train_step's step function jits internally "
                "and selects the shard layout from the concrete "
                "state.bucket_cap — call it eagerly instead of wrapping "
                "it in jax.jit (the compiled programs are exposed on "
                "step.cache for lowering/inspection)") from None
        cap = None if cap_raw < 0 else cap_raw
        # Compression follows the same state-owns-it discipline as the
        # cap: the residual's presence IS the error-feedback stamp
        # (ef16 is the only residual-carrying mode), so an "auto" step
        # adopts it; an explicit argument must agree with the state.
        if _auto_comp:
            comp = (Compression.ef16 if state.residual is not None
                    else resolve_compression("auto"))
            if (comp is not None and comp.error_feedback
                    and state.residual is None):
                raise ValueError(
                    "HOROVOD_COMPRESSION resolves to error feedback "
                    "(ef16) but this ZeroTrainState carries no residual "
                    "— it was built without it. Rebuild the state with "
                    "init_zero_train_state(..., compression='ef16') (or "
                    "under the same env) so the residual is born "
                    "sharded.")
        else:
            comp = _requested_comp
            ef_req = comp is not None and comp.error_feedback
            if ef_req != (state.residual is not None):
                mode = comp.name if comp is not None else "none"
                has = ("carries" if state.residual is not None
                       else "has no")
                raise ValueError(
                    f"state/step compression mismatch: the state {has} "
                    f"error-feedback residuals but make_zero_train_step "
                    f"was given compression={mode!r}. Rebuild the state "
                    f"with init_zero_train_state(..., "
                    f"compression={mode!r}) or pass the state's mode.")
        if not _auto and _requested_cap != cap:
            raise ValueError(
                f"state/step bucket cap mismatch: the state's shard "
                f"layout was built under bucket_cap_bytes={cap} but "
                f"make_zero_train_step was given {_requested_cap}. "
                f"Rebuild the state with init_zero_train_state(..., "
                f"bucket_cap_bytes={_requested_cap}) or drop the "
                f"explicit argument to follow the state.")
        # The optimizer-state specs depend on the shard length, which
        # depends on the parameter count — resolve per parameter-tree
        # structure and cache the compiled step under that key, so a
        # state with a different pytree (e.g. after model surgery) gets
        # its own compilation instead of an opaque shape error from a
        # stale spec.
        plan = _make_plan(state.params, d, cap)
        # Surgery on params without rebuilding the state leaves master/
        # optimizer shards sized for the OLD tree — and a state built
        # under a different bucket cap has a different shard layout; catch
        # both here with a descriptive error instead of an opaque
        # shard_map shape failure (round-2 advisor finding).
        expected_padded = plan.padded
        actual_padded = int(np.prod(state.pshard.shape))
        if actual_padded != expected_padded:
            raise ValueError(
                f"ZeroTrainState shards were built for a different "
                f"parameter tree or bucket cap: params flatten to "
                f"{plan.total} elements (padded {expected_padded} under "
                f"bucket_cap_bytes={cap}) but pshard holds "
                f"{actual_padded}. After changing either, rebuild the "
                f"state with init_zero_train_state(...) using the same "
                f"model and bucket_cap_bytes as this step instead of "
                f"reusing the old one.")
        if state.residual is not None:
            actual_res = int(np.prod(state.residual.shape))
            if actual_res != expected_padded:
                raise ValueError(
                    f"ZeroTrainState residual was built for a different "
                    f"layout: expected {expected_padded} elements under "
                    f"bucket_cap_bytes={cap}, got {actual_res}. Rebuild "
                    f"the state with init_zero_train_state(...).")
        key = (plan.treedef, plan.shapes,
               tuple(str(dt) for dt in plan.dtypes),
               state.gaccum is None, cap,
               comp.name if comp is not None else None)
        if key not in cache:
            opt_specs = _opt_state_specs(optimizer, plan.shard_len,
                                         axis_name)
            gaccum_spec = P() if state.gaccum is None else P(axis_name)
            residual_spec = (None if state.residual is None
                             else P(axis_name))
            # bucket_cap is None here: the cap array travels outside the
            # compiled program (re-attached below), so the device never
            # copies it and the host fetch above stays non-blocking.
            state_specs = ZeroTrainState(P(), P(axis_name), opt_specs,
                                         gaccum_spec, P(), P(), None,
                                         residual_spec)
            sharded = _shard_map(
                _build_step_fn(cap, comp), mesh,
                in_specs=(state_specs, P(axis_name), P(axis_name)),
                out_specs=(state_specs, P()),
                check_vma=False)
            cache[key] = jax.jit(
                sharded, donate_argnums=(0,) if donate else ())
        cap_arr = state.bucket_cap
        new_state, loss = cache[key](
            state._replace(bucket_cap=None), images, labels)
        return new_state._replace(bucket_cap=cap_arr), loss

    step.cache = cache  # compiled programs per tree-key (introspection)
    return step
