"""ZeRO sharded training for the JAX-native API — stages 1, 2, and 3.

Beyond the reference's capability set (its DistributedOptimizer keeps the
full optimizer state on every worker): the partitioning of Rajbhandari
et al.'s ZeRO (arXiv:1910.02054), expressed TPU-natively as one compiled
SPMD program per step. The stage — ``HOROVOD_ZERO_STAGE`` / the
``zero_stage`` argument — selects how much of the training state is
partitioned 1/d across the mesh axis:

    stage 1   optimizer state + fp32 masters sharded; the full mean
              gradient is materialized on every device (per-bucket psum,
              then each device slices its own shard). Memory:
              params + grads O(P), state O(P/d).
    stage 2   gradients partitioned too (the default): each bucket's
              gradient is reduce-scattered, landing directly in its
              owning rank's shard — the full-gradient buffer never
              exists. Memory: params O(P), grads + state O(P/d).
              Numerically, psum-then-slice and psum_scatter apply the
              same reduction math, so stages 1 and 2 are bitwise equal
              on exactly-representable inputs.
    stage 3   parameters partitioned as well: the state holds NO
              replicated params (``ZeroTrainState.params`` is a
              zero-byte ``jax.ShapeDtypeStruct`` shape template), only
              the fp32 master shard. The forward pass all-gathers each
              fusion bucket's params just-in-time, in FORWARD bucket
              order (``common/fusion.forward_bucket_order`` — the
              backward-order scatter plan, run forward), with a
              depth-``HOROVOD_ZERO_PREFETCH`` prefetch chain: gather
              i's only dependence on earlier gathers is a zero-length
              anchor on gather i-(p+1), so up to p+1 gathers are in
              flight and every gather is dataflow-independent of the
              overlapped compute (XLA's latency-hiding scheduler can
              hoist them; proven by jaxpr-cone tests in
              ``tests/test_fusion_overlap.py``). The backward pass
              re-gathers under ``jax.checkpoint`` (gather outputs are
              tagged ``zero3_gather`` and excluded from the saved set),
              recomputing each bucket's params as its cotangents are
              consumed — reverse parameter order — instead of keeping
              them live across the whole backward. Gradients leave
              through the same reduce-scatter as stage 2 (it is the
              transpose of the gather). Memory: params + grads + state
              all O(P/d).

For fp32 models the stage-1/2 reduce-scatter + all-gather pair moves
exactly the same bytes as the allreduce it replaces (an allreduce IS a
reduce-scatter + all-gather). Stage 3 moves one extra gather per step
(the backward re-gather), the classic ZeRO-3 1.5x communication trade
for O(P/d) memory. For reduced-precision models (uniform bf16/fp16
params) gathers run at the model dtype — master shards are cast before
the all-gather — and only the scatter leg pays fp32 width (for
reduction precision) unless compression narrows it.

Works with any *elementwise* optax transformation (sgd, momentum, adam,
adamw, rmsprop, ...): the update runs on a flat concatenated shard, which
is elementwise-equivalent to running on the structured pytree. Transforms
that need global structure (global-norm clipping, layerwise LARS) must
stay outside or be re-derived with a psum — documented limitation.

See docs/zero.md for the stage table, memory model, prefetch schedule,
and compression composition.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import NamedSharding, PartitionSpec as P

from .common import faults as _faults
from .common.compat import shard_map as _shard_map
from .common.state import AXIS_GLOBAL
from .ops import xla as _xla


class ZeroTrainState(NamedTuple):
    params: Any       # full pytree, replicated (model dtype); at stage 3
                      # a pytree of jax.ShapeDtypeStruct — the zero-byte
                      # shape template the step rebuilds layouts from
    pshard: Any       # this device's flat fp32 master-weight shard
    opt_shard: Any    # optimizer state over the master shard
    gaccum: Any       # accumulated gradient shard (None unless accumulating)
    batch_stats: Any
    step: Any
    # Fusion-bucket cap (bytes) the shard layout was built under, as a
    # replicated int32 scalar (-1 = monolithic). THE STATE OWNS THE
    # LAYOUT: make_zero_train_step reads the cap from here, so an
    # "auto"-resolved cap can never drift between init and step (e.g.
    # when the autotuner publishes a new threshold in between) — total
    # padded size alone cannot detect such drift when leaf sizes align
    # with the mesh (zero per-bucket padding).
    bucket_cap: Any = None
    # Error-feedback residuals for the compressed reduce-scatter
    # ("ef16"), sharded like gaccum (1/d per device, fp32, padded flat
    # layout). Each device keeps the quantization error of ITS OWN
    # contribution to ITS OWN output shard and re-injects it there next
    # step — the sharded-residual scheme (full per-rank residuals would
    # cost a persistent fp32 gradient copy per device, forfeiting ZeRO's
    # memory scaling; see docs/compression.md). None when the state was
    # built without error feedback; like bucket_cap, the state owns it —
    # a step resolving a different mode is rejected.
    residual: Any = None
    # ZeRO stage (1/2/3) the state was built for, as a replicated int32
    # scalar. Same state-owns-the-mode discipline as bucket_cap: the
    # stage decides what the state physically holds (stage 3 has no
    # replicated params), so the step reads it from here and a
    # mismatched explicit argument is rejected.
    stage: Any = None


def _shard_len(total: int, d: int) -> int:
    """One source of truth for the padding arithmetic: flat length padded
    up to a multiple of d, divided across the d shards."""
    return ((total + d - 1) // d * d) // d


def _resolve_stage(zero_stage) -> int:
    """Resolve the user-facing stage knob ("auto" follows
    ``HOROVOD_ZERO_STAGE``, default 2) to a validated int in {1,2,3}."""
    from .common import config as _config

    if isinstance(zero_stage, str):
        if zero_stage != "auto":
            raise ValueError(
                f"zero_stage must be 1, 2, 3, or 'auto'; got {zero_stage!r}")
        return _config.zero_stage()
    s = int(zero_stage)
    if s not in (1, 2, 3):
        raise ValueError(f"zero_stage must be 1, 2, or 3; got {s}")
    return s


def _params_are_template(params) -> bool:
    """True when every params leaf is a zero-byte ShapeDtypeStruct —
    the stage-3 representation."""
    leaves = jax.tree_util.tree_leaves(params)
    return bool(leaves) and all(
        isinstance(l, jax.ShapeDtypeStruct) for l in leaves)


class _ZeroPlan(NamedTuple):
    """Static flattening plan, generalized over fusion buckets.

    The device shard is the concatenation of per-bucket shards: bucket j
    flattens its leaves (fp32), pads to a multiple of d, reduce-scatters,
    and contributes ``bucket_padded[j] // d`` elements. With no bucket
    cap there is exactly one bucket holding every leaf in parameter
    order — the layout (and therefore every checkpointed shard) is
    bit-identical to the pre-bucketing monolithic flat. With a cap,
    buckets come from ``common/fusion.plan_buckets`` in reverse parameter
    (≈ backward-production) order, so each bucket's reduce-scatter
    depends only on its own gradients and overlaps the rest of backprop;
    the stage-3 forward walks the same buckets in forward order
    (``fusion.forward_bucket_order``) for the parameter gathers. States
    built under different caps have different shard layouts and are not
    interchangeable — rebuild (or restore via the pytree checkpoint
    path) when changing the cap.
    """

    treedef: Any
    shapes: tuple
    dtypes: tuple
    sizes: tuple          # per-leaf element counts
    total: int            # sum(sizes)
    buckets: tuple        # tuple[tuple[int, ...]]: leaf indices per bucket
    bucket_elems: tuple   # unpadded element count per bucket
    bucket_padded: tuple  # padded element count per bucket (multiple of d)
    shard_len: int        # per-device shard length

    @property
    def padded(self) -> int:
        return sum(self.bucket_padded)


def _make_plan(params, d: int, bucket_cap_bytes=None) -> _ZeroPlan:
    from .common.fusion import plan_buckets

    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = tuple(l.shape for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    total = int(sum(sizes))
    if bucket_cap_bytes:
        # The wire format is fp32 regardless of model dtype (reduction
        # precision), so the planner sees fp32 byte sizes and one dtype —
        # buckets close on the byte cap only.
        buckets = tuple(
            b.indices for b in plan_buckets(
                [s * 4 for s in sizes], [jnp.float32] * len(sizes),
                bucket_cap_bytes))
    else:
        buckets = (tuple(range(len(sizes))),) if sizes else ()
    bucket_elems = tuple(sum(sizes[i] for i in idxs) for idxs in buckets)
    bucket_padded = tuple(_shard_len(n, d) * d for n in bucket_elems)
    shard_len = sum(p // d for p in bucket_padded)
    return _ZeroPlan(treedef, shapes, dtypes, sizes, total, buckets,
                     bucket_elems, bucket_padded, shard_len)


def _forward_order(plan: _ZeroPlan):
    """Bucket visit order for the stage-3 gathers: the backward-order
    plan run forward (``fusion.forward_bucket_order``)."""
    from .common.fusion import Bucket, forward_bucket_order

    return forward_bucket_order(
        [Bucket(idxs, None, 0) for idxs in plan.buckets])


def _bucket_flat_f32(leaves, plan: _ZeroPlan, j: int):
    """Bucket j's leaves as one padded fp32 flat (the scatter payload)."""
    idxs = plan.buckets[j]
    flat = (jnp.concatenate([leaves[i].astype(jnp.float32).reshape(-1)
                             for i in idxs])
            if len(idxs) > 1
            else leaves[idxs[0]].astype(jnp.float32).reshape(-1))
    pad = plan.bucket_padded[j] - plan.bucket_elems[j]
    return jnp.pad(flat, (0, pad)) if pad else flat


def _unflatten_plan(bucket_flats, plan: _ZeroPlan):
    """Rebuild the parameter pytree from per-bucket gathered flats."""
    leaves = [None] * len(plan.sizes)
    for j, idxs in enumerate(plan.buckets):
        flat = bucket_flats[j]
        off = 0
        for i in idxs:
            n = plan.sizes[i]
            leaves[i] = (flat[off:off + n].reshape(plan.shapes[i])
                         .astype(plan.dtypes[i]))
            off += n
    return jax.tree_util.tree_unflatten(plan.treedef, leaves)


def _opt_state_specs(optimizer, shard_len, axis_name):
    """Per-leaf partition specs for the optimizer state over a flat
    shard: vector leaves (mu/nu/momentum, one element per parameter
    element) shard along the axis; scalar leaves (step counts) are
    replicated — identical on every device by construction."""
    shapes = jax.eval_shape(
        optimizer.init, jnp.zeros((shard_len,), jnp.float32))
    return jax.tree_util.tree_map(
        lambda s: P(axis_name) if len(s.shape) >= 1 else P(), shapes)


def _make_zero3_gather(axis_name, gather_dtype, wire, ef):
    """Build the differentiable stage-3 bucket gather.

    Forward: ``ops/xla.zero_allgather`` — an optimization_barrier pins
    the gather behind its zero-length prefetch anchor (the only ordering
    edge; see the prefetch chain in ``_build_step_fn``), then a tiled
    all_gather at ``gather_dtype``. The barrier has no differentiation
    rule, which is exactly why the gather is a ``jax.custom_vjp``: the
    primal/fwd bodies are never differentiated through, and the anchor's
    "gradient" is defined as zeros.

    Backward: the transpose of the gather is the stage-2 gradient
    reduce-scatter, so the bucket's gradient exchange IS this VJP —
    cotangents are upcast to fp32, (for ef16) the device's sharded
    residual is injected into its own segment, the payload is cast to
    the wire dtype and tiled-psum_scattered, and the reduced shard is
    upcast to fp32 (the fp32-accumulation-window discipline of
    ``ops/xla.py``). For ef16 the residual input's returned cotangent
    is defined as ``my - sent`` — the quantization error of this
    device's contribution to its own output shard — so
    ``value_and_grad`` over (pshard, residual) yields the new residual
    for free, in the same sharded layout.
    """
    if ef:
        @jax.custom_vjp
        def gather(seg, res, anchor):
            return _xla.zero_allgather(seg, axis_name, gather_dtype, anchor)

        def gather_fwd(seg, res, anchor):
            return (_xla.zero_allgather(seg, axis_name, gather_dtype, anchor),
                    (res, anchor))

        def gather_bwd(saved, cot):
            res, anchor = saved
            slen = res.shape[0]
            flat = cot.astype(jnp.float32)
            idx = lax.axis_index(axis_name)
            my = lax.dynamic_slice(flat, (idx * slen,), (slen,)) + res
            flat = lax.dynamic_update_slice(flat, my, (idx * slen,))
            payload = flat.astype(wire) if wire is not None else flat
            gseg = _xla.zero_reducescatter(flat, axis_name, wire)
            sent = lax.dynamic_slice(payload, (idx * slen,),
                                     (slen,)).astype(jnp.float32)
            return gseg, my - sent, jnp.zeros_like(anchor)

        gather.defvjp(gather_fwd, gather_bwd)
        return gather

    @jax.custom_vjp
    def gather(seg, anchor):
        return _xla.zero_allgather(seg, axis_name, gather_dtype, anchor)

    def gather_fwd(seg, anchor):
        return (_xla.zero_allgather(seg, axis_name, gather_dtype, anchor),
                anchor)

    def gather_bwd(anchor, cot):
        gseg = _xla.zero_reducescatter(
            cot.astype(jnp.float32), axis_name, wire)
        return gseg, jnp.zeros_like(anchor)

    gather.defvjp(gather_fwd, gather_bwd)
    return gather


def init_zero_train_state(model, optimizer: optax.GradientTransformation,
                          rng, sample_input, mesh,
                          axis_name: str = AXIS_GLOBAL,
                          accumulate_steps: int = 1,
                          bucket_cap_bytes="auto",
                          compression="auto",
                          zero_stage="auto") -> ZeroTrainState:
    """Initialize the ZeRO train state for the resolved stage.

    Masters and optimizer state are created per-device on that device's
    flat shard inside a shard_mapped init, so they are born sharded — no
    full fp32 copy ever exists on any one device. With
    ``accumulate_steps > 1`` a sharded gradient accumulator is added (the
    ``backward_passes_per_step`` role, still 1/d memory).

    ``zero_stage`` ("auto" follows ``HOROVOD_ZERO_STAGE``, default 2)
    is stamped into the state (``ZeroTrainState.stage``) the same way
    the bucket cap is — the state owns the mode. At stage 3 the
    replicated model-dtype params are DROPPED after the master shards
    are carved: ``state.params`` becomes a pytree of
    ``jax.ShapeDtypeStruct`` (zero bytes), and the persistent parameter
    footprint is the fp32 ``pshard`` alone. (``model.init`` still
    materializes full params transiently during this call — init-time
    only; the steady-state footprint is what stage 3 shrinks.)

    ``bucket_cap_bytes`` defines the shard layout (see ``_ZeroPlan``)
    and is recorded IN the state (``bucket_cap``); the step built by
    ``make_zero_train_step`` reads it from there, so an "auto"-resolved
    cap cannot drift between init and step even if the autotuner
    publishes a new threshold in between.

    ``compression`` (on-wire gradient format, ``common/compression.py``)
    only shapes the state through its error-feedback variant: "ef16"
    adds a sharded fp32 residual (``ZeroTrainState.residual``); fp16 and
    bf16 are stateless wire casts, so their states are identical to the
    uncompressed one. "auto" (default) follows ``HOROVOD_COMPRESSION``.
    All modes compose with every stage — at stage 3 the residual feeds
    the gather VJP's reduce-scatter (see ``_make_zero3_gather``)."""
    from .common.compression import resolve_compression
    from .common.fusion import resolve_bucket_cap

    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats")

    d = int(mesh.shape[axis_name])
    stage = _resolve_stage(zero_stage)
    cap = resolve_bucket_cap(bucket_cap_bytes)
    if cap is not None and cap >= 2 ** 31:
        # The cap is stamped into the state as int32 (x64-safe); a >=2GiB
        # bucket cap is indistinguishable from monolithic in practice —
        # reject it instead of overflowing deep inside init.
        raise ValueError(
            f"bucket_cap_bytes={cap} does not fit int32; use a smaller "
            f"cap (or None for monolithic fusion)")
    plan = _make_plan(params, d, cap)
    shard_len = plan.shard_len

    def init_shard(p):
        leaves = jax.tree_util.tree_leaves(p)
        idx = lax.axis_index(axis_name)
        segs = []
        for j in range(len(plan.buckets)):
            slen = plan.bucket_padded[j] // d
            segs.append(lax.dynamic_slice(
                _bucket_flat_f32(leaves, plan, j), (idx * slen,), (slen,)))
        my = jnp.concatenate(segs) if len(segs) > 1 else segs[0]
        return my, optimizer.init(my)

    sharded_init = jax.jit(_shard_map(
        init_shard, mesh, in_specs=(P(),),
        out_specs=(P(axis_name),
                   _opt_state_specs(optimizer, shard_len, axis_name)),
        check_vma=False))

    replicated = NamedSharding(mesh, P())
    params = jax.device_put(params, replicated)
    if batch_stats is not None:
        batch_stats = jax.device_put(batch_stats, replicated)
    pshard, opt_shard = sharded_init(params)

    if stage == 3:
        # Parameters live ONLY as the fp32 master shard from here on;
        # the template keeps structure/shapes/dtypes for the step's
        # plan and for gather_params without holding a single byte.
        params = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), params)

    def _born_sharded_zeros():
        # Born sharded, like pshard/opt_shard: materializing the full
        # padded fp32 buffer on one device first would break the "no full
        # fp32 copy on any one device" invariant exactly when it matters.
        return jax.jit(
            lambda: jnp.zeros((plan.padded,), jnp.float32),
            out_shardings=NamedSharding(mesh, P(axis_name)))()

    gaccum = None
    if accumulate_steps > 1:
        gaccum = _born_sharded_zeros()
    comp = resolve_compression(compression)
    residual = None
    if comp is not None and comp.error_feedback:
        residual = _born_sharded_zeros()
    return ZeroTrainState(params, pshard, opt_shard, gaccum, batch_stats,
                          jax.device_put(jnp.zeros((), jnp.int32),
                                         replicated),
                          jax.device_put(
                              jnp.asarray(-1 if cap is None else cap,
                                          jnp.int32), replicated),
                          residual,
                          jax.device_put(jnp.asarray(stage, jnp.int32),
                                         replicated))


def gather_params(state: ZeroTrainState, mesh,
                  axis_name: str = AXIS_GLOBAL):
    """Materialize the full parameter pytree from any ZeroTrainState.

    For stage-1/2 states this is just ``state.params`` (already
    replicated). For stage-3 states (params held as a shape template)
    the fp32 master shards are all-gathered per bucket and unflattened —
    the eval/checkpoint/export escape hatch; the train step itself never
    calls this (it gathers just-in-time inside the compiled program)."""
    if state.params is None:
        raise ValueError("state has no params (not an initialized "
                         "ZeroTrainState)")
    if not _params_are_template(state.params):
        return state.params
    if state.bucket_cap is None:
        raise ValueError(
            "stage-3 ZeroTrainState has no bucket_cap stamp — rebuild "
            "it with init_zero_train_state(...)")
    cap_raw = int(np.asarray(state.bucket_cap))
    cap = None if cap_raw < 0 else cap_raw
    d = int(mesh.shape[axis_name])
    plan = _make_plan(state.params, d, cap)

    def gather(pshard):
        flats = []
        off = 0
        for j in range(len(plan.buckets)):
            slen = plan.bucket_padded[j] // d
            flats.append(lax.all_gather(
                lax.slice_in_dim(pshard, off, off + slen),
                axis_name, tiled=True))
            off += slen
        return _unflatten_plan(flats, plan)

    fn = jax.jit(_shard_map(gather, mesh, in_specs=(P(axis_name),),
                            out_specs=P(), check_vma=False))
    return fn(state.pshard)


def make_zero_train_step(model, optimizer: optax.GradientTransformation,
                         mesh, axis_name: str = AXIS_GLOBAL,
                         donate: bool = True, accumulate_steps: int = 1,
                         bucket_cap_bytes="auto", compression="auto",
                         zero_stage="auto", prefetch="auto"):
    """Build the jitted SPMD train step for ZeRO stage 1, 2, or 3.

    Drop-in alternative to ``training.make_train_step`` (same call
    signature on the state it builds); the loss/batch-stats semantics
    match it exactly. The stage is read from the state's stamp (see
    ``init_zero_train_state``); an explicit ``zero_stage`` here is only
    a cross-check, exactly like ``bucket_cap_bytes``.

    ``prefetch`` (stage 3 only; "auto" follows ``HOROVOD_ZERO_PREFETCH``
    or the autotuner's pinned depth, default 1) sets how many parameter
    gathers may be in flight ahead of the compute front: gather i's only
    dependence on earlier gathers is a zero-length anchor on gather
    i-(p+1). Depth 0 serializes the gathers against each other (they
    remain independent of compute); depth never changes numerics, only
    the dataflow chain — so it is autotunable for free.

    ``accumulate_steps=k`` plays the reference's
    ``backward_passes_per_step`` role: k micro-batches accumulate before
    one optimizer update. The accumulator is the already-scattered
    gradient shard, so accumulation memory stays 1/d (each micro-step
    pays one reduce-scatter — half an allreduce's bytes — and the
    all-gather only runs on update steps, when params actually change;
    at stage 3 the forward gathers run every micro-step by necessity).
    Micro-batch gradients are AVERAGED (matching this framework's
    DistributedOptimizer accumulation), not summed as the reference's
    hook accumulation effectively does — multiply the learning rate by k
    when porting a reference config that relied on summed accumulation.
    Requires a state built with the same ``accumulate_steps``.

    ``compression`` compresses the reduce-scatter leg: with fp16/bf16
    the scatter payload travels at the 16-bit wire dtype (half the
    scatter bytes of the fp32 wire) and the reduced shard is upcast to
    fp32 before the ``/d`` averaging and the optimizer update; the
    gather leg already runs at the model dtype and is unchanged. "ef16"
    additionally keeps a sharded fp32 residual in the state (see
    ``ZeroTrainState.residual``) — states with/without residuals are not
    interchangeable, and like the bucket cap, a mismatched state/step
    pair is rejected. "auto" (default) follows ``HOROVOD_COMPRESSION``
    and, for error feedback, the state: a state carrying residuals gets
    the ef16 step. At stage 3 the compressed scatter (and the residual
    update) runs inside the gather VJP — same wire bytes, same
    sharded-residual semantics."""
    from .common.compression import Compression, resolve_compression
    from .common.fusion import resolve_bucket_cap, resolve_prefetch_depth
    from .training import cross_entropy_loss

    d = int(mesh.shape[axis_name])
    k = accumulate_steps
    # THE STATE OWNS THE LAYOUT (and the stage): the effective cap and
    # stage are read from the state at call time. Explicit (non-"auto")
    # arguments here are only cross-checks against the state; "auto"
    # simply follows whatever the state was built under.
    _auto = isinstance(bucket_cap_bytes, str) and bucket_cap_bytes == "auto"
    _requested_cap = None if _auto else resolve_bucket_cap(bucket_cap_bytes)
    _auto_comp = isinstance(compression, str) and compression == "auto"
    _requested_comp = None if _auto_comp else resolve_compression(compression)
    _auto_stage = isinstance(zero_stage, str) and zero_stage == "auto"
    _requested_stage = None if _auto_stage else _resolve_stage(zero_stage)

    def _build_step_fn(plan, cap, comp, stage, pf):
        wire = comp.wire_dtype(jnp.float32) if comp is not None else None
        ef = comp is not None and comp.error_feedback
        dtypes = plan.dtypes
        # Uniform-dtype models gather at the model dtype (halving gather
        # bytes and the transient flat buffer for bf16); mixed-dtype trees
        # gather at fp32 and let _unflatten_plan cast per leaf.
        gather_dtype = (dtypes[0] if all(dt == dtypes[0] for dt in dtypes)
                        else jnp.float32)
        nb = len(plan.buckets)
        slens = [p // d for p in plan.bucket_padded]
        offs = []
        off = 0
        for s in slens:
            offs.append(off)
            off += s

        def grads_dp(state, images, labels):
            """Stages 1/2: differentiate w.r.t. the replicated params,
            then exchange gradient shards per fusion bucket."""
            def loss_fn(p):
                variables = {"params": p}
                if state.batch_stats is not None:
                    variables["batch_stats"] = state.batch_stats
                    logits, updated = model.apply(
                        variables, images, train=True, mutable=["batch_stats"])
                    return (cross_entropy_loss(logits, labels),
                            updated["batch_stats"])
                logits = model.apply(variables, images, train=True)
                return cross_entropy_loss(logits, labels), None

            (loss, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)

            # Mean-reduce per fusion bucket: each device leaves with its
            # shard of the global-mean gradient. Stage 2 reduce-scatters
            # (the full-gradient buffer never exists); stage 1 psums the
            # full bucket and slices its own shard — the full mean
            # gradient is live, the classic stage-1 memory shape, and
            # bitwise-identical to stage 2 for exactly-representable
            # values (same reduction math, same operands). One bucket
            # (no cap) = one collective, the original monolithic layout;
            # with a cap, bucket k's collective depends only on bucket
            # k's gradients — produced *early* in backprop (reverse
            # parameter order) — so XLA overlaps the exchange with the
            # rest of the backward pass. With compression the payload is
            # cast to the 16-bit wire dtype (that halving is the on-wire
            # saving; the flats are fp32 by construction, so one wire
            # dtype covers every bucket) and the reduced shard upcast to
            # fp32 before the /d averaging — fp32 accumulation on the
            # reduced value.
            gleaves = jax.tree_util.tree_leaves(grads)
            idx = (lax.axis_index(axis_name)
                   if (ef or stage == 1) else None)
            segs = []
            res_segs = []
            off = 0
            for j in range(nb):
                flat = _bucket_flat_f32(gleaves, plan, j)
                slen = slens[j]
                if ef:
                    # Sharded error feedback: this device's residual
                    # covers its own contribution to its own output
                    # shard — add it back into that segment before
                    # quantizing (ZeroTrainState.residual docstring).
                    my = (lax.dynamic_slice(flat, (idx * slen,), (slen,))
                          + lax.slice_in_dim(state.residual, off, off + slen))
                    flat = lax.dynamic_update_slice(flat, my, (idx * slen,))
                payload = flat.astype(wire) if wire is not None else flat
                if stage == 1:
                    full = lax.psum(payload, axis_name)
                    if wire is not None:
                        full = full.astype(jnp.float32)
                    seg = lax.dynamic_slice(full, (idx * slen,), (slen,))
                else:
                    seg = lax.psum_scatter(payload, axis_name, tiled=True)
                    if wire is not None:
                        seg = seg.astype(jnp.float32)
                segs.append(seg / d)
                if ef:
                    sent = lax.dynamic_slice(payload, (idx * slen,), (slen,))
                    res_segs.append(my - sent.astype(jnp.float32))
                off += slen
            gshard = jnp.concatenate(segs) if len(segs) > 1 else segs[0]
            new_residual = ((jnp.concatenate(res_segs)
                             if len(res_segs) > 1 else res_segs[0])
                            if ef else state.residual)
            return loss, new_stats, gshard, new_residual

        order = _forward_order(plan)
        gather = _make_zero3_gather(axis_name, gather_dtype, wire, ef)

        def grads_zero3(state, images, labels):
            """Stage 3: params exist only as the fp32 master shard.
            Differentiate w.r.t. the shard itself — the forward gathers
            each bucket just-in-time through the custom-VJP gather, and
            the VJP's reduce-scatter IS the gradient exchange (it lands
            the bucket's gradient directly in its owning shard, stage-2
            style). The whole loss runs under ``jax.checkpoint`` with
            the gather outputs excluded from the saved set, so the
            backward pass re-gathers each bucket as its cotangents come
            due (reverse parameter order) instead of holding every
            gathered bucket live across backprop."""

            def loss_fn(pshard, residual):
                gathered = [None] * nb
                visited = []
                for pos, j in enumerate(order):
                    seg = lax.slice_in_dim(pshard, offs[j], offs[j] + slens[j])
                    if pos > pf:
                        # The prefetch chain: a ZERO-LENGTH slice of the
                        # gather p+1 positions back is this gather's only
                        # ordering edge — no data bytes, no dependence on
                        # any compute, just "at most p+1 gathers in
                        # flight" for the scheduler.
                        anchor = lax.slice_in_dim(
                            visited[pos - pf - 1], 0, 0)
                    else:
                        anchor = jnp.zeros((0,), gather_dtype)
                    if ef:
                        res_seg = lax.slice_in_dim(
                            residual, offs[j], offs[j] + slens[j])
                        g = gather(seg, res_seg, anchor)
                    else:
                        g = gather(seg, anchor)
                    # Named so the remat policy below EXCLUDES gathered
                    # params from the saved set — the backward re-gathers
                    # instead of keeping O(P) gathered buffers alive.
                    g = checkpoint_name(g, "zero3_gather")
                    visited.append(g)
                    gathered[j] = g
                p = _unflatten_plan(gathered, plan)
                variables = {"params": p}
                if state.batch_stats is not None:
                    variables["batch_stats"] = state.batch_stats
                    logits, updated = model.apply(
                        variables, images, train=True, mutable=["batch_stats"])
                    return (cross_entropy_loss(logits, labels),
                            updated["batch_stats"])
                logits = model.apply(variables, images, train=True)
                return cross_entropy_loss(logits, labels), None

            ckpt_loss = jax.checkpoint(
                loss_fn,
                policy=jax.checkpoint_policies.save_any_names_but_these(
                    "zero3_gather"))
            if ef:
                ((loss, new_stats),
                 (gsum, new_residual)) = jax.value_and_grad(
                     ckpt_loss, argnums=(0, 1), has_aux=True)(
                         state.pshard, state.residual)
            else:
                (loss, new_stats), gsum = jax.value_and_grad(
                    ckpt_loss, has_aux=True)(state.pshard, state.residual)
                new_residual = state.residual
            # The VJP reduce-scatter sums over ranks; average here (the
            # stage-1/2 paths divide per bucket — same value).
            return loss, new_stats, gsum / d, new_residual

        def step_fn(state: ZeroTrainState, images, labels):
            if stage == 3:
                loss, new_stats, gshard, new_residual = grads_zero3(
                    state, images, labels)
            else:
                loss, new_stats, gshard, new_residual = grads_dp(
                    state, images, labels)

            def apply_update(gshard, opt_shard, pshard):
                updates, new_opt = optimizer.update(gshard, opt_shard, pshard)
                new_pshard = optax.apply_updates(pshard, updates)
                if stage == 3:
                    # Parameters stay partitioned: no trailing gather —
                    # the NEXT step's forward gathers the fresh masters
                    # just-in-time.
                    return None, new_pshard, new_opt
                flats = []
                off = 0
                for j in range(nb):
                    seg = lax.slice_in_dim(new_pshard, off, off + slens[j])
                    flats.append(lax.all_gather(seg.astype(gather_dtype),
                                                axis_name, tiled=True))
                    off += slens[j]
                return (_unflatten_plan(flats, plan), new_pshard, new_opt)

            step = state.step + 1
            if k <= 1:
                new_params, new_pshard, new_opt = apply_update(
                    gshard, state.opt_shard, state.pshard)
                new_gaccum = state.gaccum
            else:
                acc = state.gaccum + gshard
                do_update = (step % k) == 0

                def update_branch(operand):
                    acc, opt_shard, pshard = operand
                    p, ps, op_ = apply_update(acc / k, opt_shard, pshard)
                    return p, ps, op_, jnp.zeros_like(acc)

                def skip_branch(operand):
                    acc, opt_shard, pshard = operand
                    return state.params, pshard, opt_shard, acc

                new_params, new_pshard, new_opt, new_gaccum = lax.cond(
                    do_update, update_branch, skip_branch,
                    (acc, state.opt_shard, state.pshard))

            if new_stats is not None:
                new_stats = jax.tree_util.tree_map(
                    lambda x: lax.pmean(x, axis_name), new_stats)
            loss = lax.pmean(loss, axis_name)
            return ZeroTrainState(new_params, new_pshard, new_opt, new_gaccum,
                                  new_stats, step, state.bucket_cap,
                                  new_residual, state.stage), loss

        return step_fn

    cache = {}

    def step(state: ZeroTrainState, images, labels):
        if (state.gaccum is None) != (k <= 1):
            raise ValueError(
                "state/step accumulate_steps mismatch: build the state "
                "with init_zero_train_state(..., accumulate_steps=k) "
                "matching make_zero_train_step's")
        # The layout-defining cap and the stage ride the state (init
        # stamped them); explicit arguments here must agree. The fetch
        # never blocks the train loop: bucket_cap/stage are init-time
        # arrays carried OUTSIDE the jitted program (stripped below), so
        # they are always ready — never outputs of the in-flight step.
        if state.bucket_cap is None:
            raise ValueError(
                "ZeroTrainState has no bucket_cap stamp — it was built "
                "by hand or restored without the field. Rebuild it with "
                "init_zero_train_state(...), or _replace(bucket_cap="
                "jnp.int32(-1)) if the layout is known-monolithic.")
        if state.stage is None:
            raise ValueError(
                "ZeroTrainState has no stage stamp — it was built by "
                "hand or restored from a pre-stage checkpoint. Rebuild "
                "it with init_zero_train_state(...), or _replace(stage="
                "jnp.int32(2)) if it predates stages (the historical "
                "behavior is stage 2: scattered gradients).")
        try:
            cap_raw = int(np.asarray(state.bucket_cap))
            stage = int(np.asarray(state.stage))
        except jax.errors.TracerArrayConversionError:
            raise ValueError(
                "make_zero_train_step's step function jits internally "
                "and selects the shard layout from the concrete "
                "state.bucket_cap/state.stage — call it eagerly instead "
                "of wrapping it in jax.jit (the compiled programs are "
                "exposed on step.cache for lowering/inspection)") from None
        cap = None if cap_raw < 0 else cap_raw
        if stage not in (1, 2, 3):
            raise ValueError(
                f"ZeroTrainState carries invalid stage stamp {stage}; "
                f"expected 1, 2, or 3")
        if not _auto_stage and _requested_stage != stage:
            raise ValueError(
                f"state/step ZeRO stage mismatch: the state was built "
                f"for stage {stage} but make_zero_train_step was given "
                f"zero_stage={_requested_stage}. Rebuild the state with "
                f"init_zero_train_state(..., zero_stage="
                f"{_requested_stage}) or drop the explicit argument to "
                f"follow the state.")
        is_template = _params_are_template(state.params)
        if stage == 3 and not is_template:
            raise ValueError(
                "stage-3 ZeroTrainState must hold its params as a "
                "zero-byte shape template (jax.ShapeDtypeStruct pytree) "
                "— this state carries concrete arrays, so it was built "
                "by hand or its stage stamp was forged. Rebuild it with "
                "init_zero_train_state(..., zero_stage=3).")
        if stage != 3 and is_template:
            raise ValueError(
                f"stage-{stage} ZeroTrainState must carry replicated "
                f"params, but this state holds a shape template "
                f"(stage-3 layout). Rebuild it with "
                f"init_zero_train_state(..., zero_stage={stage}).")
        # Compression follows the same state-owns-it discipline as the
        # cap: the residual's presence IS the error-feedback stamp
        # (ef16 is the only residual-carrying mode), so an "auto" step
        # adopts it; an explicit argument must agree with the state.
        if _auto_comp:
            comp = (Compression.ef16 if state.residual is not None
                    else resolve_compression("auto"))
            if (comp is not None and comp.error_feedback
                    and state.residual is None):
                raise ValueError(
                    "HOROVOD_COMPRESSION resolves to error feedback "
                    "(ef16) but this ZeroTrainState carries no residual "
                    "— it was built without it. Rebuild the state with "
                    "init_zero_train_state(..., compression='ef16') (or "
                    "under the same env) so the residual is born "
                    "sharded.")
        else:
            comp = _requested_comp
            ef_req = comp is not None and comp.error_feedback
            if ef_req != (state.residual is not None):
                mode = comp.name if comp is not None else "none"
                has = ("carries" if state.residual is not None
                       else "has no")
                raise ValueError(
                    f"state/step compression mismatch: the state {has} "
                    f"error-feedback residuals but make_zero_train_step "
                    f"was given compression={mode!r}. Rebuild the state "
                    f"with init_zero_train_state(..., "
                    f"compression={mode!r}) or pass the state's mode.")
        if not _auto and _requested_cap != cap:
            raise ValueError(
                f"state/step bucket cap mismatch: the state's shard "
                f"layout was built under bucket_cap_bytes={cap} but "
                f"make_zero_train_step was given {_requested_cap}. "
                f"Rebuild the state with init_zero_train_state(..., "
                f"bucket_cap_bytes={_requested_cap}) or drop the "
                f"explicit argument to follow the state.")
        # Prefetch depth only shapes stage-3 programs; resolve it live
        # (the autotuner may pin a new depth between steps — a changed
        # depth is a new cache key, i.e. a recompile, not a drift).
        pf = resolve_prefetch_depth(prefetch) if stage == 3 else 0
        # The optimizer-state specs depend on the shard length, which
        # depends on the parameter count — resolve per parameter-tree
        # structure and cache the compiled step under that key, so a
        # state with a different pytree (e.g. after model surgery) gets
        # its own compilation instead of an opaque shape error from a
        # stale spec.
        plan = _make_plan(state.params, d, cap)
        # Surgery on params without rebuilding the state leaves master/
        # optimizer shards sized for the OLD tree — and a state built
        # under a different bucket cap has a different shard layout; catch
        # both here with a descriptive error instead of an opaque
        # shard_map shape failure (round-2 advisor finding).
        expected_padded = plan.padded
        actual_padded = int(np.prod(state.pshard.shape))
        if actual_padded != expected_padded:
            raise ValueError(
                f"ZeroTrainState shards were built for a different "
                f"parameter tree or bucket cap: params flatten to "
                f"{plan.total} elements (padded {expected_padded} under "
                f"bucket_cap_bytes={cap}) but pshard holds "
                f"{actual_padded}. After changing either, rebuild the "
                f"state with init_zero_train_state(...) using the same "
                f"model and bucket_cap_bytes as this step instead of "
                f"reusing the old one.")
        if state.residual is not None:
            actual_res = int(np.prod(state.residual.shape))
            if actual_res != expected_padded:
                raise ValueError(
                    f"ZeroTrainState residual was built for a different "
                    f"layout: expected {expected_padded} elements under "
                    f"bucket_cap_bytes={cap}, got {actual_res}. Rebuild "
                    f"the state with init_zero_train_state(...).")
        if stage == 3:
            # Chaos seam for the partition plane: armed as a stage-3
            # step launches its gather-bearing program, so kind=raise
            # surfaces HorovodInternalError to the elastic retry loop
            # exactly where a real gather failure would
            # (docs/fault-injection.md; docs/zero.md).
            _faults.point("zero.gather")
        key = (plan.treedef, plan.shapes,
               tuple(str(dt) for dt in plan.dtypes),
               state.gaccum is None, cap,
               comp.name if comp is not None else None,
               stage, pf)
        if key not in cache:
            opt_specs = _opt_state_specs(optimizer, plan.shard_len,
                                         axis_name)
            gaccum_spec = P() if state.gaccum is None else P(axis_name)
            residual_spec = (None if state.residual is None
                             else P(axis_name))
            # bucket_cap/stage are None here: those arrays travel
            # outside the compiled program (re-attached below), so the
            # device never copies them and the host fetch above stays
            # non-blocking. At stage 3 params are None too — the
            # template is pure metadata; the program works on pshard.
            params_spec = None if stage == 3 else P()
            state_specs = ZeroTrainState(params_spec, P(axis_name),
                                         opt_specs, gaccum_spec, P(), P(),
                                         None, residual_spec, None)
            sharded = _shard_map(
                _build_step_fn(plan, cap, comp, stage, pf), mesh,
                in_specs=(state_specs, P(axis_name), P(axis_name)),
                out_specs=(state_specs, P()),
                check_vma=False)
            cache[key] = jax.jit(
                sharded, donate_argnums=(0,) if donate else ())
        cap_arr = state.bucket_cap
        stage_arr = state.stage
        template = state.params if stage == 3 else None
        inp = state._replace(bucket_cap=None, stage=None)
        if stage == 3:
            inp = inp._replace(params=None)
        new_state, loss = cache[key](inp, images, labels)
        new_state = new_state._replace(bucket_cap=cap_arr, stage=stage_arr)
        if stage == 3:
            new_state = new_state._replace(params=template)
        return new_state, loss

    step.cache = cache  # compiled programs per tree-key (introspection)
    return step
