"""Elastic Keras surface (parity: ``horovod/tensorflow/keras/elastic.py``
and ``horovod/keras/elastic.py`` — one module here, since Keras 3 unified
``keras``/``tf.keras``).

Usage, the reference's elastic-Keras shape
(``examples/elastic/tensorflow_keras_mnist_elastic.py``)::

    import horovod_tpu.keras as hvd
    from horovod_tpu.keras import elastic

    hvd.init()
    model.compile(optimizer=hvd.DistributedOptimizer(opt), loss=...)
    state = elastic.KerasState(model, batch=0, epoch=0)

    @elastic.run
    def train(state):
        model.fit(dataset, steps_per_epoch=steps,
                  epochs=epochs - state.epoch,
                  callbacks=[elastic.CommitStateCallback(state),
                             elastic.UpdateBatchStateCallback(state),
                             elastic.UpdateEpochStateCallback(state)],
                  verbose=verbose)

    train(state)
"""

from __future__ import annotations

from ..tensorflow.elastic import TensorFlowKerasState
from ..tensorflow.elastic import run  # noqa: F401  (elastic retry loop)
from .callbacks import (  # noqa: F401
    CommitStateCallback, UpdateBatchStateCallback, UpdateEpochStateCallback)


class KerasState(TensorFlowKerasState):
    """State of a Keras model + optimizer for elastic training (parity:
    ``tensorflow/keras/elastic.py`` KerasState): snapshots weights on
    ``commit``, restores them after a ``HorovodInternalError``, and
    broadcasts from the coordinator on ``sync``. Extra kwargs (``batch``,
    ``epoch``, ...) become synced attributes driven by the Update*
    callbacks."""
