"""Keras callbacks (parity: ``horovod/keras/callbacks.py``): thin classes
binding the shared ``_keras/callbacks.py`` impls to ``keras.callbacks``.
"""

from __future__ import annotations

import keras

from .. import tensorflow as _hvd_tf
from .._keras import callbacks as _impl
from .._keras import elastic as _elastic_impl


class BroadcastGlobalVariablesCallback(
        _impl.BroadcastGlobalVariablesCallbackImpl, keras.callbacks.Callback):
    def __init__(self, root_rank=0, device=""):
        super().__init__(_hvd_tf, root_rank, device)


class MetricAverageCallback(_impl.MetricAverageCallbackImpl,
                            keras.callbacks.Callback):
    def __init__(self, device=""):
        super().__init__(_hvd_tf, device)


class LearningRateScheduleCallback(_impl.LearningRateScheduleCallbackImpl,
                                   keras.callbacks.Callback):
    def __init__(self, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True, momentum_correction=True,
                 steps_per_epoch=None, initial_lr=None):
        super().__init__(_hvd_tf, multiplier, start_epoch, end_epoch,
                         staircase, momentum_correction, steps_per_epoch,
                         initial_lr)


class LearningRateWarmupCallback(_impl.LearningRateWarmupCallbackImpl,
                                 keras.callbacks.Callback):
    def __init__(self, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0, initial_lr=None):
        super().__init__(_hvd_tf, warmup_epochs, momentum_correction,
                         steps_per_epoch, verbose, initial_lr)


class CommitStateCallback(_elastic_impl.CommitStateCallbackImpl,
                          keras.callbacks.Callback):
    def __init__(self, state, batches_per_commit=1):
        super().__init__(_hvd_tf, state, batches_per_commit)


class UpdateBatchStateCallback(_elastic_impl.UpdateBatchStateCallbackImpl,
                               keras.callbacks.Callback):
    def __init__(self, state):
        super().__init__(_hvd_tf, state)


class UpdateEpochStateCallback(_elastic_impl.UpdateEpochStateCallbackImpl,
                               keras.callbacks.Callback):
    def __init__(self, state):
        super().__init__(_hvd_tf, state)
