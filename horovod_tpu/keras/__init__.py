"""``import horovod_tpu.keras as hvd`` — Keras binding.

Capability parity with the reference's ``horovod/keras/__init__.py``:
init/rank/size family, ``DistributedOptimizer``, ``broadcast_variables``,
``allreduce``/``allgather``/``broadcast`` on host values, ``load_model``
with distributed-optimizer reconstruction, and the callbacks package.
Under Keras 3 this module and ``horovod_tpu.tensorflow.keras`` share the
same implementation (the reference keeps two thin wrappers over
``horovod/_keras/`` for keras-vs-tf.keras; Keras 3 unified them).
"""

from __future__ import annotations

import keras

from .. import tensorflow as _hvd_tf
from .. import _keras as _impl
from ..tensorflow import (  # noqa: F401
    Adasum, Average, Compression, Max, Min, ReduceOp, Sum, allgather_object,
    barrier, broadcast_object, broadcast_object_fn, broadcast_variables,
    ccl_built, cross_rank, cross_size, ddl_built, gloo_built, gloo_enabled,
    init, is_initialized, join, local_rank, local_size, mpi_built,
    mpi_enabled, mpi_threads_supported, nccl_built, rank, shutdown, size)
from . import callbacks, elastic  # noqa: F401


def DistributedOptimizer(optimizer, name=None,
                         device_dense="", device_sparse="",
                         compression=Compression.none,
                         sparse_as_dense=False, op=Average):
    """Wrap a Keras optimizer so gradients are allreduced before applying
    (parity: ``keras/__init__.py`` → ``_keras/__init__.py:23``)."""
    return _impl.create_distributed_optimizer(
        _hvd_tf, keras, optimizer, name=name, compression=compression,
        sparse_as_dense=sparse_as_dense, op=op)


def allreduce(value, name=None, average=True):
    """Allreduce a host value (parity: ``keras/__init__.py`` allreduce)."""
    return _impl.allreduce(_hvd_tf, None, value, name, average)


def allgather(value, name=None):
    return _impl.allgather(_hvd_tf, None, value, name)


def broadcast(value, root_rank, name=None):
    return _impl.broadcast(_hvd_tf, None, value, root_rank, name)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a model saved by any rank, wrapping its optimizer in
    ``DistributedOptimizer`` (parity: ``keras/__init__.py`` load_model)."""
    model = keras.models.load_model(filepath,
                                    custom_objects=custom_objects)
    opt = getattr(model, "optimizer", None)
    if opt is not None:
        model.optimizer = DistributedOptimizer(opt,
                                               compression=compression)
    return model
