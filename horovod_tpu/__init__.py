"""horovod_tpu — a TPU-native distributed training framework.

Capability parity with Horovod (reference: tgravescs/horovod v0.19.2),
re-architected for TPU: XLA collectives over ICI/DCN replace NCCL/MPI/Gloo,
``jax.sharding.Mesh`` topology replaces MPI rank discovery, and the
coordination control plane lives in a native runtime library.

Typical use (JAX-native, mirrors ``import horovod.torch as hvd`` scripts)::

    import horovod_tpu as hvd

    hvd.init()
    # eager API
    summed = hvd.allreduce(per_chip_grads, op=hvd.Sum)
    # in-jit API (inside shard_map/pjit over hvd.mesh())
    grads = hvd.xla.allreduce(grads, op=hvd.Average)

Framework bindings live in ``horovod_tpu.torch``, ``horovod_tpu.tensorflow``,
``horovod_tpu.keras`` (import the one matching your framework, as with the
reference).
"""

from typing import List, Optional

from .version import __version__  # noqa: F401
from .common import exceptions  # noqa: F401
from .common.exceptions import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from .common.state import (  # noqa: F401
    ccl_built,
    cross_rank,
    cross_size,
    ddl_built,
    gloo_built,
    gloo_enabled,
    hierarchical_mesh,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mesh,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    shutdown,
    size,
    tpu_available,
    xla_built,
)
from .common.state import global_state as _global_state
from .common.compression import Compression  # noqa: F401
from .ops import xla  # noqa: F401
from .ops.xla import Adasum, Average, Max, Min, ReduceOp, Sum  # noqa: F401


def _engine():
    st = _global_state()
    if not st.initialized or st.engine is None:
        from .common.exceptions import NotInitializedError

        raise NotInitializedError("collective API")
    return st.engine


# ---- eager async API (parity: hvd.allreduce_async_/poll/synchronize) -------


def allreduce_async(tensor, name: Optional[str] = None, op: int = Average,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0) -> int:
    """Default op is Average, same as the sync form — the reference's
    async flavors average by default too (``torch/mpi_ops.py:91-129``)."""
    return _engine().allreduce_async(
        tensor, name=name, op=op, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor)


def allreduce(tensor, name: Optional[str] = None, op: int = Average,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Eager allreduce. Default op is Average, matching the reference's
    Python-level default (``torch/mpi_ops.py:91-129``)."""
    return synchronize(allreduce_async(
        tensor, name=name, op=op, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor))


def grouped_allreduce_async(tensors: List, name: Optional[str] = None,
                            op: int = Average, prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0) -> int:
    return _engine().grouped_allreduce_async(
        tensors, name=name, op=op, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor)


def grouped_allreduce(tensors: List, name: Optional[str] = None,
                      op: int = Average, prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0):
    return synchronize(grouped_allreduce_async(
        tensors, name=name, op=op, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor))


def allgather_async(tensor, name: Optional[str] = None) -> int:
    return _engine().allgather_async(tensor, name=name)


def allgather(tensor, name: Optional[str] = None):
    return synchronize(allgather_async(tensor, name=name))


def broadcast_async(tensor, root_rank: int, name: Optional[str] = None) -> int:
    return _engine().broadcast_async(tensor, root_rank, name=name)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    return synchronize(broadcast_async(tensor, root_rank, name=name))


def reducescatter_async(tensor, name: Optional[str] = None, op: int = Sum) -> int:
    return _engine().reducescatter_async(tensor, name=name, op=op)


def reducescatter(tensor, name: Optional[str] = None, op: int = Sum):
    return synchronize(reducescatter_async(tensor, name=name, op=op))


def alltoall_async(tensor, name: Optional[str] = None) -> int:
    return _engine().alltoall_async(tensor, name=name)


def alltoall(tensor, name: Optional[str] = None):
    return synchronize(alltoall_async(tensor, name=name))


def poll(handle: int) -> bool:
    """True if the collective behind ``handle`` has completed."""
    return _engine().poll(handle)


def synchronize(handle: int):
    """Block until the collective completes and return its result."""
    return _engine().synchronize(handle)


def barrier():
    """Synchronize all participants (capability extension; the reference
    gained hvd.barrier() post-0.19)."""
    _engine().barrier()


def stall_report() -> str:
    """Drain and return the native stall inspector's accumulated warnings
    (reference ``stall_inspector.cc``: the coordinator reports tensors
    some ranks submitted and others never did — the classic desync
    signature). ALWAYS returns ``str``: the empty string — never None,
    never an exception — when nothing stalled, when ``hvd.init()``
    hasn't run, or when the native core is absent (pure-XLA direct
    mode); the shape is pinned by tests/test_metrics.py.

    Consuming a non-empty report also records a ``STALL_WARNING`` instant
    in the timeline (when one is active), so stalls line up with the
    collectives that caused them in post-mortems."""
    core = _native_core()
    if core is None:
        return ""
    st = _global_state()
    report = core.stall_report()
    if report and st.initialized and st.timeline is not None:
        from .common import timeline as _timeline_mod

        st.timeline.instant(_timeline_mod.STALL_WARNING,
                            {"report": report})
    return report


def liveness_report() -> str:
    """Drain and return the native liveness plane's accumulated events
    (docs/liveness.md): ``SUSPECT``/``EVICT``/``DRAIN``/``RECOVER``
    lines from the controller's heartbeat state machine, one per
    transition. ALWAYS returns ``str``: the empty string — never None,
    never an exception — when the plane is disabled
    (``HOROVOD_HEARTBEAT_MS=0``, the default), when nothing happened,
    when ``hvd.init()`` hasn't run, or when the native core is absent
    (pure-XLA direct mode); the shape is pinned by
    tests/test_metrics.py. Like ``stall_report()``, reading consumes —
    the drain rides the unified metrics snapshot (docs/metrics.md)."""
    core = _native_core()
    if core is None:
        return ""
    return core.liveness_report()


def _native_core():
    """The process's live NativeCore: the XLA engine's when one runs,
    else the host (process-rank) world's. None in pure-direct mode.
    (One rule, owned by common/metrics.py — every observability surface
    resolves the core identically.)"""
    from .common import metrics as _metrics

    return _metrics.live_native_core()


def metrics() -> dict:
    """The unified metrics snapshot (docs/metrics.md):
    ``{"python": {...}, "native": {...} | None}``.

    ``python`` holds the Python-plane counters (Retrier retries, fault
    injections, shm/stripe fallback armings, elastic evictions/drains);
    ``native`` is the registry snapshot from the single
    ``hvd_metrics_snapshot`` getter — traffic/control counters, the
    log2 latency histograms (enqueue→negotiated→executed per op class,
    background-cycle duration, coordinator per-rank gather wait,
    cross/shm/stripe leg timings, per-step rank skew), and the
    straggler detector's state — or None before init / in pure-XLA
    direct mode. Reading drains pending STRAGGLER_WARNING events into
    ``native["straggler"]["events"]`` and mirrors them as timeline
    instants when a timeline is active; counters and histograms are
    cumulative for the world and unaffected by reads."""
    from .common import metrics as _metrics

    return _metrics.snapshot()


def metrics_report() -> str:
    """Human-readable rendering of :func:`metrics` — counters, each
    non-empty histogram with approximate p50/p99 (log2 buckets), and
    the straggler state. Empty-safe: always returns a string, with or
    without a native core."""
    from .common import metrics as _metrics

    return _metrics.report_text()


def ring_traffic() -> dict:
    """Host data-plane traffic accounting with the local/cross/shm split.

    Returns a dict with ``bytes_sent`` (every payload byte this process
    moved on the host data plane, TCP and shm), ``local_bytes`` (TCP to
    same-host peers — the loopback legs of the hierarchical collectives
    when the shm transport is off or fell back), ``cross_bytes`` (to
    peers on other hosts: the scarce budget the two-level paths
    minimize; see ``docs/hierarchical.md``), ``shm_bytes`` (payload
    moved through the shared-memory transport's rings with zero socket
    syscalls — with shm active the local leg lives here and
    ``local_bytes`` collapses to ~0; ``docs/shm-transport.md``),
    ``shm`` (True when this rank's shm transport is live — the
    transport choice), ``stripe_bytes`` (payload that rode the striped
    cross-host transport — a subset of ``cross_bytes``, which stays
    byte-identical to the single-socket path; see
    ``docs/cross-transport.md``), ``stripes`` (the stripe count in
    active use: K once a leader pair carries striped traffic, 0 with
    striping off or fully fallen back), the effective
    ``hierarchical_allreduce``/``hierarchical_allgather`` host-plane
    dispatch (autotuner-synced value when present, else the env
    config), and ``tuned`` (True once an autotuner decision reached
    this rank). All zeros/False before init or in pure-XLA direct
    mode."""
    core = _native_core()
    empty = {"bytes_sent": 0, "local_bytes": 0, "cross_bytes": 0,
             "shm_bytes": 0, "shm": False,
             "stripe_bytes": 0, "stripes": 0,
             "hierarchical_allreduce": False,
             "hierarchical_allgather": False, "tuned": False}
    if core is None:
        return empty
    # One native call through the unified snapshot (docs/metrics.md)
    # instead of nine per-counter getters — the consistency invariant
    # (bytes_sent == local + cross + shm) is asserted against this same
    # document in tests/test_metrics.py.
    snap = core.metrics_snapshot()
    if not snap:
        return empty
    c = snap.get("counters", {})
    flags = int(c.get("host_hier_flags", 0))
    return {
        "bytes_sent": int(c.get("bytes_sent", 0)),
        "local_bytes": int(c.get("local_bytes", 0)),
        "cross_bytes": int(c.get("cross_bytes", 0)),
        "shm_bytes": int(c.get("shm_bytes", 0)),
        "shm": bool(c.get("shm_active", 0)),
        "stripe_bytes": int(c.get("stripe_bytes", 0)),
        "stripes": int(c.get("stripes", 0)),
        "hierarchical_allreduce": bool(flags & 1),
        "hierarchical_allgather": bool(flags & 2),
        "tuned": int(c.get("tuned_hier_flags", -1)) >= 0,
    }


def join() -> int:
    """Graceful departure (parity: ``hvd.join()``, ``operations.cc:937-961``).

    A process that calls ``join()`` stops submitting tensors and contributes
    zeros to the remaining processes' allreduces until every process has
    joined (allgather/broadcast while a rank is joined raise an error, as in
    the reference). Returns the last joined participant's global rank. In
    single-controller SPMD mode every chip is driven by one live process, so
    join degenerates to a barrier.
    """
    st = _global_state()
    st.last_joined = _engine().join()
    return st.last_joined


# ---- high-level JAX-native helpers -----------------------------------------


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a pytree of parameters from ``root_rank`` to all
    participants (parity: ``torch/functions.py:30-226``). In SPMD
    single-controller mode the tree is already consistent process-wide; the
    broadcast runs across processes when there are several."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    out = [broadcast(l, root_rank, name=f"bcast.param.{i}")
           for i, l in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None):
    """Broadcast an arbitrary picklable object (parity:
    ``torch/functions.py`` broadcast_object)."""
    import pickle

    import numpy as np

    st = _global_state()
    if st.process_count == 1:
        return obj  # single controller: nothing to do
    payload = pickle.dumps(obj) if st.process_index == root_rank else b""
    n = int(np.asarray(
        synchronize(allreduce_async(
            np.asarray(len(payload), dtype=np.int64), op=Sum,
            name=(name or "bcast.obj") + ".len"))).max())
    buf = np.zeros(n, dtype=np.uint8)
    if st.process_index == root_rank:
        buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    buf = broadcast(buf, root_rank, name=(name or "bcast.obj") + ".data")
    return pickle.loads(bytes(np.asarray(buf)))


from . import elastic  # noqa: E402,F401


class DistributedOptimizer:
    """Optax gradient-transformation wrapper that averages gradients across
    the mesh (parity: ``hvd.DistributedOptimizer``; see
    ``horovod_tpu.opt`` for the full implementation)."""

    def __new__(cls, optimizer, **kwargs):
        from .opt import DistributedOptimizer as _impl

        return _impl(optimizer, **kwargs)
