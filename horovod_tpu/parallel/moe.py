"""Mixture-of-Experts with expert parallelism (top-1 Switch or top-2
GShard routing).

Expert parallelism rides the ``dp`` mesh axis (the standard GShard/Switch
placement): each dp group member owns ``E / ep`` experts; tokens are
delivered to their expert's owner with a single ``lax.all_to_all`` over the
axis and returned the same way. Routing uses static capacity
(``capacity_factor``) so every shape is compile-time constant — the XLA
requirement that rules out the reference-style dynamic dispatch.

``top_k=2`` follows the GShard recipe: gates renormalized over the two
picks, first choices take capacity priority over every second choice,
and the optional auxiliary load-balance loss (``return_aux=True``) is
the Switch formulation E * sum_e(f_e * P_e) — 1.0 at perfect balance.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size


def init_moe_params(rng, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = 1.0 / jnp.sqrt(d_model)
    scale_out = 1.0 / jnp.sqrt(d_ff)
    return {
        "gate": (jax.random.normal(k1, (d_model, n_experts)) * scale_in
                 ).astype(jnp.float32),
        "w_in": (jax.random.normal(k2, (n_experts, d_model, d_ff)) * scale_in
                 ).astype(dtype),
        "w_out": (jax.random.normal(k3, (n_experts, d_ff, d_model)) * scale_out
                  ).astype(dtype),
    }


def moe_layer(x, params, axis_name: str = "dp", capacity_factor: float = 1.25,
              top_k: int = 1, return_aux: bool = False):
    """Top-k MoE over tokens. x: [T, d] (local tokens); params['w_in']:
    [E_local, d, f] — the *local* expert shard when run under shard_map
    with the expert dim sharded over ``axis_name``.

    ``top_k``: 1 (Switch) or 2 (GShard; gates renormalized over the two
    picks, first choices win capacity). ``return_aux``: also return the
    load-balance auxiliary loss (scalar, ~1.0 when balanced) for the
    caller to weight into the training loss.

    Returns [T, d], or ([T, d], aux) with ``return_aux``.
    """
    ep = _axis_size(axis_name)
    T, d = x.shape
    e_local = params["w_in"].shape[0]
    E = e_local * ep
    if not 1 <= top_k <= E:
        raise ValueError(f"top_k={top_k} must be in [1, {E}]")

    # --- routing (fp32) -----------------------------------------------------
    logits = x.astype(jnp.float32) @ params["gate"]  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    topg, topi = lax.top_k(probs, top_k)  # [T, k]
    if top_k > 1:
        topg = topg / jnp.sum(topg, axis=-1, keepdims=True)

    # Virtual-token view, choice-major ([all 1st choices; all 2nd ...]):
    # the capacity cumsum below then gives every first choice priority
    # over any second choice (the GShard policy).
    vidx = topi.T.reshape(-1)   # [k*T]
    vgate = topg.T.reshape(-1)  # [k*T]

    capacity = max(1, int(capacity_factor * top_k * T / E))
    onehot = jax.nn.one_hot(vidx, E, dtype=jnp.float32)  # [kT, E]
    # position of each virtual token within its expert's queue
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [kT, E]
    keep = (pos < capacity) * onehot  # [kT, E] tokens within capacity
    pos = jnp.sum(pos * keep, axis=-1).astype(jnp.int32)  # [kT]
    kept = jnp.sum(keep, axis=-1) > 0  # [kT]

    # dispatch tensor [kT, E, C]
    dispatch = (keep[:, :, None]
                * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :])
    # expert input buffers [E, C, d]; each token's features enter once
    # per surviving choice
    x32 = jnp.tile(x.astype(jnp.float32), (top_k, 1))  # [kT, d]
    buffers = jnp.einsum("tec,td->ecd", dispatch, x32)

    # --- all_to_all: deliver each expert's buffer to its owner --------------
    # [E, C, d] -> [ep, e_local, C, d]; exchange over axis -> every member
    # ends with its local experts' tokens from all peers: [ep, e_local, C, d]
    buffers = buffers.reshape(ep, e_local, capacity, d)
    recv = lax.all_to_all(buffers, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)  # [ep, e_local, C, d]
    # merge peer dim into capacity: [e_local, ep*C, d]
    recv = recv.transpose(1, 0, 2, 3).reshape(e_local, ep * capacity, d)

    # --- expert FFN ---------------------------------------------------------
    h = jnp.einsum("ecd,edf->ecf", recv, params["w_in"].astype(jnp.float32))
    h = jax.nn.gelu(h, approximate=False)
    out = jnp.einsum("ecf,efd->ecd", h, params["w_out"].astype(jnp.float32))

    # --- return trip --------------------------------------------------------
    out = out.reshape(e_local, ep, capacity, d).transpose(1, 0, 2, 3)
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)  # [ep, e_local, C, d]
    back = back.reshape(E, capacity, d)

    # combine: weight each choice's returned features by its gate, then
    # sum the k choices per real token: [kT, d] -> [k, T, d] -> [T, d]
    combined = jnp.einsum("tec,ecd->td", dispatch, back)
    y = (combined * (vgate * kept)[:, None]).reshape(top_k, T, d).sum(0)
    y = y.astype(x.dtype)
    if not return_aux:
        return y
    # Switch aux loss: E * sum_e(fraction of tokens whose FIRST choice is
    # e  *  mean router prob on e). 1.0 at perfect balance; grows as
    # routing collapses onto few experts. The token means are averaged
    # over the expert-parallel axis so every member returns the same
    # (global) scalar.
    first = jax.nn.one_hot(topi[:, 0], E, dtype=jnp.float32)  # [T, E]
    f = lax.pmean(jnp.mean(first, axis=0), axis_name)
    p = lax.pmean(jnp.mean(probs, axis=0), axis_name)
    aux = E * jnp.sum(f * p)
    return y, aux
