"""Mixture-of-Experts with expert parallelism (Switch-style top-1 routing).

Expert parallelism rides the ``dp`` mesh axis (the standard GShard/Switch
placement): each dp group member owns ``E / ep`` experts; tokens are
delivered to their expert's owner with a single ``lax.all_to_all`` over the
axis and returned the same way. Routing uses static capacity
(``capacity_factor``) so every shape is compile-time constant — the XLA
requirement that rules out the reference-style dynamic dispatch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def init_moe_params(rng, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = 1.0 / jnp.sqrt(d_model)
    scale_out = 1.0 / jnp.sqrt(d_ff)
    return {
        "gate": (jax.random.normal(k1, (d_model, n_experts)) * scale_in
                 ).astype(jnp.float32),
        "w_in": (jax.random.normal(k2, (n_experts, d_model, d_ff)) * scale_in
                 ).astype(dtype),
        "w_out": (jax.random.normal(k3, (n_experts, d_ff, d_model)) * scale_out
                  ).astype(dtype),
    }


def moe_layer(x, params, axis_name: str = "dp", capacity_factor: float = 1.25):
    """Top-1 MoE over tokens. x: [T, d] (local tokens); params['w_in']:
    [E_local, d, f] — the *local* expert shard when run under shard_map
    with the expert dim sharded over ``axis_name``.

    Returns [T, d].
    """
    ep = lax.axis_size(axis_name)
    T, d = x.shape
    e_local = params["w_in"].shape[0]
    E = e_local * ep

    # --- routing (fp32) -----------------------------------------------------
    logits = x.astype(jnp.float32) @ params["gate"]  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    capacity = max(1, int(capacity_factor * T / E))
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, E]
    # position of each token within its expert's queue
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [T, E]
    keep = (pos < capacity) * onehot  # [T, E] tokens within capacity
    pos = jnp.sum(pos * keep, axis=-1).astype(jnp.int32)  # [T]
    kept = jnp.sum(keep, axis=-1) > 0  # [T]

    # dispatch tensor [T, E, C]
    dispatch = (keep[:, :, None]
                * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :])
    # expert input buffers [E, C, d]
    buffers = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))

    # --- all_to_all: deliver each expert's buffer to its owner --------------
    # [E, C, d] -> [ep, e_local, C, d]; exchange over axis -> every member
    # ends with its local experts' tokens from all peers: [ep, e_local, C, d]
    buffers = buffers.reshape(ep, e_local, capacity, d)
    recv = lax.all_to_all(buffers, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)  # [ep, e_local, C, d]
    # merge peer dim into capacity: [e_local, ep*C, d]
    recv = recv.transpose(1, 0, 2, 3).reshape(e_local, ep * capacity, d)

    # --- expert FFN ---------------------------------------------------------
    h = jnp.einsum("ecd,edf->ecf", recv, params["w_in"].astype(jnp.float32))
    h = jax.nn.gelu(h, approximate=False)
    out = jnp.einsum("ecf,efd->ecd", h, params["w_out"].astype(jnp.float32))

    # --- return trip --------------------------------------------------------
    out = out.reshape(e_local, ep, capacity, d).transpose(1, 0, 2, 3)
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)  # [ep, e_local, C, d]
    back = back.reshape(E, capacity, d)

    # combine: [T, d]
    combined = jnp.einsum("tec,ecd->td", dispatch, back)
    y = combined * (gate * kept)[:, None]
    return y.astype(x.dtype)
