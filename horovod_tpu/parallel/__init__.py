from . import mesh  # noqa: F401
from . import ulysses  # noqa: F401
