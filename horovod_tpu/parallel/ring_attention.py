"""Ring attention: context parallelism for long sequences over ICI.

First-class long-context support (absent from the reference, SURVEY §5
"Long-context / sequence parallelism"): the sequence is sharded across the
``sp`` mesh axis; each chip holds its Q block while K/V blocks rotate around
the ring via ``lax.ppermute``, with online-softmax (flash-style) accumulation
so the full attention matrix never materializes. Communication of the next
K/V block overlaps with compute of the current one under XLA's async
collective-permute scheduling on ICI.

Numerics: log-sum-exp streaming accumulation in float32 regardless of input
dtype — the same max-shifted accumulation flash attention uses.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size

NEG_INF = -1e30


def _block_attend(q, k, v, m, l, o, mask):
    """One flash-attention block update.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; m/l: [B, H, Tq]; o: [B, Tq, H, D]
    mask: [Tq, Tk] additive (0 or NEG_INF), or None.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = s + mask[None, None, :, :]
    m_blk = jnp.max(s, axis=-1)  # [B,H,Tq]
    m_new = jnp.maximum(m, m_blk)
    # Guard fully-masked blocks: exp(NEG_INF - NEG_INF) must not be 1.
    alive = m_new > NEG_INF / 2
    corr = jnp.where(alive, jnp.exp(m - m_new), 1.0)
    # Masked entries have s == NEG_INF; when a whole tile is masked
    # m_new == NEG_INF too and exp(s - m_new) would be exp(0) = 1, so zero
    # them explicitly instead of relying on underflow.
    p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[..., None]))
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _expand_kv(k, v, g):
    """Expand GQA K/V from Hkv to H = g * Hkv query heads (consecutive
    repeat: query head j reads KV head j // g). The backward adjoint is
    the matching group-sum, dk.reshape(B, T, Hkv, g, D).sum(3) — keep
    the two in lockstep."""
    if g <= 1:
        return k, v
    return jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2)


def _ring_fwd_pass(q, k, v, seg, axis_name: str, causal: bool,
                   window=None):
    """The forward ring: flash block kernel per rotating K/V block +
    online-softmax merge. Returns (o in q.dtype, lse f32 [B, H, Tq]) —
    lse is the backward pass's residual. ``seg``: optional int32 [B, T]
    local segment ids (packed sequences); the K-side ids rotate with
    their K/V block."""
    sp = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    g = H // k.shape[2]  # GQA group size (1 = plain multi-head)
    m = jnp.full((B, H, Tq), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((B, H, Tq), dtype=jnp.float32)
    o = jnp.zeros((B, Tq, H, D), dtype=jnp.float32)

    fwd_perm = [(i, (i + 1) % sp) for i in range(sp)]

    def body(carry, step):
        m, l, o, k_cur, v_cur, kseg_cur = carry
        # k_cur originated at rank (my - step) mod sp. Each block's local
        # attention state comes from the flash kernel (Pallas on TPU, XLA
        # elsewhere); the cross-block merge below is the standard
        # online-softmax combine. GQA K/V travel the ring at their
        # reduced head width and expand only for the kernel call.
        from ..ops.pallas_attention import flash_attention_block

        k_blk = (my - step) % sp
        k_full, v_full = _expand_kv(k_cur, v_cur, g)
        acc_b, m_b, l_b = flash_attention_block(
            q, k_full, v_full, q_off=my * Tq,
            k_off=k_blk * k_cur.shape[1],
            causal=causal, q_segment_ids=seg,
            k_segment_ids=None if seg is None else kseg_cur,
            window=window)
        m_new = jnp.maximum(m, m_b)                       # [B,H,Tq]
        alive = m_new > NEG_INF / 2
        c_old = jnp.where(alive, jnp.exp(m - m_new), 1.0)
        c_blk = jnp.where(alive & (m_b > NEG_INF / 2),
                          jnp.exp(m_b - m_new), 0.0)
        l = l * c_old + l_b * c_blk
        o = (o * c_old.transpose(0, 2, 1)[..., None] +
             acc_b * c_blk.transpose(0, 2, 1)[..., None])
        k_nxt = lax.ppermute(k_cur, axis_name, fwd_perm)
        v_nxt = lax.ppermute(v_cur, axis_name, fwd_perm)
        kseg_nxt = (kseg_cur if seg is None else
                    lax.ppermute(kseg_cur, axis_name, fwd_perm))
        return (m_new, l, o, k_nxt, v_nxt, kseg_nxt), None

    kseg0 = jnp.zeros((B, Tq), jnp.int32) if seg is None else seg
    (m, l, o, _, _, _), _ = lax.scan(
        body, (m, l, o, k, v, kseg0), jnp.arange(sp))
    o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    # Dead rows (no visible key) take a huge POSITIVE lse so the
    # backward's exp(s - lse) underflows to zero for them.
    lse = jnp.where(l > 0.0, m + jnp.log(jnp.maximum(l, 1e-30)), -NEG_INF)
    # Anchor the axis index in the live output dataflow: when the mask
    # path doesn't consume it (causal=False, no window/segments), some
    # XLA versions leave the dead partition-id where the SPMD partitioner
    # rejects it ("PartitionId instruction is not supported for SPMD
    # partitioning", jaxlib 0.4.x CPU). A zero-weight use costs nothing
    # and keeps the op inside the manual region.
    o = o + (my * 0).astype(o.dtype)
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _ring_core(q, k, v, seg, axis_name, causal, window):
    return _ring_fwd_pass(q, k, v, seg, axis_name, causal, window)[0]


def _ring_vjp_fwd(q, k, v, seg, axis_name, causal, window):
    o, lse = _ring_fwd_pass(q, k, v, seg, axis_name, causal, window)
    return o, (q, k, v, seg, o, lse)


def _ring_vjp_bwd(axis_name, causal, window, res, do):
    """Backward ring pass (the ring-attention paper's second rotation):
    K/V blocks rotate again, each visit computes that block's (dq, dk, dv)
    through the flash backward kernels with the GLOBAL lse/delta
    residuals, and dK/dV accumulators travel with their blocks — after sp
    rotations every gradient is home. Twice the forward's ppermute bytes
    (k, v, dk, dv per step), the standard ring-backward cost."""
    from ..ops.pallas_attention import flash_attention_block_grads

    q, k, v, seg, o, lse = res
    sp = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    Hkv = k.shape[2]
    g = H // Hkv
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).transpose(0, 2, 1)            # [B, H, Tq]

    fwd_perm = [(i, (i + 1) % sp) for i in range(sp)]
    dq0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    dk0 = jnp.zeros((B, Tk, Hkv, D), jnp.float32)
    dv0 = jnp.zeros((B, Tk, Hkv, D), jnp.float32)

    def body(carry, step):
        dq, dk, dv, k_cur, v_cur, kseg_cur = carry
        k_blk = (my - step) % sp
        k_full, v_full = _expand_kv(k_cur, v_cur, g)
        dq_b, dk_b, dv_b = flash_attention_block_grads(
            q, k_full, v_full, do, lse, delta,
            q_off=my * Tq, k_off=k_blk * Tk, causal=causal,
            q_segment_ids=seg,
            k_segment_ids=None if seg is None else kseg_cur,
            window=window)
        if g > 1:
            # repeat's transpose: sum each query-head group back onto
            # its shared K/V head, so dK/dV accumulate (and rotate) at
            # the reduced width.
            dk_b = dk_b.reshape(B, Tk, Hkv, g, D).sum(3)
            dv_b = dv_b.reshape(B, Tk, Hkv, g, D).sum(3)
        dq = dq + dq_b
        dk = dk + dk_b
        dv = dv + dv_b
        k_nxt = lax.ppermute(k_cur, axis_name, fwd_perm)
        v_nxt = lax.ppermute(v_cur, axis_name, fwd_perm)
        kseg_nxt = (kseg_cur if seg is None else
                    lax.ppermute(kseg_cur, axis_name, fwd_perm))
        dk = lax.ppermute(dk, axis_name, fwd_perm)
        dv = lax.ppermute(dv, axis_name, fwd_perm)
        return (dq, dk, dv, k_nxt, v_nxt, kseg_nxt), None

    kseg0 = jnp.zeros((B, Tq), jnp.int32) if seg is None else seg
    (dq, dk, dv, _, _, _), _ = lax.scan(
        body, (dq0, dk0, dv0, k, v, kseg0), jnp.arange(sp))
    from ..ops.pallas_attention import int_cotangent

    # Same partition-id anchor as the forward pass (see _ring_fwd_pass).
    dq = dq + (my * 0).astype(dq.dtype)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            int_cotangent(seg))


_ring_core.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = True,
                   segment_ids=None, window=None):
    """Context-parallel attention. q/k/v: [B, T_local, H, D] per chip.

    Every K/V block's local attention runs through the flash kernel
    (Pallas/Mosaic on TPU, XLA elsewhere — ``ops.pallas_attention``):
    sp == 1 is a single full-attention kernel call; sp > 1 calls the
    block-state kernel once per ring step and merges blocks with the
    online-softmax combine, while ``ppermute`` rotates K/V so transfer
    overlaps compute under XLA's collective scheduling. Training's
    backward is a second ring pass through the flash backward kernels
    (``_ring_vjp_bwd``) — no attention recompute through XLA.

    ``segment_ids`` (int [B, T_local], sequence-sharded like q):
    packed-sequence masking — tokens attend only within their segment;
    the K-side ids rotate around the ring with their K/V block and
    stream into the flash kernels as extra id tiles.
    """
    sp = _axis_size(axis_name)
    if sp == 1:
        from ..ops.pallas_attention import flash_attention

        k, v = _expand_kv(k, v, q.shape[2] // k.shape[2])
        return flash_attention(q, k, v, causal=causal,
                               q_segment_ids=segment_ids,
                               k_segment_ids=segment_ids, window=window)
    if segment_ids is not None:
        segment_ids = jnp.asarray(segment_ids, jnp.int32)
    return _ring_core(q, k, v, segment_ids, axis_name, causal, window)


def local_flash_attention(q, k, v, causal: bool = True):
    """Single-device flash-accumulated attention (reference oracle for
    tests and the sp=1 fast path)."""
    B, T, H, D = q.shape
    m = jnp.full((B, H, T), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((B, H, T), dtype=jnp.float32)
    o = jnp.zeros((B, T, H, D), dtype=jnp.float32)
    mask = None
    if causal:
        iq = jnp.arange(T)[:, None]
        ik = jnp.arange(T)[None, :]
        mask = jnp.where(iq >= ik, 0.0, NEG_INF)
    m, l, o = _block_attend(q, k, v, m, l, o, mask)
    o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o.astype(q.dtype)
