"""Parallelism mesh construction: dp / pp / sp / tp axes over TPU devices.

The reference framework is data-parallel only (SURVEY §2.5); this module is
the TPU-native extension point it anticipates: a multi-axis
``jax.sharding.Mesh`` where

- ``dp``: data parallelism (the Horovod-parity axis). Expert parallelism
  (ep) rides this axis, as in Switch/GShard-style MoE systems.
- ``pp``: pipeline stages (GPipe-style SPMD schedule,
  ``horovod_tpu.parallel.pipeline``).
- ``sp``: sequence/context parallelism — ring attention shards the sequence
  across this axis (``horovod_tpu.parallel.ring_attention``).
- ``tp``: tensor parallelism (Megatron-style sharded attention heads and
  MLP); Megatron *sequence parallelism* (norm/residual regions sharded over
  the sequence) also rides this axis.

Axis order is outer-to-inner by communication intensity: tp (most chatty)
innermost so it lands on the shortest ICI rings; dp outermost so gradient
allreduce can cross DCN.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

AXES = ("dp", "pp", "sp", "tp")


def factor_devices(n: int, tp: Optional[int] = None, pp: Optional[int] = None,
                   sp: Optional[int] = None,
                   dp: Optional[int] = None) -> Dict[str, int]:
    """Choose axis sizes multiplying to ``n``.

    Unspecified axes are filled greedily with powers of two, preferring
    tp, then pp, then sp, and giving the remainder to dp — tiny-mesh
    defaults for dry runs; real jobs pass sizes explicitly.
    """
    fixed = {"tp": tp, "pp": pp, "sp": sp, "dp": dp}
    remaining = n
    for name, v in fixed.items():
        if v is not None:
            if remaining % v != 0:
                raise ValueError(f"{name}={v} does not divide {remaining}")
            remaining //= v
    for name in ("tp", "pp", "sp"):
        if fixed[name] is None:
            fixed[name] = 2 if remaining % 2 == 0 and remaining > 1 else 1
            remaining //= fixed[name]
    if fixed["dp"] is None:
        fixed["dp"] = remaining
        remaining = 1
    if remaining != 1:
        raise ValueError(
            f"axis sizes {fixed} do not use all {n} devices")
    return fixed


def build_parallel_mesh(devices: Sequence, tp: Optional[int] = None,
                        pp: Optional[int] = None, sp: Optional[int] = None,
                        dp: Optional[int] = None):
    """Build a 4-axis ('dp','pp','sp','tp') mesh over ``devices``."""
    from jax.sharding import Mesh

    n = len(devices)
    sizes = factor_devices(n, tp=tp, pp=pp, sp=sp, dp=dp)
    arr = np.array(devices, dtype=object).reshape(
        sizes["dp"], sizes["pp"], sizes["sp"], sizes["tp"])
    return Mesh(arr, AXES)
