"""All-to-all sequence parallelism (Ulysses-style context parallelism).

The second first-class long-context strategy next to
:mod:`~horovod_tpu.parallel.ring_attention` (SURVEY §5 "Long-context /
sequence parallelism"; absent from the reference, which is DP-only).
Where ring attention keeps the sequence sharded and rotates K/V blocks
around the ``sp`` ring (sp - 1 ppermute steps, compute/transfer
overlapped), the all-to-all strategy re-shards once: an ``all_to_all``
swaps the sequence sharding for a head sharding, every chip runs plain
flash attention over the FULL sequence for its H/sp heads, and a second
``all_to_all`` swaps back.

Trade-offs (why both exist):

- **Bytes on the fabric**: all-to-all moves each Q/K/V element once
  (3 + 1 collectives of (sp-1)/sp of the local block each) — about half
  the ring's 2 x (sp-1) K/V block rotations. Better when attention
  compute is too short to hide the ring's rotations behind.
- **Constraint**: needs ``heads % sp == 0`` (after tp sharding). The
  ring has no head constraint and its working set stays T_local — the
  only option when the full sequence doesn't fit one chip's HBM.
- **Kernel shape**: local attention sees the full sequence, so the
  Pallas flash kernel runs at its natural tiling with a plain causal
  mask — no cross-block online-softmax merge.

Autodiff: ``lax.all_to_all`` is linear and differentiable; the backward
pass is the mirrored pair of all-to-alls around the flash backward — no
custom VJP needed.
"""

from __future__ import annotations

from jax import lax

from ..common.compat import axis_size as _axis_size


def gather_segment_ids(segment_ids, axis_name: str = "sp"):
    """All-gather sequence-sharded segment ids to [B, T_global].

    The gather is loop-invariant across decoder layers; callers running
    attention inside a layer scan (models/transformer.py) hoist it by
    gathering once and passing ``gathered_segment_ids`` — XLA does not
    lift collectives out of ``lax.scan`` bodies."""
    from jax import numpy as jnp

    return lax.all_gather(jnp.asarray(segment_ids, jnp.int32), axis_name,
                          axis=1, tiled=True)


def ulysses_attention(q, k, v, axis_name: str = "sp", causal: bool = True,
                      segment_ids=None, gathered_segment_ids=None,
                      window=None):
    """Context-parallel attention via head<->sequence all-to-all.

    q/k/v: [B, T_local, H, D] per chip, sequence-sharded over
    ``axis_name``. Returns [B, T_local, H, D] with the same sharding.
    Requires ``H % axis_size == 0``. ``segment_ids`` (int [B, T_local],
    sequence-sharded like q): packed-sequence masking — after the
    re-shard every chip holds the full sequence, so the ids are simply
    all-gathered along it (or pass ``gathered_segment_ids`` [B, T_global]
    from :func:`gather_segment_ids` to hoist the gather out of a layer
    loop).
    """
    sp = _axis_size(axis_name)
    from jax import numpy as jnp

    from ..ops.pallas_attention import flash_attention

    heads = q.shape[2]
    g = heads // k.shape[2]  # GQA group size (1 = plain multi-head)
    from .ring_attention import _expand_kv

    if sp == 1:
        k, v = _expand_kv(k, v, g)
        return flash_attention(q, k, v, causal=causal,
                               q_segment_ids=segment_ids,
                               k_segment_ids=segment_ids, window=window)
    if heads % sp != 0 or k.shape[2] % sp != 0:
        raise ValueError(
            f"ulysses_attention needs heads divisible by the '{axis_name}' "
            f"axis: {heads} query / {k.shape[2]} KV heads across {sp} "
            f"chips (after any tp head sharding). Use ring_attention "
            f"when heads don't divide.")

    # [B, T_local, H, D] -> [B, T_global, H/sp, D]: split the head axis
    # sp ways, concatenate the received blocks along the sequence axis.
    def seq_to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    full_seg = gathered_segment_ids
    if full_seg is None and segment_ids is not None:
        full_seg = gather_segment_ids(segment_ids, axis_name)
    # GQA K/V cross the fabric at their reduced width; the contiguous
    # head split means shard i's query heads use exactly shard i's KV
    # heads, so the post-exchange expansion is purely local.
    kf, vf = _expand_kv(seq_to_heads(k), seq_to_heads(v), g)
    o = flash_attention(seq_to_heads(q), kf, vf,
                        causal=causal, q_segment_ids=full_seg,
                        k_segment_ids=full_seg, window=window)
    return heads_to_seq(o)


def context_parallel_attention(q, k, v, axis_name: str = "sp",
                               causal: bool = True,
                               strategy: str = "ring",
                               segment_ids=None,
                               gathered_segment_ids=None, window=None):
    """Dispatch between the two sequence-parallel attention strategies.

    ``strategy``: ``"ring"`` (default — no head constraint, T_local
    working set), ``"ulysses"`` (all-to-all re-shard, needs
    heads % sp == 0), or ``"auto"`` (ulysses when the head constraint
    holds, ring otherwise). ``segment_ids``: packed-sequence masking,
    accepted by both strategies (``gathered_segment_ids`` additionally
    lets ulysses callers hoist the id gather out of a layer loop; the
    ring ignores it — its masking is block-local).
    """
    from .ring_attention import ring_attention

    if strategy == "auto":
        sp = _axis_size(axis_name)
        # Both query AND (GQA-reduced) KV heads must divide the axis for
        # ulysses' head split; otherwise fall back to ring as documented.
        strategy = ("ulysses" if q.shape[2] % sp == 0
                    and k.shape[2] % sp == 0 else "ring")
    if strategy == "ulysses":
        return ulysses_attention(q, k, v, axis_name=axis_name,
                                 causal=causal, segment_ids=segment_ids,
                                 gathered_segment_ids=gathered_segment_ids,
                                 window=window)
    if strategy == "ring":
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                              segment_ids=segment_ids, window=window)
    raise ValueError(f"unknown sequence-parallel strategy {strategy!r}; "
                     "expected 'ring', 'ulysses', or 'auto'")
