"""SPMD pipeline parallelism (GPipe schedule via collective_permute).

Each ``pp`` mesh-axis member holds one stage's parameters (stage params are
sharded over ``pp``). The schedule runs ``M + S - 1`` ticks; at each tick
every stage applies itself to its current activation, then activations shift
one hop around the ring (``lax.ppermute``) — stage 0 injects a fresh
microbatch each of the first ``M`` ticks, the last stage emits a finished
microbatch from tick ``S-1`` on. Autodiff through the scan + ppermute gives
the backward pipeline for free (ppermute's transpose is the reverse
permute), so one ``jax.grad`` over the whole thing yields a correct
1F1B-equivalent-cost GPipe backward.

The reference has no pipeline support (SURVEY §2.5) — this is part of the
TPU build's parallelism surface beyond DP parity.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size


def spmd_pipeline(stage_fn: Callable, stage_params, microbatches,
                  axis_name: str = "pp", collect_fn: Callable = None):
    """Run ``microbatches`` through the pipeline.

    stage_fn(params, x) -> y : applies ONE stage (same structure in/out).
    stage_params: this member's stage parameters (already pp-local).
    microbatches: [M, ...] stacked microbatch activations — a single
    array or any pytree of [M, ...] leaves (e.g. ``(x, segment_ids)``
    for packed sequences: per-microbatch side data rides the activation
    ring with the activations). Stage-0 input layout; other stages
    ignore the values and receive via the ring.

    collect_fn(y) selects the sub-pytree that is actually an OUTPUT;
    defaults to the whole structure. Side data the stages merely pass
    through (segment ids) still rides the per-tick ring carry — later
    stages consume it — but is excluded from the per-tick output
    collect and the closing psum-broadcast, saving a dynamic-update per
    tick and collective bandwidth per leaf.

    Returns ``collect_fn``-selected [M, ...] outputs as produced by the
    LAST stage (valid on every member after the closing psum-broadcast).
    """
    tmap = jax.tree_util.tree_map
    S = _axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    T = M + S - 1
    if collect_fn is None:
        collect_fn = lambda y: y  # noqa: E731

    fwd = [(i, (i + 1) % S) for i in range(S)]
    x0 = tmap(lambda m: jnp.zeros_like(m[0]), microbatches)
    outbuf = tmap(jnp.zeros_like, collect_fn(microbatches))

    def tick(carry, t):
        state, outbuf = carry
        # stage 0 injects microbatch t (clamped; masked when t >= M)
        mb = tmap(lambda m: lax.dynamic_index_in_dim(
            m, jnp.clip(t, 0, M - 1), 0, keepdims=False), microbatches)
        inject = jnp.logical_and(stage == 0, t < M)
        state = tmap(lambda m, s: jnp.where(inject, m, s), mb, state)
        y = stage_fn(stage_params, state)
        # last stage collects finished microbatch t-(S-1)
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        collect = jnp.logical_and(stage == S - 1, t >= S - 1)

        def collect_leaf(ob, yy):
            cur = lax.dynamic_index_in_dim(ob, out_idx, 0, keepdims=False)
            return lax.dynamic_update_index_in_dim(
                ob, jnp.where(collect, yy, cur), out_idx, 0)

        outbuf = tmap(collect_leaf, outbuf, collect_fn(y))
        state = tmap(lambda yy: lax.ppermute(yy, axis_name, fwd), y)
        return (state, outbuf), None

    (_, outbuf), _ = lax.scan(tick, (x0, outbuf), jnp.arange(T))
    # Broadcast the last stage's outputs to all pp members so downstream
    # (loss) code is uniform SPMD.
    outbuf = tmap(
        lambda ob: lax.psum(
            jnp.where(stage == S - 1, ob, jnp.zeros_like(ob)), axis_name),
        outbuf)
    return outbuf
