"""Sharding-aware checkpointing for train states (orbax-backed).

The reference leaves durable checkpoints to user code (rank-0
``torch.save`` in every example; SURVEY §5 checkpoint/resume) and ships
only the in-memory elastic ``State``. On TPU the natural store is orbax:
it writes each device's shards without gathering (a ZeRO state's sharded
masters/optimizer never materialize on one host) and restores arrays
directly onto the target mesh's shardings.

    from horovod_tpu.checkpoint import CheckpointManager
    mgr = CheckpointManager("/ckpt", max_to_keep=3)
    mgr.save(step, state)                       # any pytree of jax arrays
    state = mgr.restore(template=state)         # latest, onto state's shardings
    state = mgr.restore(step=100, template=state)

The template supplies structure, dtypes, and shardings — pass a freshly
initialized state (e.g. ``init_zero_train_state(...)``) and the restore
lands every leaf on its proper devices, sharded exactly as initialized.
For raw optax states on a model-parallel mesh, build the template with
``training.init_opt_state(optimizer, params, mesh)``: a bare
``jit(optimizer.init)`` leaves scalar leaves (Adam's ``count``) on one
device, and a state restored onto that template then mixes single-device
and full-mesh arrays in the next step, which jax rejects.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import orbax.checkpoint as ocp

from .common import faults as _faults


class CheckpointManager:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager`` with the
    framework's conventions: step-numbered directories, bounded retention,
    template-driven sharded restore."""

    def __init__(self, directory: str, max_to_keep: Optional[int] = None):
        self._directory = os.path.abspath(directory)
        # orbax owns directory creation (create=True default) — in
        # multi-host deployments it coordinates it on the primary host.
        self._mgr = ocp.CheckpointManager(
            self._directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, step: int, state: Any, wait: bool = True) -> None:
        """Write ``state`` (any pytree of jax/numpy arrays) under ``step``.

        Sharded leaves are written shard-by-shard from their owning
        devices. With ``wait=False`` the write completes in the
        background; call ``wait_until_finished()`` (or the next save)
        before depending on it."""
        # Chaos seam: prove recovery paths against a checkpoint write
        # that dies / stalls / drops mid-flight (docs/fault-injection.md).
        _faults.point("checkpoint.write")
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore ``step`` (default: latest) onto ``template``'s
        structure/dtypes/shardings."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self._directory}")
        return self._mgr.restore(step,
                                 args=ocp.args.StandardRestore(template))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
