"""Chrome-tracing timeline writer.

Parity with the reference Timeline (``common/timeline.{h,cc}``): per-tensor
phase events (NEGOTIATE_* → processing activities) written as Chrome tracing
JSON, with a dedicated writer thread fed by a queue so the hot path never
blocks on file IO (the reference uses a boost lock-free SPSC queue,
``timeline.h:47-75``; a ``queue.SimpleQueue`` plays that role here — the
C++ core supplies the native writer in the runtime library).

Activity names follow ``common.h:31-59`` so existing timeline-analysis
tooling for the reference reads our traces unchanged; device-side timing
comes from XLA profiler hooks rather than CUDA events.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional

# Activity names (parity: common.h:31-59 / docs/timeline.rst:22-43)
NEGOTIATE_ALLREDUCE = "NEGOTIATE_ALLREDUCE"
NEGOTIATE_ALLGATHER = "NEGOTIATE_ALLGATHER"
NEGOTIATE_BROADCAST = "NEGOTIATE_BROADCAST"
WAIT_FOR_DATA = "WAIT_FOR_DATA"
WAIT_FOR_OTHER_TENSOR_DATA = "WAIT_FOR_OTHER_TENSOR_DATA"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
XLA_ALLREDUCE = "XLA_ALLREDUCE"
XLA_ALLGATHER = "XLA_ALLGATHER"
XLA_BCAST = "XLA_BCAST"
XLA_REDUCESCATTER = "XLA_REDUCESCATTER"
COMPILE = "COMPILE"
# Robustness-plane instants (docs/fault-injection.md): a Retrier backing
# off, a stall-inspector warning drained by hvd.stall_report(), and the
# elastic driver blacklisting a host (launcher-side timeline).
RETRY = "RETRY"
STALL_WARNING = "STALL_WARNING"
HOST_BLACKLISTED = "HOST_BLACKLISTED"
# Liveness-plane instants (docs/liveness.md), recorded in the
# launcher-side `<timeline>.driver.json` alongside HOST_BLACKLISTED: the
# heartbeat state machine's escalation steps and the two phases of a
# preemption drain.
HEARTBEAT_MISS = "HEARTBEAT_MISS"
RANK_SUSPECT = "RANK_SUSPECT"
RANK_EVICTED = "RANK_EVICTED"
DRAIN_BEGIN = "DRAIN_BEGIN"
DRAIN_COMMIT = "DRAIN_COMMIT"
# Metrics-plane instants (docs/metrics.md): the coordinator's straggler
# detector naming the rank whose EWMA lag behind the group's fastest
# crossed the threshold (args: rank, lag_ms), and the cycle marker
# emitted by mark_cycle.
STRAGGLER_WARNING = "STRAGGLER_WARNING"
CYCLE = "CYCLE"
# Self-healing-plane instant (docs/self-healing.md): a cross-host data
# link was redialed in place mid-collective (args: reconnects — the
# native link.reconnects counter after the heal).
LINK_RECONNECT = "LINK_RECONNECT"

# Single source of truth for timeline instant names — the same
# registry discipline as ``faults.CATALOG``: every ``timeline.instant``
# call site must pass one of these module constants (enforced by
# hvdlint's ``timeline-instant-registry`` check; a genuinely dynamic
# relay needs a reasoned suppression). Tooling that consumes traces
# keys off these strings, so a name used ad hoc at a call site is an
# event no dashboard will ever find.
INSTANT_CATALOG = (
    RETRY,
    STALL_WARNING,
    HOST_BLACKLISTED,
    HEARTBEAT_MISS,
    RANK_SUSPECT,
    RANK_EVICTED,
    DRAIN_BEGIN,
    DRAIN_COMMIT,
    STRAGGLER_WARNING,
    CYCLE,
    LINK_RECONNECT,
)


class Timeline:
    """Rank-0 Chrome-tracing JSON writer with a background writer thread."""

    def __init__(self, filename: str, mark_cycles: bool = False):
        self._filename = filename
        self._mark_cycles = mark_cycles
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._start_ns = time.monotonic_ns()
        self._pid = os.getpid()
        self._tensor_tids = {}
        self._next_tid = 1
        self._closed = False
        self._file = open(filename, "w")
        self._file.write("[\n")
        self._writer = threading.Thread(target=self._drain, daemon=True)
        self._writer.start()

    # -- event API -----------------------------------------------------------

    def _ts_us(self) -> float:
        return (time.monotonic_ns() - self._start_ns) / 1e3

    def _tid(self, tensor_name: str) -> int:
        tid = self._tensor_tids.get(tensor_name)
        if tid is None:
            tid = self._next_tid
            self._next_tid += 1
            self._tensor_tids[tensor_name] = tid
            self._emit(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": self._pid,
                    "tid": tid,
                    "args": {"name": tensor_name},
                }
            )
        return tid

    def _emit(self, ev: dict):
        if not self._closed:
            self._queue.put(ev)

    def start_activity(self, tensor_name: str, activity: str):
        self._emit(
            {
                "name": activity,
                "ph": "B",
                "pid": self._pid,
                "tid": self._tid(tensor_name),
                "ts": self._ts_us(),
            }
        )

    def end_activity(self, tensor_name: str, activity: str):
        self._emit(
            {
                "name": activity,
                "ph": "E",
                "pid": self._pid,
                "tid": self._tid(tensor_name),
                "ts": self._ts_us(),
            }
        )

    def instant(self, name: str, args: Optional[dict] = None):
        self._emit(
            {
                "name": name,
                "ph": "i",
                "s": "g",
                "pid": self._pid,
                "tid": 0,
                "ts": self._ts_us(),
                "args": args or {},
            }
        )

    def rank_ready(self, tensor_name: str, rank: int,
                   mono_ns: Optional[int] = None):
        """Per-rank negotiation tick (parity: Timeline::NegotiateRankReady,
        reference controller.cc:797-809): marks when ``rank``'s submission
        for ``tensor_name`` reached the coordinator, so stragglers are
        visible inside the NEGOTIATE span. ``mono_ns`` is a
        CLOCK_MONOTONIC timestamp (the native controller's clock, the same
        clock as ``time.monotonic_ns``)."""
        ts = (self._ts_us() if mono_ns is None
              else (mono_ns - self._start_ns) / 1e3)
        self._emit(
            {
                "name": f"RANK_READY[{rank}]",
                "ph": "i",
                "s": "t",
                "pid": self._pid,
                "tid": self._tid(tensor_name),
                "ts": ts,
                "args": {"rank": rank},
            }
        )

    def counter(self, name: str, values: dict):
        """Chrome-tracing counter event ("C" phase): ``values`` maps
        series name -> number, rendered by trace viewers as stacked
        counter tracks. The metrics exporter emits these periodically
        (docs/metrics.md) so byte counters and cache hits line up with
        the collectives on the same time axis."""
        self._emit(
            {
                "name": name,
                "ph": "C",
                "pid": self._pid,
                "ts": self._ts_us(),
                "args": values,
            }
        )

    def mark_cycle(self):
        if self._mark_cycles:
            self.instant(CYCLE)

    # -- writer thread -------------------------------------------------------

    def _drain(self):
        first = True
        while True:
            ev = self._queue.get()
            if ev is None:
                break
            if not first:
                self._file.write(",\n")
            first = False
            self._file.write(json.dumps(ev))
        self._file.write("\n]\n")
        self._file.flush()
        self._file.close()

    def close(self):
        if not self._closed:
            self._closed = True
            self._queue.put(None)
            self._writer.join(timeout=5.0)
