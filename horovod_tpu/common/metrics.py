"""Unified metrics plane — the Python half (docs/metrics.md).

Merges the native registry's JSON snapshot (``csrc/hvd/metrics.cc``,
read through the single ``hvd_metrics_snapshot`` getter) with the
Python-plane counters that never touch the native core: Retrier
retries, fault injections, shm/stripe fallback armings, elastic
evictions and drains. Surfaced as ``hvd.metrics()`` /
``hvd.metrics_report()`` and, behind ``HOROVOD_METRICS_EXPORT``
(default off = byte-identical behavior), published periodically as a
Prometheus textfile plus Chrome-tracing counter ("C" phase) events in
the active timeline.

The straggler warnings the native detector drains through the snapshot
become ``STRAGGLER_WARNING`` timeline instants here — the Python plane
owns the timeline, the native plane owns the per-rank ready
timestamps.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from . import config as _config
from . import logging as _log

# ---- Python-plane counters -------------------------------------------------
#
# One flat namespace of monotonically increasing ints. Callers use
# dotted names mirroring the subsystem that owns them:
#   retrier.retries        every Retrier backoff taken (faults.py)
#   faults.injected        every fault point that fired (faults.py)
#   chaos.injected         the subset of faults.injected drawn by the
#                          seeded chaos scheduler, HOROVOD_CHAOS_SPEC
#                          (faults.py; docs/self-healing.md)
#   shm.attach_fallback    ring.shm.attach seam armed a forced TCP
#                          fallback for this world (host_world.py)
#   stripe.connect_fallback  the stripe sibling (host_world.py)
#   elastic.evictions      driver-side liveness evictions (driver.py)
#   elastic.drains         commit-marked graceful drains (driver.py)
#
# The native snapshot carries the self-healing counters alongside these
# (link.reconnects / link.resume_chunks_discarded /
# link.stale_epoch_rejected / epoch — csrc/hvd/operations.cc).

_lock = threading.Lock()
_counters: dict = {}


def inc(name: str, n: int = 1) -> None:
    """Bump a Python-plane counter (thread-safe, near-zero cost)."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def counters() -> dict:
    """A copy of the Python-plane counters."""
    with _lock:
        return dict(_counters)


def reset() -> None:
    """Zero the Python-plane counters (tests)."""
    with _lock:
        _counters.clear()


# ---- native snapshot access ------------------------------------------------


def live_native_core():
    """The process's live NativeCore: the XLA engine's when one runs,
    else the host (process-rank) world's. None in pure-direct mode or
    before init — the ONE core-resolution rule every observability
    surface shares (``hvd.stall_report``/``ring_traffic``/``metrics``)."""
    from . import state as _state

    st = _state.global_state()
    if st.initialized and st.engine is not None:
        core = getattr(st.engine, "native_core", None)
        if core is not None:
            return core
    from . import host_world as _host_world

    world = _host_world.world()
    return world._core if world.initialized else None


def _active_timeline():
    from . import state as _state

    st = _state.global_state()
    return st.timeline if st.initialized else None


def _emit_straggler_instants(native: Optional[dict]) -> None:
    """Drained straggler events -> STRAGGLER_WARNING timeline instants
    (when a timeline is active; the events also live in the returned
    snapshot either way)."""
    if not native:
        return
    events = native.get("straggler", {}).get("events", ())
    if not events:
        return
    timeline = _active_timeline()
    if timeline is None:
        return
    from . import timeline as _timeline

    for ev in events:
        timeline.instant(_timeline.STRAGGLER_WARNING,
                         {"rank": ev.get("rank"),
                          "lag_ms": ev.get("lag_ms")})


def snapshot(drain: bool = True) -> dict:
    """The merged metrics view behind ``hvd.metrics()``:

    ``{"python": {counter: value}, "native": {...} | None}``

    ``native`` is the parsed unified snapshot (counters, log2
    histograms, straggler state) or None when no native core is live.
    With ``drain`` (the default), pending straggler warning events are
    consumed into ``native["straggler"]["events"]`` and mirrored as
    ``STRAGGLER_WARNING`` timeline instants; monitors that must not
    steal events pass ``drain=False``."""
    native = None
    core = live_native_core()
    if core is not None:
        flags = core.METRICS_DRAIN_STRAGGLER if drain else 0
        native = core.metrics_snapshot(flags) or None
        if drain:
            _emit_straggler_instants(native)
    return {"python": counters(), "native": native}


# ---- histogram math --------------------------------------------------------


def percentiles(hist: dict, qs=(50, 90, 99)) -> dict:
    """Approximate percentiles of a native log2 histogram (value taken
    at each covering bucket's upper bound, 2^(i+1); exact enough for
    "did p99 gather wait regress 10x", which is what log2 buckets are
    for). ``hist`` is the snapshot shape ``{"count":..., "buckets":
    [[index, count], ...]}``. Returns {"p50": v, ...} (zeros when
    empty)."""
    total = int(hist.get("count", 0))
    out = {f"p{q}": 0 for q in qs}
    if total <= 0:
        return out
    buckets = sorted((int(b), int(c)) for b, c in hist.get("buckets", ()))
    for q in qs:
        target = total * q / 100.0
        seen = 0
        val = 0
        for b, c in buckets:
            seen += c
            if seen >= target:
                val = 2 ** (b + 1)
                break
        out[f"p{q}"] = val
    return out


def report_text(snap: Optional[dict] = None) -> str:
    """Human-readable rendering of a merged snapshot (the string behind
    ``hvd.metrics_report()``): counters, then each non-empty histogram
    with count / approximate p50/p99 / max, then straggler state.
    Reads with ``drain=False`` — a human glance must not steal pending
    straggler events from ``hvd.metrics()``, which renders them."""
    snap = snap if snap is not None else snapshot(drain=False)
    lines = ["== horovod_tpu metrics =="]
    py = snap.get("python") or {}
    native = snap.get("native")
    if py:
        lines.append("-- python counters --")
        for k in sorted(py):
            lines.append(f"{k}: {py[k]}")
    if not native:
        lines.append("native core: absent (pure-XLA direct mode or "
                     "not initialized)")
        return "\n".join(lines) + "\n"
    lines.append("-- native counters --")
    for k in sorted(native.get("counters", {})):
        lines.append(f"{k}: {native['counters'][k]}")
    lines.append("-- histograms (us) --")
    for name in sorted(native.get("histograms", {})):
        h = native["histograms"][name]
        if not h.get("count"):
            continue
        p = percentiles(h, (50, 99))
        lines.append(f"{name}: n={h['count']} p50~{p['p50']} "
                     f"p99~{p['p99']} max={h['max']}")
    st = native.get("straggler", {})
    lines.append(f"straggler: warnings={st.get('warnings', 0)} "
                 f"last_rank={st.get('last_rank', -1)} "
                 f"last_lag_ms={st.get('last_lag_ms', 0)}")
    return "\n".join(lines) + "\n"


# ---- Prometheus textfile exporter ------------------------------------------


def _prom_name(name: str) -> str:
    return "hvd_" + name.replace(".", "_").replace("-", "_")


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Render a merged snapshot in node-exporter textfile format:
    counters as gauges, log2 histograms as Prometheus histograms with
    ``le`` = the bucket upper bounds (2^(i+1) microseconds)."""
    snap = snap if snap is not None else snapshot(drain=False)
    out = []
    py = snap.get("python") or {}
    for k in sorted(py):
        n = _prom_name(k)
        out.append(f"# TYPE {n} counter")
        out.append(f"{n} {py[k]}")
    native = snap.get("native")
    if native:
        for k in sorted(native.get("counters", {})):
            v = native["counters"][k]
            n = _prom_name(k)
            out.append(f"# TYPE {n} gauge")
            out.append(f"{n} {v}")
        for name in sorted(native.get("histograms", {})):
            h = native["histograms"][name]
            n = _prom_name(name)
            out.append(f"# TYPE {n} histogram")
            cum = 0
            for b, c in sorted((int(b), int(c))
                               for b, c in h.get("buckets", ())):
                cum += c
                out.append(f'{n}_bucket{{le="{2 ** (b + 1)}"}} {cum}')
            # The snapshot reads count before the bucket array while
            # recorders increment bucket-then-count (relaxed): a Record
            # landing between the reads makes sum(buckets) == count+1.
            # +Inf/_count must stay >= every explicit bucket or the
            # series is an invalid decreasing histogram.
            total = max(cum, int(h.get("count", 0)))
            out.append(f'{n}_bucket{{le="+Inf"}} {total}')
            out.append(f"{n}_sum {h.get('sum', 0)}")
            out.append(f"{n}_count {total}")
        st = native.get("straggler", {})
        out.append("# TYPE hvd_straggler_warnings counter")
        out.append(f"hvd_straggler_warnings {st.get('warnings', 0)}")
        out.append("# TYPE hvd_straggler_last_rank gauge")
        out.append(f"hvd_straggler_last_rank {st.get('last_rank', -1)}")
    return "\n".join(out) + "\n"


class MetricsPump(threading.Thread):
    """The exporter thread (rank-side, armed ONLY by
    ``HOROVOD_METRICS_EXPORT``): every interval, snapshot once and
    publish twice — atomically rewrite the textfile, and (when a
    timeline is active) emit Chrome-tracing counter events plus any
    drained STRAGGLER_WARNING instants. Daemonized and stop()-able; a
    publish failure logs and keeps the thread alive (observability must
    never take the job down)."""

    def __init__(self, path: str, interval_ms: int):
        super().__init__(name="hvd-metrics-pump", daemon=True)
        self._path = path
        self._interval_s = max(0.1, interval_ms / 1000.0)
        # NOT self._stop: threading.Thread owns a private _stop() method
        # (CPython's tstate cleanup calls it) — shadowing it with an
        # Event breaks Thread.join on 3.10.
        self._stop_evt = threading.Event()
        # Last observed native link.reconnects value: a growth between
        # publishes becomes a LINK_RECONNECT timeline instant (the pump
        # is the only reader, so plain int is fine).
        self._last_reconnects = 0

    def stop(self):
        self._stop_evt.set()
        self.join(timeout=5.0)

    def publish_once(self):
        # Drain straggler events only when a timeline exists to receive
        # them as instants — otherwise the pump would silently discard
        # events that hvd.metrics() promises to deliver (the textfile
        # renders cumulative straggler state either way).
        snap = snapshot(drain=_active_timeline() is not None)
        text = prometheus_text(snap)
        tmp = f"{self._path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, self._path)
        timeline = _active_timeline()
        native = snap.get("native")
        if timeline is not None and native:
            c = native.get("counters", {})
            timeline.counter("hvd_bytes", {
                "bytes_sent": c.get("bytes_sent", 0),
                "cross_bytes": c.get("cross_bytes", 0),
                "shm_bytes": c.get("shm_bytes", 0),
            })
            timeline.counter("hvd_control", {
                "cache_hits": c.get("cache_hits", 0),
                "cycles": c.get("cycles", 0),
                "pending": c.get("pending", 0),
            })
            reconnects = int(c.get("link.reconnects", 0))
            if reconnects > self._last_reconnects:
                from . import timeline as _timeline

                timeline.instant(_timeline.LINK_RECONNECT,
                                 {"reconnects": reconnects})
            self._last_reconnects = reconnects

    def run(self):
        while not self._stop_evt.wait(self._interval_s):
            try:
                self.publish_once()
            # The exporter is best-effort by contract: a transient
            # write/snapshot error must not kill the pump (or the
            # training job).
            except Exception as e:
                _log.warning(f"metrics export failed: {e}")
        # Final publish so short jobs still leave a file behind.
        try:
            self.publish_once()
        # Same best-effort contract on the shutdown flush.
        except Exception as e:
            _log.debug(f"final metrics export failed: {e}")


_pump: Optional[MetricsPump] = None


def maybe_start_pump() -> Optional[MetricsPump]:
    """Start the exporter iff ``HOROVOD_METRICS_EXPORT`` is set (called
    from ``hvd.init``). Unset = nothing starts, nothing is written —
    the byte-identical default (regression-tested)."""
    global _pump
    path = _config.metrics_export_path()
    if not path or _pump is not None:
        return _pump
    _pump = MetricsPump(path, _config.metrics_interval_ms())
    _pump.start()
    return _pump


def stop_pump() -> None:
    """Stop the exporter (called from ``hvd.shutdown``); flushes one
    final snapshot to the textfile."""
    global _pump
    if _pump is not None:
        _pump.stop()
        _pump = None
