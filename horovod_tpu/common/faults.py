"""Deterministic fault injection + the shared retry/backoff policy.

Two halves of one robustness story (Sergeev & Del Balso, 2018 pair the
elastic driver with blacklisting + stall inspection; Li et al., 2020 treat
failure detection and deterministic reproduction as a first-class
subsystem):

- **Fault points** — named, zero-cost-when-disabled hooks
  (``faults.point("ring.exec")``) sprinkled through every host-plane seam
  and activated by a parsed ``HOROVOD_FAULT_SPEC`` env (grammar in
  ``common/config.py``; catalog below). Firing is deterministic by rank +
  a per-point hit counter, so a multi-process chaos test that kills rank 1
  on the 3rd enqueue reproduces exactly, every run.

- **Retrier** — the one retry/backoff implementation for every
  host-plane network loop (KV reads, rendezvous polls, driver probes):
  exponential backoff with full jitter, an overall deadline, and an
  on-retry callback into ``common/logging.py`` + ``timeline.py``.
  Per-call policies come from ``HOROVOD_RETRY_*`` envs
  (``config.retry_policy_from_env``). hvdlint's retry-discipline check
  (docs/static-analysis.md) enforces that
  no new bare ``time.sleep(`` retry loop appears outside this module.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from . import config as _config
from . import logging as _log
from .exceptions import HorovodInternalError

# Fault-point catalog (docs/fault-injection.md). Extensible — an unknown
# point in a spec only warns — but these are the wired seams:
CATALOG = (
    "host_world.enqueue",    # HostWorld.enqueue, before the native submit
    "rendezvous.poll",       # elastic slot-layout fetch from the KV
    "rendezvous.endpoint",   # controller-endpoint poll from the KV
    "ring.exec",             # blocking wait on a host-ring collective
    "ring.hier.cross",       # same seam, armed only on a local leader of a
                             # hierarchical multi-host world — kills/delays
                             # the rank carrying the cross-host leg
    "ring.shm.attach",       # shm-transport attach at world init
                             # (docs/shm-transport.md): kind=raise makes
                             # THIS rank's native shm attaches fail, so
                             # the registered TCP fallback carries its
                             # local legs — byte-identical results, the
                             # fallback path under test (the one seam
                             # whose raise is absorbed, not propagated)
    "ring.shm.exec",         # blocking wait on a collective in a world
                             # with the shm transport active — the shm
                             # analog of ring.exec for kills/delays/
                             # raises while bytes ride the shm rings
    "ring.stripe.connect",   # striped cross-host transport connect at
                             # world init (docs/cross-transport.md):
                             # kind=raise is ABSORBED like
                             # ring.shm.attach — it forces THIS rank's
                             # native stripe dials to fail, so the
                             # negotiation falls through to single-
                             # socket TCP in lock-step (strict mode
                             # HOROVOD_STRIPE_FALLBACK=0 hard-errors
                             # instead); kind=exit/delay keep their
                             # usual semantics
    "ring.stripe.exec",      # blocking wait on a collective in a world
                             # with the striped cross transport armed —
                             # the stripe analog of ring.exec for
                             # kills/delays/raises while chunks are
                             # mid-flight across the stripe sockets
    "xla.exec",              # eager engine executing an XLA-plane response
    "zero.gather",           # ZeRO stage-3 parameter-gather leg
                             # (zero.py step dispatch; docs/zero.md):
                             # armed on the host side as a stage-3 step
                             # launches its gather-bearing program, so
                             # kind=raise surfaces HorovodInternalError
                             # to the elastic retry loop exactly where a
                             # real gather failure would — the partition
                             # plane's chaos hook
    "elastic.worker.start",  # driver-side worker launch (slot.rank)
    "checkpoint.write",      # CheckpointManager.save
    "control.heartbeat",     # worker heartbeat KV put (docs/liveness.md);
                             # kind=drop_conn drops a beat, kind=delay_ms
                             # lands it late — the chaos inputs for the
                             # miss/SUSPECT/EVICT escalation tests
    "elastic.drain",         # preemption drain protocol, between the
                             # DRAIN begin announcement and the state
                             # commit — kill here = preemption deadline
                             # beating the drain (charged as a crash)
)

# Injectable for tests (fake clock / no real sleeps in tier-1).
_sleep = time.sleep


class FaultInjected(HorovodInternalError):
    """Raised by ``kind=raise`` faults. A subclass of
    ``HorovodInternalError`` so the elastic retry loop treats an injected
    failure exactly like a real collective failure."""


_lock = threading.Lock()
_specs: Tuple[_config.FaultSpec, ...] = ()
_hits: Dict[str, int] = {}
_fired: Dict[int, int] = {}  # spec index -> fire count
_loaded = False
# Index into _specs where the compiled chaos schedule begins: specs at
# [_chaos_base:] came from HOROVOD_CHAOS_SPEC and additionally count
# metrics "chaos.injected" when they fire.
_chaos_base = 0


def refresh() -> None:
    """(Re-)read ``HOROVOD_FAULT_SPEC`` + ``HOROVOD_CHAOS_SPEC`` and
    reset all hit/fire counters.

    Called lazily on the first ``point()`` of a process; call explicitly
    after mutating the env in-process (tests). The chaos spec compiles
    deterministically from its seed (config.parse_chaos_spec), so the
    same spec string arms the same schedule in every process."""
    global _specs, _hits, _fired, _loaded, _chaos_base
    with _lock:
        _specs = _config.parse_fault_spec_env()
        _chaos_base = len(_specs)
        _specs = _specs + _config.parse_chaos_spec_env()
        _hits = {}
        _fired = {}
        _loaded = True
        for spec in _specs:
            if spec.point not in CATALOG:
                _log.warning(
                    f"fault spec names unknown point {spec.point!r} "
                    f"(catalog: {', '.join(CATALOG)}); it will only fire "
                    f"if some code calls faults.point({spec.point!r})")


def active() -> bool:
    """True when any fault spec is armed in this process."""
    if not _loaded:
        refresh()
    return bool(_specs)


def _default_rank() -> int:
    return _config.rank()


def point(name: str, rank: Optional[int] = None) -> None:
    """A named fault point. No-op (and near-zero cost: one global load +
    truthiness test) unless ``HOROVOD_FAULT_SPEC`` armed a spec in this
    process — hit counters only advance while armed, so the disabled
    behavior is byte-identical to the hook not existing.

    ``rank`` is the caller's rank when it knows it (elastic re-rendezvous
    moves ranks while the env stays stale); default is ``HOROVOD_RANK``.
    """
    if _loaded:
        if not _specs:
            return
    else:
        refresh()
        if not _specs:
            return
    with _lock:
        hit = _hits.get(name, 0)
        _hits[name] = hit + 1
        if rank is None:
            rank = _default_rank()
        to_fire = None
        chaos = False
        for i, spec in enumerate(_specs):
            if spec.point != name:
                continue
            if spec.rank >= 0 and spec.rank != rank:
                continue
            if spec.step >= 0 and spec.step != hit:
                continue
            if spec.times > 0 and _fired.get(i, 0) >= spec.times:
                continue
            _fired[i] = _fired.get(i, 0) + 1
            to_fire = spec
            chaos = i >= _chaos_base
            break
    if to_fire is None:
        return
    _fire(to_fire, name, rank, hit, chaos=chaos)


def _fire(spec: _config.FaultSpec, name: str, rank: int, hit: int,
          chaos: bool = False) -> None:
    desc = f"fault injected at {name} (rank={rank} hit={hit} " \
           f"kind={spec.kind})"
    _log.warning(desc)
    from . import metrics as _metrics

    _metrics.inc("faults.injected")
    if chaos:
        # The chaos scheduler's own tally, split from hand-armed faults
        # so a soak's bench JSON can assert the drawn schedule actually
        # fired (docs/self-healing.md, chaos-spec section).
        _metrics.inc("chaos.injected")
    if spec.kind == "delay_ms":
        _sleep(spec.ms / 1000.0)
        return
    if spec.kind == "exit":
        # Hard death, as if the process was OOM-killed/preempted: no
        # atexit, no finally blocks — the chaos being simulated.
        os._exit(spec.code)
    if spec.kind == "drop_conn":
        raise ConnectionResetError(desc)
    raise FaultInjected(desc)


# ---- shared retry/backoff -------------------------------------------------


def _timeline_instant(name: str, args: dict) -> None:
    """Best-effort timeline event for a retry (rank-side only: the
    launcher has no global state). Imported lazily — faults sits below
    state in the module graph."""
    try:
        from . import state as _state

        st = _state.global_state()
        timeline = st.timeline if st.initialized else None
    except Exception:
        return
    if timeline is not None:
        # hvdlint: ignore[timeline-instant-registry] -- generic relay:
        # the one call site passes the RETRY catalog constant through
        timeline.instant(name, args)


def default_on_retry(name: str, attempt: int, delay: float,
                     err: Optional[BaseException]) -> None:
    """Log + timeline-record + metrics-count one retry (the Retrier
    default)."""
    why = f" ({err})" if err is not None else ""
    _log.warning(f"{name}: attempt {attempt + 1} failed{why}; "
                 f"retrying in {delay:.2f}s")
    from . import metrics as _metrics

    _metrics.inc("retrier.retries")
    from . import timeline as _timeline

    _timeline_instant(_timeline.RETRY, {
        "site": name, "attempt": attempt, "delay_s": round(delay, 3),
        "error": str(err) if err is not None else "",
    })


class RetryExhausted(TimeoutError):
    """Raised by ``Retrier.poll`` when the deadline expires without a
    result (``Retrier.call`` re-raises the last real exception instead)."""


class Retrier:
    """Exponential backoff + full jitter + overall deadline.

    Deterministic where it matters: the jitter rng is seeded by
    ``(name, rank)``, so a retry schedule observed in one chaos run is
    the schedule of every run. ``clock``/``sleep`` are injectable so
    tier-1 tests verify schedules with a fake clock and zero real
    sleeping.

        Retrier(policy, "kv.read").call(fn, retry_on=(OSError,))
        Retrier(policy, "endpoint").poll(fetch)   # until non-None
    """

    def __init__(self, policy: _config.RetryPolicy, name: str,
                 on_retry: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Optional[Callable[[float], None]] = None,
                 rank: Optional[int] = None):
        self.policy = policy
        self.name = name
        self._on_retry = on_retry if on_retry is not None else (
            lambda attempt, delay, err: default_on_retry(
                name, attempt, delay, err))
        self._clock = clock
        self._sleep = sleep if sleep is not None else (lambda s: _sleep(s))
        self._seed_rank = rank if rank is not None else _default_rank()
        # Lazily seeded: the no-retry success path (every healthy KV
        # read) should not pay Random construction.
        self._rng = None

    def backoff(self, attempt: int) -> float:
        """The delay after ``attempt`` (0-based) failures: full jitter
        over an exponentially growing cap (AWS-style ``uniform(0, cap)``
        — decorrelates a thundering herd of workers re-rendezvousing
        after the same failure)."""
        p = self.policy
        cap = min(p.max_delay, p.base_delay * (p.multiplier ** attempt))
        if not p.jitter:
            return cap
        if self._rng is None:
            self._rng = random.Random(f"{self.name}:{self._seed_rank}")
        return self._rng.uniform(0.0, cap)

    def _deadline(self) -> float:
        p = self.policy
        return self._clock() + p.deadline if p.deadline > 0 \
            else float("inf")

    def call(self, fn: Callable, retry_on: Tuple = (OSError,), *args,
             **kwargs):
        """Run ``fn`` until it returns, retrying on ``retry_on``. The
        final failure re-raises ``fn``'s own exception — callers keep
        their existing error contracts."""
        deadline = self._deadline()
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except retry_on as e:
                attempt += 1
                p = self.policy
                if p.max_attempts > 0 and attempt >= p.max_attempts:
                    raise
                delay = self.backoff(attempt - 1)
                if self._clock() + delay > deadline:
                    raise
                self._on_retry(attempt - 1, delay, e)
                self._sleep(delay)

    def poll(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` until it returns non-None; between polls sleep the
        backoff schedule (capped by ``max_delay``). Returns the value, or
        raises ``RetryExhausted`` at the deadline. ``fn`` raising
        propagates immediately — a poll target that errors is a different
        failure than one that is merely not ready."""
        deadline = self._deadline()
        attempt = 0
        while True:
            result = fn(*args, **kwargs)
            if result is not None:
                return result
            p = self.policy
            attempt += 1
            if p.max_attempts > 0 and attempt >= p.max_attempts:
                raise RetryExhausted(
                    f"{self.name}: no result after {attempt} attempts")
            delay = self.backoff(attempt - 1)
            now = self._clock()
            if now >= deadline:
                raise RetryExhausted(
                    f"{self.name}: no result within "
                    f"{self.policy.deadline:.1f}s deadline")
            delay = min(delay, max(0.0, deadline - now))
            self._sleep(delay)


def retrier(scope: str, name: Optional[str] = None,
            on_retry: Optional[Callable] = None,
            rank: Optional[int] = None, pinned=(),
            **defaults) -> Retrier:
    """Sugar: a ``Retrier`` whose policy comes from the ``scope``'s
    ``HOROVOD_RETRY_*`` envs over the given coded defaults (``pinned``
    fields stay at their coded values — see
    ``config.retry_policy_from_env``)."""
    return Retrier(
        _config.retry_policy_from_env(scope, pinned=pinned, **defaults),
        name or scope.lower(), on_retry=on_retry, rank=rank)
