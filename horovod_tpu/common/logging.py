"""Leveled, rank-prefixed logger.

Parity with the reference C++ macro logger (``common/logging.{h,cc}``):
levels TRACE/DEBUG/INFO/WARNING/ERROR/FATAL selected by ``HOROVOD_LOG_LEVEL``,
timestamps suppressed by ``HOROVOD_LOG_HIDE_TIME``.
"""

from __future__ import annotations

import logging as _pylogging
import os
import sys

_LEVELS = {
    "trace": 5,
    "debug": _pylogging.DEBUG,
    "info": _pylogging.INFO,
    "warning": _pylogging.WARNING,
    "error": _pylogging.ERROR,
    "fatal": _pylogging.CRITICAL,
}

_pylogging.addLevelName(5, "TRACE")

_logger = None


def get_logger() -> _pylogging.Logger:
    global _logger
    if _logger is None:
        _logger = _pylogging.getLogger("horovod_tpu")
        level_name = os.environ.get("HOROVOD_LOG_LEVEL", "warning").strip().lower()
        _logger.setLevel(_LEVELS.get(level_name, _pylogging.WARNING))
        handler = _pylogging.StreamHandler(sys.stderr)
        hide_time = os.environ.get("HOROVOD_LOG_HIDE_TIME", "").strip().lower() in (
            "1",
            "true",
        )
        fmt = "[%(levelname)s] %(message)s" if hide_time else (
            "%(asctime)s [%(levelname)s] %(message)s"
        )
        handler.setFormatter(_pylogging.Formatter(fmt))
        _logger.addHandler(handler)
        _logger.propagate = False
    return _logger


def _prefix(msg: str) -> str:
    rank = os.environ.get("HOROVOD_RANK")
    return f"[rank {rank}] {msg}" if rank is not None else msg


def trace(msg: str) -> None:
    get_logger().log(5, _prefix(msg))


def debug(msg: str) -> None:
    get_logger().debug(_prefix(msg))


def info(msg: str) -> None:
    get_logger().info(_prefix(msg))


def warning(msg: str) -> None:
    get_logger().warning(_prefix(msg))


def error(msg: str) -> None:
    get_logger().error(_prefix(msg))
