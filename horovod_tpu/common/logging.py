"""Leveled, rank-prefixed logger.

Parity with the reference C++ macro logger (``common/logging.{h,cc}``):
levels TRACE/DEBUG/INFO/WARNING/ERROR/FATAL selected by ``HOROVOD_LOG_LEVEL``,
timestamps suppressed by ``HOROVOD_LOG_HIDE_TIME``.
"""

from __future__ import annotations

import logging as _pylogging
import sys

from . import config as _config

_LEVELS = {
    "trace": 5,
    "debug": _pylogging.DEBUG,
    "info": _pylogging.INFO,
    "warning": _pylogging.WARNING,
    "error": _pylogging.ERROR,
    "fatal": _pylogging.CRITICAL,
}

_pylogging.addLevelName(5, "TRACE")

_logger = None


def get_logger() -> _pylogging.Logger:
    global _logger
    if _logger is None:
        _logger = _pylogging.getLogger("horovod_tpu")
        level_name = _config.log_level_name()
        _logger.setLevel(_LEVELS.get(level_name, _pylogging.WARNING))
        handler = _pylogging.StreamHandler(sys.stderr)
        hide_time = _config.log_hide_time()
        fmt = "[%(levelname)s] %(message)s" if hide_time else (
            "%(asctime)s [%(levelname)s] %(message)s"
        )
        handler.setFormatter(_pylogging.Formatter(fmt))
        _logger.addHandler(handler)
        _logger.propagate = False
    return _logger


def _prefix(msg: str) -> str:
    rank = _config.rank_string()
    return f"[rank {rank}] {msg}" if rank is not None else msg


def trace(msg: str) -> None:
    get_logger().log(5, _prefix(msg))


def debug(msg: str) -> None:
    get_logger().debug(_prefix(msg))


def info(msg: str) -> None:
    get_logger().info(_prefix(msg))


def warning(msg: str) -> None:
    get_logger().warning(_prefix(msg))


def error(msg: str) -> None:
    get_logger().error(_prefix(msg))
