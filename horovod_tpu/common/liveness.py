"""Liveness plane: heartbeat tracking and the miss → SUSPECT → EVICT
state machine (docs/liveness.md).

Horovod's runtime historically *noticed* a dead peer only when a
collective broke or the stall inspector complained after the fact
(reference ``stall_inspector.cc``); production fleets need active
failure detection and clean preemption departures. This module is the
Python half of that plane — the elastic driver tracks worker heartbeats
(pushed into the rendezvous KV by ``run/elastic/worker.py``) through a
``LivenessTracker`` here, while the native controller runs the same
state machine over control-socket heartbeat frames in C++
(``csrc/hvd/controller.cc``).

Everything is deterministic under an injectable clock: the chaos
acceptance ("survivors begin re-rendezvous within 2x
``HOROVOD_LIVENESS_TIMEOUT_MS``") is asserted with a fake clock in
tier-1, no real sleeping.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Hashable, List, Optional, Tuple

from . import config as _config

# Member states. DRAINING members are exempt from eviction for the drain
# grace (they announced a clean departure and get to finish it); DRAINED
# and EVICTED are terminal.
ALIVE = "ALIVE"
SUSPECT = "SUSPECT"
EVICTED = "EVICTED"
DRAINING = "DRAINING"
DRAINED = "DRAINED"

# Event kinds produced by LivenessTracker.check().
MISS = "MISS"        # silence past 2x the heartbeat interval (informational)
SUSPECT_EVENT = "SUSPECT"   # silence past half the liveness timeout
EVICT = "EVICT"      # silence past the full liveness timeout
RECOVER = "RECOVER"  # a SUSPECT member beat again before eviction


class LivenessEvent:
    """One escalation step for one member."""

    __slots__ = ("kind", "member", "silence_ms")

    def __init__(self, kind: str, member: Hashable, silence_ms: float):
        self.kind = kind
        self.member = member
        self.silence_ms = silence_ms

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"LivenessEvent({self.kind}, {self.member}, "
                f"{self.silence_ms:.0f}ms)")


class LivenessTracker:
    """Per-member last-seen tracking with miss → SUSPECT → EVICT
    escalation.

    Thresholds (all from one ``liveness_timeout_ms``):

    - ``MISS``     at ``2 * heartbeat_ms`` of silence (one beat lost plus
      slack — scheduling jitter alone must not page anyone);
    - ``SUSPECT``  at ``liveness_timeout_ms / 2``;
    - ``EVICT``    at ``liveness_timeout_ms``.

    ``clock`` returns seconds (``time.monotonic`` signature) and is
    injectable so every transition is testable deterministically. The
    tracker never sleeps and never spawns threads — callers poll
    ``check()`` at their own cadence (the driver piggybacks on its 1 s
    discovery loop; detection latency is bounded by timeout + one poll
    tick, comfortably inside the 2x-timeout acceptance window).
    """

    def __init__(self, heartbeat_ms: Optional[int] = None,
                 timeout_ms: Optional[int] = None,
                 drain_grace_ms: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.heartbeat_ms = (heartbeat_ms if heartbeat_ms is not None
                             else _config.heartbeat_ms())
        self.timeout_ms = (timeout_ms if timeout_ms is not None
                           else _config.liveness_timeout_ms())
        self.drain_grace_ms = (drain_grace_ms if drain_grace_ms is not None
                               else _config.drain_grace_ms())
        self._clock = clock
        self._last_seen: Dict[Hashable, float] = {}
        self._state: Dict[Hashable, str] = {}
        self._missed: Dict[Hashable, bool] = {}
        self._drain_deadline: Dict[Hashable, float] = {}

    # -- membership ----------------------------------------------------------

    def watch(self, member: Hashable) -> None:
        """Start tracking ``member`` (idempotent); the watch itself counts
        as a beat — a rank must get a full quiet window from admission
        before any escalation."""
        if member not in self._state:
            self._state[member] = ALIVE
            self._last_seen[member] = self._clock()
            self._missed[member] = False

    def forget(self, member: Hashable) -> None:
        self._state.pop(member, None)
        self._last_seen.pop(member, None)
        self._missed.pop(member, None)
        self._drain_deadline.pop(member, None)

    def members(self) -> List[Hashable]:
        return list(self._state)

    def state(self, member: Hashable) -> Optional[str]:
        return self._state.get(member)

    # -- signals -------------------------------------------------------------

    def beat(self, member: Hashable) -> Optional[LivenessEvent]:
        """Record a heartbeat. Returns a RECOVER event when it rescues a
        SUSPECT member; terminal states (EVICTED/DRAINED) stay terminal —
        a zombie's late beat must not resurrect its slot."""
        self.watch(member)
        st = self._state[member]
        if st in (EVICTED, DRAINED):
            return None
        now = self._clock()
        self._last_seen[member] = now
        self._missed[member] = False
        if st == SUSPECT:
            self._state[member] = ALIVE
            return LivenessEvent(RECOVER, member, 0.0)
        return None

    def mark_draining(self, member: Hashable) -> None:
        """The member announced a graceful drain: exempt from eviction
        while it finishes — but only for the drain grace (plus slack
        for the announcement's own latency). A drain whose host died
        outright mid-protocol (power loss: no commit marker, no exit)
        must still be bounded, or the 'graceful' path would reintroduce
        the unbounded hang this plane exists to kill."""
        self.watch(member)
        if self._state[member] not in (EVICTED, DRAINED, DRAINING):
            self._state[member] = DRAINING
            self._drain_deadline[member] = self._clock() + \
                2.0 * self.drain_grace_ms / 1000.0

    def mark_drained(self, member: Hashable) -> None:
        self.watch(member)
        self._state[member] = DRAINED

    def suspect(self, member: Hashable,
                silence_ms: float = 0.0) -> Optional[LivenessEvent]:
        """Externally-sourced suspicion (the stall inspector's escalation
        path): mark ``member`` SUSPECT through the same machine a
        heartbeat miss uses."""
        self.watch(member)
        if self._state[member] != ALIVE:
            return None
        self._state[member] = SUSPECT
        return LivenessEvent(SUSPECT_EVENT, member, silence_ms)

    # -- escalation ----------------------------------------------------------

    def check(self) -> List[LivenessEvent]:
        """One escalation pass; returns the transitions it caused, in
        deterministic member order. Call at any cadence."""
        now = self._clock()
        events: List[LivenessEvent] = []
        for member in sorted(self._state, key=repr):
            st = self._state[member]
            if st == DRAINING:
                deadline = self._drain_deadline.get(member, now)
                if now >= deadline:
                    # The drain outlived 2x its grace: the host died
                    # mid-protocol. Evict — the exit-time commit-marker
                    # check still wins if a commit actually landed.
                    self._state[member] = EVICTED
                    events.append(LivenessEvent(
                        EVICT, member,
                        (now - self._last_seen[member]) * 1000.0))
                continue
            if st in (EVICTED, DRAINED):
                continue
            silence_ms = (now - self._last_seen[member]) * 1000.0
            if silence_ms >= self.timeout_ms:
                self._state[member] = EVICTED
                events.append(LivenessEvent(EVICT, member, silence_ms))
                continue
            if st == ALIVE and silence_ms >= self.timeout_ms / 2.0:
                self._state[member] = SUSPECT
                events.append(
                    LivenessEvent(SUSPECT_EVENT, member, silence_ms))
                continue
            if (st == ALIVE and not self._missed[member]
                    and self.heartbeat_ms > 0
                    and silence_ms >= 2.0 * self.heartbeat_ms):
                self._missed[member] = True
                events.append(LivenessEvent(MISS, member, silence_ms))
        return events


def enabled() -> bool:
    """Whether the liveness plane is armed in this process
    (``HOROVOD_HEARTBEAT_MS`` > 0; default off — byte-identical to the
    pre-liveness runtime when unset)."""
    return _config.heartbeat_ms() > 0


LivenessMember = Tuple[str, int]  # (hostname, local_rank) — a slot identity
