"""Bucketed fusion planner — tensor fusion v2 for the XLA plane.

The v1 XLA-plane fusion (``ops/xla.py _grouped``) concatenates the whole
gradient list into ONE fused buffer per dtype. That single AllReduce
data-depends on the *last* gradient backprop produces, so XLA's scheduler
cannot launch any communication until the backward pass has fully
finished — exactly the serialization the reference's background fusion
cycle exists to avoid (reference ``tensor_fusion`` docs; PAPER.md §7).

This module is the shared planner: a pure function over (byte-size,
dtype) specs that returns size-capped, dtype-pure buckets in **reverse
parameter order** — the approximation of backward production order that
PyTorch DDP's ``bucket_cap_mb`` gradient bucketing and ZeRO's bucketed
reduce-scatter use on the GPU side. Each bucket's collective depends only
on that bucket's gradients, so XLA can overlap bucket k's AllReduce with
the computation of bucket k+1's gradients.

Consumers:

- ``ops/xla.py grouped_allreduce / grouped_hierarchical_allreduce``
  (``bucket_cap_bytes=`` path): one AllReduce per bucket.
- ``opt.py DistributedOptimizer`` / ``training.py make_train_step``:
  cap plumbed from ``HOROVOD_FUSION_THRESHOLD`` (the same knob the host
  plane's cycle fusion consumes), default "auto".
- ``zero.py``: the reduce-scatter/all-gather flat layout is built
  per-bucket so shard exchange overlaps backward the same way.
- ``common/parameter_manager.py``: the autotuner's fusion-threshold
  search drives this cap too, so one tuner governs both planes.

The planner is deliberately static and pure — under ``jit`` it runs at
trace time on shape/dtype metadata only, so bucketing never adds runtime
work beyond the collectives it restructures.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

__all__ = [
    "Bucket",
    "plan_buckets",
    "plan_buckets_for",
    "forward_bucket_order",
    "leaf_nbytes",
    "resolve_bucket_cap",
    "resolve_prefetch_depth",
    "describe_plan",
]


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One fusion bucket: the leaf indices it covers (in emission order),
    their common dtype (as a string key; "mixed" never occurs — buckets
    are dtype-pure by construction), and its payload size in bytes."""

    indices: Tuple[int, ...]
    dtype: Any
    nbytes: int


def leaf_nbytes(leaf) -> int:
    """Byte size of an array-like or abstract value (works on tracers)."""
    size = 1
    for d in leaf.shape:
        size *= int(d)
    return size * leaf.dtype.itemsize


# Low-precision floats are accumulated — and therefore travel the wire —
# at fp32 (ops/xla.py allreduce; zero.py flattens to fp32 masters).
_FP32_WIRE_DTYPES = ("bfloat16", "float16")


def leaf_wire_nbytes(leaf, compression=None) -> int:
    """Bytes the leaf actually occupies in the fused collective: the
    compressed wire dtype's width when ``compression`` (a resolved
    ``common/compression.Compressor``) applies to the leaf, else fp32
    width for bf16/fp16 (the accumulation dtype), else native width.
    The cap is a *wire* budget — planning on storage bytes would make one
    ``HOROVOD_FUSION_THRESHOLD`` mean 2x different effective bucket sizes
    between a bf16 data-parallel allreduce and ZeRO's fp32 scatter; the
    same argument makes a compressed plan budget f16/bf16 widths, so one
    threshold keeps meaning wire bytes with compression on."""
    import numpy as np

    size = 1
    for d in leaf.shape:
        size *= int(d)
    if compression is not None:
        w = compression.wire_dtype(leaf.dtype)
        if w is not None:
            return size * np.dtype(w).itemsize
    item = 4 if str(leaf.dtype) in _FP32_WIRE_DTYPES else leaf.dtype.itemsize
    return size * item


def plan_buckets(
    sizes_bytes: Sequence[int],
    dtypes: Sequence[Any],
    bucket_cap_bytes: Optional[int] = None,
) -> List[Bucket]:
    """Partition leaves ``0..n-1`` into fusion buckets.

    With ``bucket_cap_bytes`` unset (None or <= 0) the plan reproduces the
    v1 monolithic grouping exactly: one bucket per dtype, dtypes in
    first-seen order, indices ascending — byte-identical programs to the
    pre-bucketing ``_grouped`` fast path.

    With a cap, leaves are walked in REVERSE index order (parameter order
    approximates forward graph order, so reverse order approximates the
    order backprop produces gradients). A bucket closes when the next
    leaf would push it past the cap or has a different dtype (buckets
    stay dtype-pure AND contiguous in production order — an interleaved
    dtype reopening an old bucket would reintroduce the late dependency
    bucketing exists to break). A single leaf larger than the cap gets a
    bucket of its own.
    """
    n = len(sizes_bytes)
    if n != len(dtypes):
        raise ValueError(f"sizes/dtypes length mismatch: {n} vs {len(dtypes)}")
    if n == 0:
        return []

    if not bucket_cap_bytes or bucket_cap_bytes <= 0:
        by_dtype: dict = {}
        for i in range(n):
            key = _dtype_key(dtypes[i])
            by_dtype.setdefault(key, ([], dtypes[i]))[0].append(i)
        return [
            Bucket(tuple(idxs), dt, sum(sizes_bytes[i] for i in idxs))
            for idxs, dt in by_dtype.values()
        ]

    cap = int(bucket_cap_bytes)
    buckets: List[Bucket] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype: Any = None

    def close():
        nonlocal cur, cur_bytes, cur_dtype
        if cur:
            buckets.append(Bucket(tuple(cur), cur_dtype, cur_bytes))
        cur, cur_bytes, cur_dtype = [], 0, None

    for i in range(n - 1, -1, -1):
        nb = int(sizes_bytes[i])
        if cur and (_dtype_key(dtypes[i]) != _dtype_key(cur_dtype)
                    or cur_bytes + nb > cap):
            close()
        cur.append(i)
        cur_bytes += nb
        cur_dtype = dtypes[i]
        if cur_bytes >= cap:
            close()
    close()
    return buckets


def plan_buckets_for(leaves: Sequence[Any],
                     bucket_cap_bytes: Optional[int] = None,
                     compression=None) -> List[Bucket]:
    """Convenience overload: plan directly from array-likes / tracers,
    budgeting each leaf at its WIRE width (see ``leaf_wire_nbytes``,
    including the compressed dtype when ``compression`` is a resolved
    compressor) so the same cap means the same bucket sizes on every
    plane."""
    return plan_buckets([leaf_wire_nbytes(l, compression) for l in leaves],
                        [l.dtype for l in leaves], bucket_cap_bytes)


def forward_bucket_order(buckets: Sequence[Bucket]) -> Tuple[int, ...]:
    """The backward-order plan, run FORWARD: bucket indices ordered by
    their smallest leaf index, i.e. the order the forward pass consumes
    parameters. ``plan_buckets`` emits buckets in reverse parameter
    order (backward-production order, for gradient collectives); the
    ZeRO stage-3 parameter gathers walk the *same* buckets in this
    order, so the first bucket gathered is the first one the forward
    compute needs and a depth-p prefetch chain keeps at most p+1
    buckets' params gathered ahead of the compute front (docs/zero.md).
    For the monolithic per-dtype plan (no cap) this is first-seen dtype
    order — already forward order."""
    return tuple(sorted(range(len(buckets)),
                        key=lambda j: min(buckets[j].indices)
                        if buckets[j].indices else 0))


def resolve_prefetch_depth(depth="auto") -> int:
    """Resolve the stage-3 gather prefetch depth to a concrete int
    (clamped to [0, 8]).

    - ``"auto"`` (the plumbing default): the autotuned/explicit
      ``HOROVOD_ZERO_PREFETCH`` when one is in force — the live runtime
      config first (the autotuner pins its grid winner there), else the
      raw env — otherwise the default depth 1 (one bucket gathered
      ahead: overlap without unbounded gather memory).
    - an int: that depth (0 = fully serialized gathers).

    Unlike the bucket cap, depth never changes results — only the
    dataflow chain between gathers — so "auto" always yields a depth
    (there is no "unset disables the feature" case; stage 3 itself is
    the opt-in)."""
    if not isinstance(depth, str):
        return max(0, min(8, int(depth)))
    if depth != "auto":
        raise ValueError(
            f"prefetch depth must be an int or 'auto'; got {depth!r}")
    from . import config as _config
    from .state import global_state

    st = global_state()
    if (st.initialized and st.config is not None
            and getattr(st.config, "zero_prefetch_explicit", False)):
        return max(0, min(8, int(st.config.zero_prefetch)))
    v, _ = _config.zero_prefetch_env()
    return v


def _dtype_key(dtype: Any) -> str:
    return str(dtype)


def resolve_bucket_cap(bucket_cap_bytes) -> Optional[int]:
    """Resolve a user-facing cap knob to an int or None (monolithic).

    - ``"auto"`` (the plumbing default): the autotuned/explicit
      ``HOROVOD_FUSION_THRESHOLD`` when one is in force — the live
      runtime config when ``hvd.init()`` has run and the knob was set or
      tuned, else the raw env var — otherwise None. An *unset* knob keeps
      the v1 monolithic behavior byte-identical.
    - ``None`` / ``0``: monolithic (explicitly no bucketing).
    - int > 0: that many bytes.
    """
    if bucket_cap_bytes is None:
        return None
    if isinstance(bucket_cap_bytes, str):
        if bucket_cap_bytes != "auto":
            raise ValueError(
                f"bucket_cap_bytes must be an int, None, or 'auto'; "
                f"got {bucket_cap_bytes!r}")
        from . import config as _config
        from .state import global_state

        st = global_state()
        if (st.initialized and st.config is not None
                and getattr(st.config, "fusion_threshold_explicit", False)):
            v = int(st.config.fusion_threshold_bytes)
            return v if v > 0 else None
        # Same parser as RuntimeConfig.from_env (one owner for the env
        # var's int semantics); <= 0 normalizes to monolithic everywhere.
        v, explicit = _config._get_int_explicit(
            _config.HOROVOD_FUSION_THRESHOLD, 0)
        return v if explicit and v > 0 else None
    cap = int(bucket_cap_bytes)
    return cap if cap > 0 else None


def describe_plan(buckets: Sequence[Bucket]) -> dict:
    """JSON-friendly summary of a plan (bench/timeline attribution)."""
    return {
        "num_buckets": len(buckets),
        "bucket_bytes": [b.nbytes for b in buckets],
        "bucket_dtypes": [str(b.dtype) for b in buckets],
        "bucket_sizes": [len(b.indices) for b in buckets],
    }
