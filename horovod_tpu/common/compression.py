"""On-wire gradient compression — the shared compressor implementation.

Horovod's headline bandwidth lever after tensor fusion is wire
compression: ``hvd.Compression.fp16`` casts gradients to a 16-bit wire
format before the allreduce and back after (reference
``horovod/torch/compression.py:46``), halving collective bytes on
bandwidth-bound models. This module is the single implementation behind
every plane:

- **Compiled collectives** (``ops/xla.py``): ``allreduce`` /
  ``grouped_allreduce`` / ``hierarchical_allreduce`` take a
  ``compression`` argument and reduce *in the wire dtype* — the compiled
  HLO all-reduce operand is f16/bf16, so the ICI/DCN bytes actually
  halve — then accumulate post-reduction arithmetic (averaging,
  postscale) in fp32 before casting back.
- **Optimizer plane** (``opt.py`` / ``training.py`` / ``zero.py``):
  ``DistributedOptimizer(compression=...)`` and the ZeRO pair thread the
  compressor through the gradient exchange; the error-feedback variant
  keeps per-parameter fp32 residuals in the train state so quantization
  error is re-injected next step instead of lost (the EF-SGD /
  PyTorch-DDP bf16-comm-hook residual scheme, PAPERS.md).
- **Fusion planner** (``common/fusion.py``): bucket caps budget the
  *compressed* wire dtype, so one ``HOROVOD_FUSION_THRESHOLD`` value
  keeps meaning wire bytes whether or not compression is on.
- **Framework stubs** (``torch/compression.py``,
  ``tensorflow/compression.py``): built by
  ``make_framework_compression`` from the same cast policy, so there is
  one compressor implementation tree-wide.

Selection: ``HOROVOD_COMPRESSION`` env var (``none`` / ``fp16`` /
``bf16`` / ``ef16``), resolved by ``resolve_compression("auto")`` with
the same live-config-then-env precedence as the fusion threshold. With
the knob unset, every compiled program is byte-identical to the
uncompressed path — compression only engages when asked for.

Choosing a format (docs/compression.md): bf16 keeps fp32's exponent
range (no overflow, TPU-native) but only 8 mantissa bits; fp16 has more
mantissa but can overflow/underflow large or tiny gradients; ef16 is
fp16 plus error feedback, recovering convergence where plain fp16's
rounding stalls it, at the cost of one fp32 residual per parameter.
"""

from __future__ import annotations

from typing import Any, Optional

__all__ = [
    "Compressor",
    "NoneCompressor",
    "Fp16Compressor",
    "Bf16Compressor",
    "ErrorFeedbackCompressor",
    "Compression",
    "resolve_compression",
    "apply_error_feedback",
    "init_residual",
    "make_framework_compression",
]

# Canonical wire-format names shared by every binding (the one policy
# table: a compressor compresses floating tensors to its wire format and
# leaves integer/bool tensors untouched).
_WIRE_FORMATS = ("float16", "bfloat16")


class Compressor:
    """A wire-format compressor for the JAX/XLA plane.

    ``wire_dtype(dtype)`` is the compiled path's contract: the dtype a
    tensor of ``dtype`` travels at inside the collective, or None when
    the tensor is not compressed (non-float inputs, or the
    NoneCompressor). ``compress``/``decompress`` keep the reference's
    per-tensor ``(tensor, ctx)`` API for the eager/legacy paths.
    """

    name = "none"
    wire: Optional[str] = None  # canonical wire format name, or None
    error_feedback = False

    def wire_dtype(self, dtype):
        """Wire dtype for an input of ``dtype``, or None (uncompressed)."""
        if self.wire is None:
            return None
        import jax.numpy as jnp

        dt = jnp.dtype(dtype)
        if not jnp.issubdtype(dt, jnp.floating):
            return None
        return jnp.dtype(self.wire)

    def compress(self, tensor):
        w = self.wire_dtype(tensor.dtype)
        if w is None or w == tensor.dtype:
            return tensor, None
        return tensor.astype(w), tensor.dtype

    def decompress(self, tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"


class NoneCompressor(Compressor):
    """Identity: tensors travel at their accumulation dtype (bf16/fp16
    inputs upcast to fp32 on the wire — the uncompressed contract)."""

    name = "none"


class Fp16Compressor(Compressor):
    """float16 wire format. More mantissa than bf16 but a narrow
    exponent: very large/tiny gradients can overflow/flush — pair with
    ``ef16`` (error feedback) when that stalls convergence."""

    name = "fp16"
    wire = "float16"


class Bf16Compressor(Compressor):
    """bfloat16 wire format — TPU-native. fp32's exponent range (no
    overflow scaling needed), 8 mantissa bits."""

    name = "bf16"
    wire = "bfloat16"


class ErrorFeedbackCompressor(Compressor):
    """Wraps a wire compressor with error feedback: the caller keeps a
    per-parameter fp32 residual in train state, adds it to the gradient
    before quantization, and stores back the quantization error
    (``corrected - Q(corrected)``) so low-precision rounding is
    re-injected next step instead of lost (EF-SGD; PyTorch DDP's bf16
    comm hook ships the same residual scheme).

    The wrapper itself is stateless — state lives in the optimizer /
    ZeRO train state (``opt.py DistributedState.residual``,
    ``zero.py ZeroTrainState.residual``); ``apply_error_feedback`` is
    the shared correct/quantize/residual-update step.
    """

    error_feedback = True

    def __init__(self, inner: Compressor, name: Optional[str] = None):
        if inner.wire is None:
            raise ValueError("error feedback needs a lossy wire format; "
                             "wrapping the NoneCompressor is meaningless")
        self.inner = inner
        self.name = name or f"ef-{inner.name}"

    @property
    def wire(self):  # type: ignore[override]
        return self.inner.wire

    def wire_dtype(self, dtype):
        return self.inner.wire_dtype(dtype)


class Compression:
    """Option namespace (parity: reference ``Compression.none`` /
    ``Compression.fp16``), JAX-native. ``ef16`` is fp16 with error
    feedback — requires the optimizer plane (it needs residual state);
    the raw collectives treat it as its fp16 wire format."""

    none = NoneCompressor()
    fp16 = Fp16Compressor()
    bf16 = Bf16Compressor()
    ef16 = ErrorFeedbackCompressor(Fp16Compressor(), name="ef16")


_BY_NAME = {
    "none": None,
    "fp16": Compression.fp16,
    "bf16": Compression.bf16,
    "ef16": Compression.ef16,
}

COMPRESSION_NAMES = tuple(_BY_NAME)


def resolve_compression(compression="auto") -> Optional[Compressor]:
    """Resolve a user-facing compression knob to a Compressor or None.

    - ``"auto"`` (the plumbing default): the live runtime config when
      ``hvd.init()`` has run and ``HOROVOD_COMPRESSION`` was explicitly
      set (or the autotuner pinned a mode), else the raw env var —
      otherwise None. An *unset* knob keeps every program byte-identical
      to the uncompressed path (the same contract as the fusion
      threshold's "auto").
    - ``None`` / ``"none"`` / ``Compression.none``: no compression.
    - ``"fp16"`` / ``"bf16"`` / ``"ef16"``: the named compressor.
    - a ``Compressor`` instance: itself.
    """
    if compression is None:
        return None
    if isinstance(compression, Compressor):
        return None if isinstance(compression, NoneCompressor) else compression
    if isinstance(compression, str):
        name = compression
        if name == "auto":
            from . import config as _config
            from .state import global_state

            st = global_state()
            if (st.initialized and st.config is not None
                    and getattr(st.config, "compression_explicit", False)):
                name = st.config.compression
            else:
                name = _config.parse_compression_env()
        if name not in _BY_NAME:
            raise ValueError(
                f"unknown compression {name!r}; expected one of "
                f"{sorted(_BY_NAME)} or 'auto'")
        return _BY_NAME[name]
    if hasattr(compression, "compress"):
        raise TypeError(
            f"{compression!r} looks like a framework compressor stub "
            f"(torch/tensorflow Compression); the XLA plane takes "
            f"horovod_tpu.Compression.{{none,fp16,bf16,ef16}} or the "
            f"name as a string")
    raise TypeError(f"cannot resolve compression from {compression!r}")


def apply_error_feedback(compressor: ErrorFeedbackCompressor, grads,
                         residual):
    """One error-feedback step over a gradient pytree.

    Returns ``(wire_grads, new_residual)``: per leaf,
    ``corrected = grad(fp32) + residual``; ``wire = Q(corrected)`` in
    the inner compressor's wire dtype; ``new_residual = corrected -
    wire(fp32)``. Leaves the wire format does not apply to (ints) pass
    through with a zero residual. The caller reduces ``wire_grads`` (in
    the wire dtype — that is the on-wire saving) and owns persisting
    ``new_residual`` in its state.
    """
    import jax
    import jax.numpy as jnp

    # Two passes (quantize, then residual) rather than one tuple-valued
    # tree_map: a gradient pytree may itself contain tuples, which an
    # is_leaf=tuple transpose would mistake for result pairs. The
    # recomputed `corrected` is CSE'd away under jit.
    def quantize(g, r):
        w = compressor.wire_dtype(g.dtype)
        if w is None:
            return g
        return (g.astype(jnp.float32) + r).astype(w)

    def new_residual(g, r):
        w = compressor.wire_dtype(g.dtype)
        if w is None:
            return jnp.zeros_like(r)
        corrected = g.astype(jnp.float32) + r
        return corrected - corrected.astype(w).astype(jnp.float32)

    wire = jax.tree_util.tree_map(quantize, grads, residual)
    new_res = jax.tree_util.tree_map(new_residual, grads, residual)
    return wire, new_res


def init_residual(params):
    """fp32 zero residuals matching a parameter/gradient pytree (the
    error-feedback state; one fp32 scalar per parameter element)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)


# ---- framework stub factory -------------------------------------------------


def make_framework_compression(cast, is_floating):
    """Build the reference-compatible ``Compression`` namespace for a
    framework binding from two primitives: ``cast(tensor, dtype)`` (where
    dtype is a canonical wire-format name or a framework dtype captured
    as ctx) and ``is_floating(tensor)``.

    This is the one implementation behind ``torch/compression.py`` and
    ``tensorflow/compression.py`` — the stubs only supply the cast.
    Returned namespace: ``Compression.none/fp16/bf16`` are classes with
    the reference's static ``compress(tensor) -> (tensor, ctx)`` /
    ``decompress(tensor, ctx)`` API; the interface base is attached as
    ``Compression.Compressor``.
    """

    class Compressor:
        """Interface: ``compress(tensor) -> (tensor, ctx)``,
        ``decompress(tensor, ctx) -> tensor``."""

        @staticmethod
        def compress(tensor):
            raise NotImplementedError

        @staticmethod
        def decompress(tensor, ctx):
            raise NotImplementedError

    def _make(wire_name):
        class _WireCompressor(Compressor):
            @staticmethod
            def compress(tensor):
                if is_floating(tensor):
                    return cast(tensor, wire_name), tensor.dtype
                return tensor, None

            @staticmethod
            def decompress(tensor, ctx):
                return cast(tensor, ctx) if ctx is not None else tensor

        _WireCompressor.__name__ = (
            "FP16Compressor" if wire_name == "float16" else "BF16Compressor")
        return _WireCompressor

    class NoneCompressor(Compressor):
        @staticmethod
        def compress(tensor):
            return tensor, None

        @staticmethod
        def decompress(tensor, ctx):
            return tensor

    class Compression:
        """Option namespace (parity: ``Compression.none`` /
        ``Compression.fp16``); bf16 is the TPU-native extension (fp32
        exponent range, no loss-scaling needed)."""

    Compression.Compressor = Compressor
    Compression.none = NoneCompressor
    Compression.fp16 = _make("float16")
    Compression.bf16 = _make("bfloat16")
    return Compression
