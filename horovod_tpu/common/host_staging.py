"""Host-tensor staging through the XLA plane (``HOROVOD_HOST_VIA_XLA=1``).

On a pod, a PyTorch/TensorFlow script's gradients are host tensors; by
default they cross hosts on the native TCP ring. This executor gives them
the fast fabric: the native cycle routes large fused host allreduces here
(``hvd_set_host_via_xla``), the fused buffer is staged to a device, one
compiled psum over a one-device-per-process mesh runs the reduction over
ICI/DCN, and the result is copied back into the framework tensors' output
buffers. The reference's GPU staging paths play this role on NVLink/IB
(``torch/mpi_ops_v2.cc:81`` DoAllreduceCudaOnCPU, hierarchical
``nccl_operations.cc:164-357``).

Activation: ``HostWorld.init`` calls :func:`activate` when the env knob is
set and the process world is multi-process. The executor replaces the host
world's reject-XLA callback; host-plane responses below the byte threshold
(``HOROVOD_HOST_VIA_XLA_THRESHOLD``, default 1 MiB) keep riding the ring —
small tensors aren't worth the host<->device hops.
"""

from __future__ import annotations

import contextlib
import ctypes
import queue
import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

from . import config as _config
from .compat import (distributed_is_initialized,
                     shard_map as _compat_shard_map)
from . import logging as _log
from . import native as _native

def _np_from_code(code):
    """Native dtype code -> the numpy dtype staging computes in.
    bfloat16 resolves through ml_dtypes' numpy registration (present with
    jax installed); bool maps to byte-identical uint8 — staging only ever
    moves or zero-sums bool data (the C++ guard keeps bool *allreduce* on
    the ring), and psum has no bool flavor."""
    for name, c in _native.DTYPE_CODES.items():
        if c == code:
            if name == "bfloat16":
                import ml_dtypes

                return np.dtype(ml_dtypes.bfloat16)
            if name == "bool":
                return np.dtype(np.uint8)
            return np.dtype(name)
    return np.dtype(np.float32)

# ReduceOp codes (ops/xla.py ReduceOp / csrc common.h, identical).
_OP_AVERAGE = 0
_OP_SUM = 1
_OP_MIN = 3
_OP_MAX = 4

# Compiled staging programs kept per (op, shape, dtype, ...) key; ragged
# workloads can produce many distinct shapes, so the cache is a bounded
# LRU rather than an append-only dict.
_PROGRAM_CACHE_CAP = 128


def _bcast_plan(n, p):
    """Ring-pipelined broadcast schedule for an ``n``-element payload over
    ``p`` ranks: split into C chunks; at step s the rank at chain position
    q (``(rank - root) % p``) forwards chunk ``s - q`` to position q+1.
    Every link carries ``steps = C + p - 2`` chunks of ``ceil(n/C)``
    elements, so per-link traffic approaches 1x the payload for C >> p —
    the psum-of-zeros broadcast this replaces moves ~2x (reduce-scatter +
    all-gather), and the reference's NCCL path is a true ~1x broadcast
    (``nccl_operations.cc:369``). C is capped so chunks stay >= 128
    elements (sub-cacheline ppermutes buy nothing but latency).

    Returns ``(num_chunks, chunk_elems, padded_elems, steps)``.
    """
    n = max(int(n), 1)
    if p <= 1:
        return 1, n, n, 0
    num_chunks = max(1, min(8 * (p - 1), (n + 127) // 128))
    chunk = (n + num_chunks - 1) // num_chunks
    return num_chunks, chunk, chunk * num_chunks, num_chunks + p - 2


class HostStagingExecutor:
    """Executor thread + compiled psum programs over the process mesh."""

    def __init__(self, world, core):
        self._world = world
        self._core = core
        self._mesh = None
        self._programs = OrderedDict()  # LRU, capped
        self._timeline = None
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread: Optional[threading.Thread] = None
        self._threshold = -1
        self._closed = False

    # -- activation ----------------------------------------------------------

    def activate(self) -> bool:
        """Join/build the device world and start serving. False (with a
        log line) when no usable per-process device mesh exists."""
        import jax

        world = self._world
        if world.size > 1 and not distributed_is_initialized():
            addr = _config.controller_addr()
            port = _config.controller_base_port()
            try:
                jax.distributed.initialize(
                    coordinator_address=f"{addr}:{port}",
                    num_processes=world.size, process_id=world.rank)
            # hvdlint: ignore[exception-discipline] -- activation probe:
            # any failure (never a collective's) degrades to the ring
            except Exception as e:
                _log.warning(
                    f"HOROVOD_HOST_VIA_XLA: jax.distributed init failed "
                    f"({e}); host tensors stay on the TCP ring")
                return False
        try:
            per_proc = {}
            for d in jax.devices():
                per_proc.setdefault(d.process_index, d)
        # hvdlint: ignore[exception-discipline] -- activation probe: no
        # collective has run yet; failure degrades to the ring
        except Exception as e:
            _log.warning(f"HOROVOD_HOST_VIA_XLA: no device backend ({e}); "
                         "host tensors stay on the TCP ring")
            return False
        if len(per_proc) != world.size:
            _log.warning(
                f"HOROVOD_HOST_VIA_XLA: device world spans "
                f"{len(per_proc)} processes but the host world has "
                f"{world.size}; host tensors stay on the TCP ring")
            return False

        from jax.sharding import Mesh

        devices = [per_proc[i] for i in sorted(per_proc)]
        self._mesh = Mesh(np.array(devices, dtype=object), ("proc",))

        if world.size > 1:
            # Capability probe: some backends enumerate a multi-process
            # device world but cannot COMPILE cross-process programs
            # (jax < 0.5's CPU backend: "Multiprocess computations aren't
            # implemented"). Prove one tiny psum compiles before going
            # live — otherwise every staged collective would fail after
            # routing already left the ring. COMPILE-ONLY on purpose:
            # compilation is process-local, while *executing* a probe
            # collective here would deadlock whenever a peer bailed out
            # of activation earlier (env drift; init failure) — the
            # stage-vs-ring agreement vote only happens after activate()
            # returns, so no cross-rank rendezvous is safe yet.
            try:
                from jax import lax
                from jax.sharding import PartitionSpec as P

                probe = jax.jit(_compat_shard_map(
                    lambda x: lax.psum(x, "proc"), self._mesh,
                    in_specs=P("proc"), out_specs=P(), check_vma=False))
                sharding = jax.sharding.NamedSharding(self._mesh, P("proc"))
                arr = jax.make_array_from_process_local_data(
                    sharding, np.ones((1,), np.float32), (world.size,))
                probe.lower(arr).compile()
            # hvdlint: ignore[exception-discipline] -- capability probe
            # (compile-only, process-local); failure degrades to the ring
            except Exception as e:
                _log.warning(
                    f"HOROVOD_HOST_VIA_XLA: backend cannot compile "
                    f"cross-process programs ({e}); host tensors stay on "
                    f"the TCP ring")
                return False

        cfg = _config.RuntimeConfig.from_env()
        if cfg.timeline_filename and world.rank == 0:
            from .timeline import Timeline

            self._timeline = Timeline(cfg.timeline_filename)

        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hvd-host-staging")
        self._thread.start()
        self._core.register_exec_callback(self._on_responses)
        self._threshold = cfg.host_via_xla_threshold
        return True

    def enable_routing(self):
        """Flip the native cycle to route large host responses here. Only
        call after ALL processes agreed to stage (see maybe_activate) —
        the stage-vs-ring decision must be global or the world deadlocks
        (staged ranks wait in the psum, ring ranks wait on the ring)."""
        self._core.set_host_via_xla(self._threshold)
        _log.info(
            f"HOROVOD_HOST_VIA_XLA active: fused host allreduces >= "
            f"{self._threshold} bytes ride the XLA plane over "
            f"{self._world.size} processes")

    def close(self):
        """Stop the executor thread (sentinel) and close the timeline.
        Re-installs a reject callback first — activate() took the exec
        slot from the host world's reject-XLA placeholder, and leaving
        the staging trampoline pointed at a queue no thread drains would
        turn later XLA-plane responses into silent hangs instead of fast
        failures (round-3 advisor finding)."""
        self._closed = True
        core = self._core

        def _reject(responses, rid):
            core.response_done(rid, False, "host staging executor closed")

        core.register_exec_callback(_reject)
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout=5.0)
        # Fail anything that slipped into the queue between the executor
        # thread exiting and the reject callback taking over.
        drained_sentinel = False
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                drained_sentinel = True
            else:
                core.response_done(item[1], False,
                                   "host staging executor closed")
        if drained_sentinel and self._thread is not None and \
                self._thread.is_alive():
            # join() timed out (thread wedged mid-collective) and the
            # drain ate its shutdown sentinel; put one back so the thread
            # exits if it ever unwedges instead of blocking forever.
            self._q.put(None)
        if self._timeline is not None:
            self._timeline.close()
            self._timeline = None

    # -- compiled-program LRU ------------------------------------------------

    def _prog_get(self, key):
        prog = self._programs.get(key)
        if prog is not None:
            self._programs.move_to_end(key)
        return prog

    def _prog_put(self, key, prog):
        self._programs[key] = prog
        if len(self._programs) > _PROGRAM_CACHE_CAP:
            self._programs.popitem(last=False)

    # -- native callback (cycle thread: enqueue only) ------------------------

    def _on_responses(self, responses, response_id):
        self._q.put((responses, response_id))

    # -- executor thread -----------------------------------------------------

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return  # close() sentinel
            responses, response_id = item
            if self._closed:
                self._core.response_done(response_id, False,
                                         "staging executor closed")
                continue
            try:
                for resp in responses:
                    self._execute(resp, response_id)
                self._core.response_done(response_id, True)
            # hvdlint: ignore[exception-discipline] -- not swallowed:
            # response_done(ok=False) IS the host plane's error channel
            # (every waiting rank raises HorovodInternalError from it)
            except Exception as e:
                _log.error(f"host staging executor failure: {e}")
                self._core.response_done(response_id, False, str(e))

    @contextlib.contextmanager
    def _activity(self, names, activity):
        """Timeline span over every tensor of a response (no-op without
        a timeline); closed in finally so failures don't leak open
        spans."""
        if self._timeline:
            for n in names:
                self._timeline.start_activity(n, activity)
        try:
            yield
        finally:
            if self._timeline:
                for n in names:
                    self._timeline.end_activity(n, activity)

    def _execute(self, resp, response_id):
        if resp.plane != _native.PLANE_HOST or \
                resp.op not in (_native.OP_ALLREDUCE, _native.OP_BROADCAST,
                                _native.OP_ALLGATHER):
            raise _native_error(
                f"host staging executor got unexpected response "
                f"(plane={resp.plane}, op={resp.op})")
        if resp.op == _native.OP_ALLGATHER:
            return self._execute_allgather(resp, response_id)
        is_bcast = resp.op == _native.OP_BROADCAST
        activity = "XLA_BROADCAST" if is_bcast else "XLA_ALLREDUCE"
        dtype = _np_from_code(resp.dtype)
        counts = [int(np.prod(s)) if s else 1 for s in resp.shapes]
        total = sum(counts)

        with self._activity(resp.names, activity):
            # Fuse into one flat host buffer in the response's canonical
            # order; a joined rank's missing slots stay zero (the
            # reference AllocateZeros join path). Broadcast runs a real
            # ring-pipelined broadcast (~1x bytes per link; see
            # _bcast_plan) — non-root ranks still fill zeros, they are
            # simply overwritten by the root's chunks.
            contribute = not is_bcast or resp.root_rank == self._world.rank
            fused = np.zeros((total,), dtype)
            views = {}
            off = 0
            for name, count in zip(resp.names, counts):
                ptrs = self._core.inflight_ptrs(response_id, name)
                if ptrs is not None:
                    data_ptr, out_ptr = ptrs
                    if contribute:
                        fused[off:off + count] = _as_array(data_ptr, count,
                                                           dtype)
                    views[name] = (off, count,
                                   _as_array(out_ptr or data_ptr, count,
                                             dtype))
                off += count

            if is_bcast:
                reduced = self._broadcast(fused, resp.root_rank)
            else:
                reduced = self._allreduce(fused, resp.reduce_op,
                                          resp.prescale, resp.postscale)

            for name, (off, count, out_view) in views.items():
                np.copyto(out_view, reduced[off:off + count])

    def _execute_allgather(self, resp, response_id):
        """Staged allgatherv: ALL of the fused response's tensors pack
        into ONE flat buffer (per-tensor regions padded to that tensor's
        global max), one compiled all_gather over the process mesh moves
        it, then per-tensor/per-rank slices deposit via hvd_store_result
        (the same fetch path ring-produced ragged results use). Pure data
        movement, so every dtype the 32-bit canonicalization allows
        stages (bool as bytes); fused responses share one dtype by the
        fusion rules, so one buffer serves the whole response."""
        rank = self._world.rank
        size = self._world.size
        dtype = _np_from_code(resp.dtype)

        with self._activity(resp.names, "XLA_ALLGATHER"):
            # Region plan: (name, offset, counts, fd, ptrs) per tensor.
            regions = []
            off = 0
            for i, name in enumerate(resp.names):
                shape = resp.shapes[i]
                trailing = int(np.prod(shape[1:])) if len(shape) > 1 else 1
                fd = (resp.first_dims[i]
                      if i < len(resp.first_dims) and resp.first_dims[i]
                      else ((shape[0] if shape else 1,) * size))
                counts = [int(d) * trailing for d in fd]
                ptrs = self._core.inflight_ptrs(response_id, name)
                regions.append((name, off, counts, fd, ptrs))
                off += max(int(d) for d in fd) * trailing

            # Bucket the padded length proportionally (~12.5% quantum,
            # never below a 128-element lane): the pow2 bucketing this
            # replaces transferred up to ~2x the bytes, while EXACT
            # rounding would compile a distinct program per fused length
            # and thrash the LRU on ragged workloads. Proportional
            # buckets cap padding at ~12.5% and distinct programs at ~16
            # per size octave.
            quantum = max(128, 1 << max(0, off.bit_length() - 4))
            bucket = max(quantum, (off + quantum - 1) // quantum * quantum)
            buf = np.zeros((bucket,), dtype)
            for name, roff, counts, fd, ptrs in regions:
                if ptrs is not None:
                    buf[roff:roff + counts[rank]] = _as_array(
                        ptrs[0], counts[rank], dtype)

            gathered = self._allgather(buf)          # [size, bucket]

            for name, roff, counts, fd, ptrs in regions:
                if ptrs is None:
                    continue  # joined rank's missing slot
                out = np.concatenate(
                    [gathered[r, roff: roff + counts[r]]
                     for r in range(size)])
                if ptrs[1]:
                    # Caller-preallocated output (equal-shape fast path).
                    np.copyto(_as_array(ptrs[1], out.shape[0], dtype), out)
                else:
                    handle = self._core.inflight_handle(response_id, name)
                    if handle >= 0:
                        self._core.store_result(handle, out.tobytes(),
                                                tuple(int(d) for d in fd))

    def _broadcast(self, fused, root):
        """Ring-pipelined broadcast of root's buffer to every process
        (schedule: _bcast_plan). Chunks hop position-to-position via
        ppermute inside one fori_loop, each link carrying ~1x the payload
        — vs ~2x for the psum-of-zeros formulation this replaced."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        P_devices = self._world.size
        n = fused.shape[0]
        key = ("bc", n, str(fused.dtype), root)
        prog = self._prog_get(key)
        if prog is None:
            prog = build_ring_broadcast(self._mesh, n, root, P_devices)
            self._prog_put(key, prog)

        sharding = NamedSharding(self._mesh, P("proc"))
        arr = jax.make_array_from_process_local_data(
            sharding, fused[None], (P_devices,) + fused.shape)
        out = prog(arr)
        return np.asarray(list(out.addressable_shards)[0].data[0])

    def _allgather(self, buf):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        P_devices = self._world.size
        key = ("ag", buf.shape[0], str(buf.dtype))
        prog = self._prog_get(key)
        if prog is None:
            from jax import lax

            mesh = self._mesh

            def fn(x):
                return lax.all_gather(x[0], "proc")  # [P, n], replicated

            prog = jax.jit(_compat_shard_map(
                fn, mesh=mesh, in_specs=P("proc"), out_specs=P(),
                check_vma=False))
            self._prog_put(key, prog)

        sharding = NamedSharding(self._mesh, P("proc"))
        arr = jax.make_array_from_process_local_data(
            sharding, buf[None], (P_devices,) + buf.shape)
        out = prog(arr)
        return np.asarray(list(out.addressable_shards)[0].data)

    def _allreduce(self, fused, reduce_op, prescale, postscale):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        P_devices = self._world.size
        # Accumulate 16-bit floats in fp32 (the ring and the XLA eager
        # plane both do).
        upcast = fused.dtype.kind == "f" and fused.dtype.itemsize == 2
        key = (fused.shape[0], str(fused.dtype), reduce_op, prescale,
               postscale)
        prog = self._prog_get(key)
        if prog is None:
            mesh = self._mesh

            def fn(x):
                y = x[0]
                if upcast:
                    y = y.astype(jnp.float32)
                if prescale != 1.0:
                    y = y * prescale
                if reduce_op == _OP_MIN:
                    y = lax.pmin(y, "proc")
                elif reduce_op == _OP_MAX:
                    y = lax.pmax(y, "proc")
                else:
                    y = lax.psum(y, "proc")
                    if reduce_op == _OP_AVERAGE:
                        y = y / P_devices
                if postscale != 1.0:
                    y = y * postscale
                return y.astype(x.dtype)[None]

            prog = jax.jit(_compat_shard_map(
                fn, mesh=mesh, in_specs=P("proc"), out_specs=P("proc"),
                check_vma=False))
            self._prog_put(key, prog)

        sharding = NamedSharding(self._mesh, P("proc"))
        global_shape = (P_devices,) + fused.shape
        arr = jax.make_array_from_process_local_data(
            sharding, fused[None], global_shape)
        out = prog(arr)
        # This process's shard is the reduced buffer (replicated content
        # across shards by construction of the allreduce).
        return np.asarray(list(out.addressable_shards)[0].data[0])


def build_ring_broadcast(mesh, n, root, p, axis="proc"):
    """Compile the ring-pipelined broadcast program over ``mesh``'s
    ``axis`` (size ``p``): input/output are ``[p, n]`` sharded one row
    per rank; on return every row holds root's row. Schedule and cost
    model: :func:`_bcast_plan`. Module-level so the pipeline logic is
    unit-testable over a virtual multi-device mesh."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    num_chunks, chunk, padded, steps = _bcast_plan(n, p)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def fn(x):
        y = x[0]
        pos = (lax.axis_index(axis) - root) % p
        yc = jnp.pad(y, (0, padded - n)).reshape(num_chunks, chunk)

        def body(s, yc):
            # Position q forwards chunk s-q (clamped; receivers mask
            # out-of-schedule traffic) and receives chunk s-q+1 from
            # position q-1. Root (q=0) never accepts.
            sid = jnp.clip(s - pos, 0, num_chunks - 1)
            recv = lax.ppermute(
                lax.dynamic_index_in_dim(yc, sid, 0, keepdims=False),
                axis, perm)
            rid_raw = s - pos + 1
            rid = jnp.clip(rid_raw, 0, num_chunks - 1)
            ok = (pos >= 1) & (rid_raw >= 0) & (rid_raw < num_chunks)
            cur = lax.dynamic_index_in_dim(yc, rid, 0, keepdims=False)
            return lax.dynamic_update_index_in_dim(
                yc, jnp.where(ok, recv, cur), rid, 0)

        yc = lax.fori_loop(0, steps, body, yc)
        return yc.reshape(padded)[:n][None]

    return jax.jit(_compat_shard_map(
        fn, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False))


def _as_array(ptr, count, dtype):
    buf = (ctypes.c_char * (count * dtype.itemsize)).from_address(ptr)
    return np.frombuffer(buf, dtype=dtype, count=count)


def _native_error(msg):
    from .exceptions import HorovodInternalError

    return HorovodInternalError(msg)


def maybe_activate(world, core,
                   owns_exec_slot: bool = True
                   ) -> Optional[HostStagingExecutor]:
    """Called from ``HostWorld.init`` on EVERY multi-process native
    world (knob set or not): returns the active executor or None. Never
    raises — staging is an optimization, the ring is the always-correct
    fallback. ``owns_exec_slot=False`` means the core is borrowed from
    the JAX-native engine, whose executor already serves the XLA plane —
    staging would fight it for the callback slot, so such ranks only
    vote."""
    if core is None or world.size <= 1:
        return None
    enabled = _config._get_bool(_config.HOROVOD_HOST_VIA_XLA)
    if enabled and not owns_exec_slot:
        _log.warning("HOROVOD_HOST_VIA_XLA ignored: the JAX-native engine "
                     "already owns the XLA executor in this process")
        enabled = False
    ex, ok = None, False
    if enabled:
        try:
            ex = HostStagingExecutor(world, core)
            ok = ex.activate()
        # hvdlint: ignore[exception-discipline] -- activation failure
        # degrades to the ring; the unanimity vote below keeps the world
        # agreeing on the routing either way
        except Exception as e:
            _log.warning(f"HOROVOD_HOST_VIA_XLA activation failed: {e}; "
                         f"host tensors stay on the TCP ring")
            ok, ex = False, None

    # The stage-vs-ring routing decision MUST be unanimous: a rank that
    # failed activation would run the ring while the others wait in the
    # psum — a world deadlock. Agree via a MIN-allreduce of the local
    # outcome on the (always-available) ring before enabling routing.
    # Ranks without the env knob vote 0 rather than skipping: the
    # agreement is a world-wide collective, and a skipped vote under
    # per-host env drift would leave the voting ranks blocked in
    # core.wait forever (round-3 advisor finding).
    flag = np.array([1.0 if ok else 0.0], np.float32)
    # Straight onto the core (not world.enqueue): maybe_activate runs
    # inside HostWorld.init, before the world reports initialized.
    h = core.enqueue("__hvd.staging.agree", _native.OP_ALLREDUCE,
                     3,  # ReduceOp.MIN
                     _native.DTYPE_CODES["float32"], (1,),
                     data_ptr=flag.ctypes.data, output_ptr=flag.ctypes.data,
                     plane=_native.PLANE_HOST)
    r, err = core.wait(h)
    if r != 1:
        _log.warning(f"HOROVOD_HOST_VIA_XLA agreement allreduce failed "
                     f"({err}); host tensors stay on the TCP ring")
        if ex is not None:
            ex.close()
        return None
    if flag[0] < 1.0:
        if ok:
            _log.warning("HOROVOD_HOST_VIA_XLA disabled: activation "
                         "failed on another process (unanimity required)")
        if ex is not None:
            ex.close()
        return None
    ex.enable_routing()
    return ex
