"""Gaussian-process regression with an RBF kernel (parity:
``horovod/common/optim/gaussian_process.h:46``).

The reference fits kernel hyperparameters with L-BFGS over Eigen matrices;
here the (tiny — tens of samples) GP is solved directly in NumPy with a
coarse grid search over the length scale, which reaches the same posterior
quality at this problem size without a native optimizer dependency.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class GaussianProcessRegressor:
    def __init__(self, alpha: float = 1e-8):
        # alpha: observation noise added to the kernel diagonal (the
        # reference's HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE plays this
        # role at the parameter-manager level).
        self.alpha = alpha
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._l: float = 1.0
        self._sigma_f: float = 1.0
        self._k_inv: Optional[np.ndarray] = None

    @staticmethod
    def _kernel(x1: np.ndarray, x2: np.ndarray, length: float,
                sigma_f: float) -> np.ndarray:
        d2 = ((x1[:, None, :] - x2[None, :, :]) ** 2).sum(-1)
        return sigma_f ** 2 * np.exp(-0.5 * d2 / (length ** 2))

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).reshape(-1)
        self._x, self._y = x, y
        self._y_mean = y.mean() if len(y) else 0.0
        yc = y - self._y_mean
        best = (np.inf, 1.0, max(yc.std(), 1e-3))
        # Marginal-likelihood grid search over the RBF length scale.
        for length in (0.1, 0.2, 0.5, 1.0, 2.0, 5.0):
            k = self._kernel(x, x, length, best[2]) + \
                self.alpha * np.eye(len(x))
            try:
                chol = np.linalg.cholesky(k)
            except np.linalg.LinAlgError:
                continue
            alpha_v = np.linalg.solve(chol.T, np.linalg.solve(chol, yc))
            nll = 0.5 * yc @ alpha_v + np.log(np.diag(chol)).sum()
            if nll < best[0]:
                best = (nll, length, best[2])
        self._l, self._sigma_f = best[1], best[2]
        k = self._kernel(x, x, self._l, self._sigma_f) + \
            self.alpha * np.eye(len(x))
        self._k_inv = np.linalg.inv(k)

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and standard deviation at query points."""
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None or len(self._x) == 0:
            return np.zeros(len(x)), np.ones(len(x))
        ks = self._kernel(x, self._x, self._l, self._sigma_f)
        kss = self._kernel(x, x, self._l, self._sigma_f)
        mu = ks @ self._k_inv @ (self._y - self._y_mean) + self._y_mean
        cov = kss - ks @ self._k_inv @ ks.T
        std = np.sqrt(np.clip(np.diag(cov), 1e-12, None))
        return mu, std
