"""Expected-improvement Bayesian optimization (parity:
``horovod/common/optim/bayesian_optimization.h:45-106``).

Suggests the next (fusion threshold, cycle time) sample by maximizing EI
over the GP posterior. The reference maximizes EI with L-BFGS restarts;
at 2 dimensions dense random candidate sampling finds the same argmax and
keeps this NumPy-only.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .gaussian_process import GaussianProcessRegressor


class BayesianOptimization:
    def __init__(self, bounds: List[Tuple[float, float]],
                 alpha: float = 1e-8, xi: float = 0.01, seed: int = 0):
        self.bounds = np.asarray(bounds, np.float64)
        self.dim = len(bounds)
        self.xi = xi
        self._gp = GaussianProcessRegressor(alpha=alpha)
        self._xs: List[np.ndarray] = []
        self._ys: List[float] = []
        self._rng = np.random.RandomState(seed)

    def add_sample(self, x, y: float) -> None:
        self._xs.append(np.asarray(x, np.float64).reshape(-1))
        self._ys.append(float(y))
        self._gp.fit(np.stack(self._xs), np.asarray(self._ys))

    def _expected_improvement(self, cand: np.ndarray) -> np.ndarray:
        from math import erf, sqrt

        mu, std = self._gp.predict(cand)
        best = max(self._ys)
        imp = mu - best - self.xi
        z = imp / std
        # Normal CDF/PDF without scipy.
        cdf = 0.5 * (1.0 + np.vectorize(erf)(z / sqrt(2.0)))
        pdf = np.exp(-0.5 * z ** 2) / np.sqrt(2 * np.pi)
        ei = imp * cdf + std * pdf
        ei[std < 1e-10] = 0.0
        return ei

    def suggest(self, n_candidates: int = 2000) -> np.ndarray:
        """Next point to sample (normalized to ``bounds``)."""
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        if not self._xs:
            return lo + self._rng.rand(self.dim) * (hi - lo)
        cand = lo + self._rng.rand(n_candidates, self.dim) * (hi - lo)
        ei = self._expected_improvement(cand)
        return cand[int(np.argmax(ei))]

    def best(self) -> Tuple[Optional[np.ndarray], float]:
        if not self._ys:
            return None, float("-inf")
        i = int(np.argmax(self._ys))
        return self._xs[i], self._ys[i]
