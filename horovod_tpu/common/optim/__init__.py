"""Optimization primitives for the autotuner (parity:
``horovod/common/optim/``): Gaussian-process regression + expected-
improvement Bayesian optimization, in NumPy (the reference uses Eigen +
L-BFGS, ``optim/gaussian_process.h:46``, ``optim/bayesian_optimization.h:45``).
"""

from .bayesian_optimization import BayesianOptimization  # noqa: F401
from .gaussian_process import GaussianProcessRegressor  # noqa: F401
