"""Version compatibility shims for the jax API surface we depend on.

The codebase targets the modern jax spelling (``jax.shard_map`` with
``check_vma=``); older installed versions (< 0.6) expose the same
machinery as ``jax.experimental.shard_map.shard_map`` with the replication
check spelled ``check_rep=``. Every internal call site goes through
:func:`shard_map` here so the framework runs unmodified on both.
"""

from __future__ import annotations

import jax
from jax import lax as _lax

if hasattr(jax, "shard_map"):

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

else:  # pre-0.6 jax: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


if hasattr(_lax, "axis_size"):
    axis_size = _lax.axis_size
else:

    def axis_size(axis_name):
        """Size of a bound mesh axis. Pre-0.5 jax has no ``lax.axis_size``;
        ``psum`` of a Python literal folds to the static size (no
        collective is emitted)."""
        return _lax.psum(1, axis_name)


def ensure_cpu_devices(n: int) -> None:
    """Size the host-CPU backend to ``n`` virtual devices (test meshes,
    virtual-mesh demos). Must run before the first device query. Uses the
    ``jax_num_cpu_devices`` config option where it exists (jax >= 0.5),
    else the ``XLA_FLAGS`` fallback; a no-op if the backend already
    initialized (same contract as the config option's RuntimeError).

    On the ``XLA_FLAGS`` path the env var stays exported for the life of
    the process — subprocesses inherit the forced count. Callers that
    spawn real one-device-per-process worker worlds must strip/restore
    it around the spawn (tests/conftest.py forces backend init and then
    restores the var for exactly this reason)."""
    import os

    try:
        jax.config.update("jax_num_cpu_devices", int(n))
        return
    except AttributeError:
        pass  # pre-0.5 jax: fall through to the XLA flag
    except RuntimeError:
        return  # backend already initialized; too late either way
    flag = f"--xla_force_host_platform_device_count={int(n)}"
    flags = os.environ.get("XLA_FLAGS", "")
    # Append even when a different count is already present — XLA takes
    # the LAST occurrence of a repeated flag, so the request wins. Whole-
    # token comparison: "count=8" is a substring of "count=80".
    if flag not in flags.split():
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()


def pallas_tpu_compiler_params(**kwargs):
    """Build a Mosaic compiler-params object under either name
    (``CompilerParams`` today; ``TPUCompilerParams`` before the rename)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` with a pre-0.5 fallback (the
    accessor was added later; older jax only exposes the client on the
    private distributed state)."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    from jax._src import distributed as _dist

    return getattr(_dist.global_state, "client", None) is not None


def install() -> None:
    """Give old jax the modern ``jax.shard_map`` spelling.

    Code written against the current API (tests, user scripts) calls
    ``jax.shard_map(..., check_vma=...)``; on installs that predate the
    promotion out of ``jax.experimental`` this plants the compat wrapper
    under the modern name. No-op when jax already provides it.
    """
    if not hasattr(jax, "shard_map"):
        jax.shard_map = shard_map
