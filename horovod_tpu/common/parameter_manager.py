"""Autotuning parameter manager (parity:
``horovod/common/parameter_manager.{h,cc}``).

Tunes (fusion threshold MB, cycle time ms) online, scoring each sample by
observed collective throughput (bytes/sec, ``parameter_manager.cc``
scoring): a warmup discard phase, then ``steps_per_sample`` scored steps
per candidate from Bayesian optimization, until ``bayes_opt_max_samples``
samples have been taken, after which the best point is pinned.

TPU-native placement: fusion planning happens centrally in the coordinator
(csrc controller ``FuseResponses``), so applying the tuned threshold on the
coordinator process governs the whole job; cycle time paces each rank's own
background loop. There is therefore no cross-rank parameter broadcast — the
reference needs ``Controller::SynchronizeParameters`` (controller.cc:33-47)
only because every rank fuses independently.

Search space follows the reference (``parameter_manager.cc:42``): fusion
threshold 0-64 MB, cycle time 1-25 ms, in log scale for the threshold.
"""

from __future__ import annotations

import time
from typing import Optional

from . import logging as _log
from .optim.bayesian_optimization import BayesianOptimization

MB = 1024 * 1024


class ParameterManager:
    def __init__(self, core, warmup_samples: int = 3,
                 steps_per_sample: int = 10, max_samples: int = 20,
                 gp_noise: float = 0.8, log_file: str = "",
                 initial_cycle_ms: float = 5.0,
                 initial_fusion_bytes: int = 64 * MB):
        self._core = core
        self._warmup_remaining = warmup_samples
        self._steps_per_sample = steps_per_sample
        self._max_samples = max_samples
        self._bayes = BayesianOptimization(
            # (fusion MB, cycle ms) — reference search space.
            bounds=[(0.0, 64.0), (1.0, 25.0)], alpha=gp_noise ** 2)
        self._log_file = log_file
        self._samples_taken = 0
        self._steps_in_sample = 0
        self._bytes_in_sample = 0
        self._sample_start = time.perf_counter()
        self._current = (initial_fusion_bytes / MB, initial_cycle_ms)
        self._tuning = True
        self._best_score: Optional[float] = None
        if log_file:
            with open(log_file, "w") as f:
                f.write("sample,fusion_mb,cycle_ms,score_bytes_per_sec\n")

    @property
    def active(self) -> bool:
        return self._tuning

    def update(self, nbytes: int) -> None:
        """Record one completed collective step of ``nbytes`` total bytes
        (parity: ``ParameterManager::Update``)."""
        if not self._tuning:
            return
        self._bytes_in_sample += nbytes
        self._steps_in_sample += 1
        if self._steps_in_sample < self._steps_per_sample:
            return
        elapsed = max(time.perf_counter() - self._sample_start, 1e-6)
        score = self._bytes_in_sample / elapsed
        if self._warmup_remaining > 0:
            # Warmup: discard the score, keep current params
            # (parity: warmup logic parameter_manager.cc:42-150).
            self._warmup_remaining -= 1
        else:
            self._record_sample(score)
        self._steps_in_sample = 0
        self._bytes_in_sample = 0
        self._sample_start = time.perf_counter()

    def _record_sample(self, score: float) -> None:
        fusion_mb, cycle_ms = self._current
        self._bayes.add_sample([fusion_mb, cycle_ms], score)
        self._samples_taken += 1
        if self._log_file:
            with open(self._log_file, "a") as f:
                f.write(f"{self._samples_taken},{fusion_mb:.2f},"
                        f"{cycle_ms:.2f},{score:.0f}\n")
        if self._samples_taken >= self._max_samples:
            best_x, best_y = self._bayes.best()
            self._tuning = False
            self._best_score = best_y
            self._apply(best_x[0], best_x[1])
            _log.info(
                f"autotune converged: fusion={best_x[0]:.1f}MB "
                f"cycle={best_x[1]:.1f}ms ({best_y / MB:.1f} MB/s)")
            return
        nxt = self._bayes.suggest()
        self._apply(nxt[0], nxt[1])

    def _apply(self, fusion_mb: float, cycle_ms: float) -> None:
        self._current = (float(fusion_mb), float(cycle_ms))
        if self._core is not None:
            self._core.set_parameters(
                cycle_time_ms=float(cycle_ms),
                fusion_threshold=int(fusion_mb * MB))

    # introspection
    @property
    def current(self):
        return self._current

    @property
    def samples_taken(self) -> int:
        return self._samples_taken
