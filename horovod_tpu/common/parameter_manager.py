"""Autotuning parameter manager (parity:
``horovod/common/parameter_manager.{h,cc}``).

Tunes (fusion threshold MB, cycle time ms) online, scoring each sample by
observed collective throughput (bytes/sec, ``parameter_manager.cc``
scoring): a warmup discard phase, then ``steps_per_sample`` scored steps
per candidate from Bayesian optimization, until ``bayes_opt_max_samples``
samples have been taken, after which the best point is pinned.

TPU-native placement: fusion planning happens centrally in the coordinator
(csrc controller ``FuseResponses``), so applying the tuned threshold on the
coordinator process governs the whole job; cycle time rides the response
broadcast to pace every rank's loop, and the categorical
hierarchical-dispatch flags ride the same broadcast (the
``Controller::SynchronizeParameters`` role, controller.cc:33-47) and are
stamped into each response frame so all ranks compile the same programs.

Search space follows the reference (``parameter_manager.cc:42``): fusion
threshold 0-64 MB, cycle time 1-25 ms; plus, when a (cross, local) mesh
exists, a leading grid phase over the four hierarchical
allreduce/allgather combos (the reference's categorical parameters).
"""

from __future__ import annotations

import time
from typing import Optional

from . import logging as _log
from .optim.bayesian_optimization import BayesianOptimization

MB = 1024 * 1024


class ParameterManager:
    def __init__(self, core, warmup_samples: int = 3,
                 steps_per_sample: int = 10, max_samples: int = 20,
                 gp_noise: float = 0.8, log_file: str = "",
                 initial_cycle_ms: float = 5.0,
                 initial_fusion_bytes: int = 64 * MB,
                 tune_hierarchical: bool = False,
                 xla_cap_setter=None,
                 compression_setter=None,
                 compression_candidates=(),
                 stripe_candidates=(),
                 zero_prefetch_setter=None,
                 zero_prefetch_candidates=()):
        self._core = core
        # Tensor-fusion v2 hook: the tuned fusion threshold also governs
        # the XLA plane's bucket cap (common/fusion.resolve_bucket_cap
        # "auto"), so ONE tuner drives both planes. The setter publishes
        # each applied threshold into the live RuntimeConfig; compiled
        # steps pick it up at their next build (a changed cap is a new
        # program — rebuilding/recompiling is inherent, not an autotune
        # limitation).
        self._xla_cap_setter = xla_cap_setter
        self._warmup_remaining = warmup_samples
        self._steps_per_sample = steps_per_sample
        self._max_samples = max_samples
        self._bayes = BayesianOptimization(
            # (fusion MB, cycle ms) — reference search space.
            bounds=[(0.0, 64.0), (1.0, 25.0)], alpha=gp_noise ** 2)
        self._log_file = log_file
        self._samples_taken = 0
        self._steps_in_sample = 0
        self._bytes_in_sample = 0
        self._sample_start = time.perf_counter()
        self._current = (initial_fusion_bytes / MB, initial_cycle_ms)
        self._tuning = True
        self._best_score: Optional[float] = None
        # Categorical phase (reference ParameterManager's categorical
        # params, parameter_manager.h:42-246): when a (cross, local) mesh
        # exists, grid-sample the four hierarchical-dispatch combos at the
        # initial numeric params, pin the best, then run the numeric GP.
        # Flags sync to every rank via the response-broadcast piggyback
        # (set_hier_flags -> Controller::set_hier_flags_hint).
        self._cat_combos = [0, 1, 2, 3] if tune_hierarchical else []
        self._cat_scores: dict = {}
        self._cat_best: Optional[int] = None
        # Stripe phase (docs/cross-transport.md): a categorical grid
        # over the cross-host stripe counts — typically (1, K_env),
        # i.e. "does the striping the user configured actually pay on
        # this fabric?" — scored like the hierarchical combos and
        # pinned via core.set_stripes (the frame-synced apply both
        # sides of every leader pair honor at the same boundary, so
        # the candidates are real lock-step A/Bs mid-world). Only
        # populated when the user opted in (HOROVOD_STRIPES > 1) and
        # the hierarchy spans hosts; runs after the hierarchical grid,
        # before compression.
        self._stripe_candidates = (list(stripe_candidates)
                                   if tune_hierarchical else [])
        self._stripe_scores: dict = {}
        self._stripe_best: Optional[int] = None
        # Compression phase (tensor-fusion v2's wire-compression sibling):
        # a categorical grid over the on-wire compression modes —
        # typically ("none", <the configured mode>), i.e. "does the
        # compression the user asked for actually pay on this model?" —
        # scored exactly like the hierarchical combos, pinned via
        # compression_setter (publishes into the live RuntimeConfig so
        # "auto"-built steps pick it up at their next build). Runs after
        # the hierarchical grid and before the numeric GP.
        self._compression_setter = compression_setter
        self._comp_candidates = (list(compression_candidates)
                                 if compression_setter else [])
        self._comp_scores: dict = {}
        self._comp_best: Optional[str] = None
        # Set by _apply_compression only — during an earlier (hier)
        # phase the ambient config's mode is still in force, and the
        # log column shows "-" rather than claiming a mode this tuner
        # has not applied yet.
        self._current_compression: Optional[str] = None
        # Prefetch phase (ZeRO stage-3's gather-overlap depth, zero.py;
        # docs/zero.md): a categorical grid over HOROVOD_ZERO_PREFETCH
        # depths, scored like the other grids and pinned via
        # zero_prefetch_setter (publishes into the live RuntimeConfig,
        # which "auto"-built stage-3 steps re-resolve each call — a
        # changed depth is a new compile, not a drift). Opt-in like the
        # stripe grid: only populated on single-controller worlds where
        # stage 3 is in force. Depth never changes numerics — only the
        # dataflow chain between gathers — so every candidate is a safe
        # A/B. Runs last among the categoricals, before the numeric GP.
        self._zero_prefetch_setter = zero_prefetch_setter
        self._pf_candidates = (list(zero_prefetch_candidates)
                               if zero_prefetch_setter else [])
        self._pf_scores: dict = {}
        self._pf_best: Optional[int] = None
        self._log_rows = 0
        if self._cat_combos:
            self._apply_hier(self._cat_combos[0])
        elif self._comp_candidates:
            self._apply_compression(self._comp_candidates[0])
        elif self._pf_candidates:
            self._apply_zero_prefetch(self._pf_candidates[0])
        if log_file:
            with open(log_file, "w") as f:
                f.write("sample,fusion_mb,cycle_ms,hier_flags,compression,"
                        "score_bytes_per_sec\n")

    @property
    def active(self) -> bool:
        return self._tuning

    def update(self, nbytes: int) -> None:
        """Record one completed collective step of ``nbytes`` total bytes
        (parity: ``ParameterManager::Update``)."""
        if not self._tuning:
            return
        self._bytes_in_sample += nbytes
        self._steps_in_sample += 1
        if self._steps_in_sample < self._steps_per_sample:
            return
        elapsed = max(time.perf_counter() - self._sample_start, 1e-6)
        score = self._bytes_in_sample / elapsed
        if self._warmup_remaining > 0:
            # Warmup: discard the score, keep current params
            # (parity: warmup logic parameter_manager.cc:42-150).
            self._warmup_remaining -= 1
        else:
            self._record_sample(score)
        self._steps_in_sample = 0
        self._bytes_in_sample = 0
        self._sample_start = time.perf_counter()

    def _record_sample(self, score: float) -> None:
        fusion_mb, cycle_ms = self._current
        self._log_sample(score)
        # Phase 1: grid over the hierarchical combos (categorical params
        # first, like the reference's categorical exploration), then pin
        # the winner for the numeric GP phase.
        if self._cat_combos:
            combo = self._cat_combos.pop(0)
            self._cat_scores[combo] = score
            if self._cat_combos:
                self._apply_hier(self._cat_combos[0])
                return
            self._cat_best = max(self._cat_scores,
                                 key=self._cat_scores.get)
            self._apply_hier(self._cat_best)
            _log.info(f"autotune: hierarchical flags pinned to "
                      f"{self._cat_best:#04b} "
                      f"({self._cat_scores[self._cat_best] / MB:.1f} MB/s)")
            if self._cat_best == 0:
                # No hierarchical leg won: there is no cross leader leg
                # for stripes to carry, so the stripe grid would score
                # noise against noise.
                self._stripe_candidates = []
            if self._stripe_candidates:
                self._apply_stripes(self._stripe_candidates[0])
            elif self._comp_candidates:
                self._apply_compression(self._comp_candidates[0])
            elif self._pf_candidates:
                self._apply_zero_prefetch(self._pf_candidates[0])
            return
        # Phase 1a': grid over the cross-host stripe counts, pin the
        # winner (each candidate is applied frame-synced on every rank,
        # so both sides of every leader pair renegotiate in lock-step
        # before the sample is scored).
        if self._stripe_candidates:
            k = self._stripe_candidates.pop(0)
            self._stripe_scores[k] = score
            if self._stripe_candidates:
                self._apply_stripes(self._stripe_candidates[0])
                return
            self._stripe_best = max(self._stripe_scores,
                                    key=self._stripe_scores.get)
            self._apply_stripes(self._stripe_best)
            _log.info(
                f"autotune: cross-host stripes pinned to "
                f"{self._stripe_best} "
                f"({self._stripe_scores[self._stripe_best] / MB:.1f} "
                f"MB/s)")
            if self._comp_candidates:
                self._apply_compression(self._comp_candidates[0])
            elif self._pf_candidates:
                self._apply_zero_prefetch(self._pf_candidates[0])
            return
        # Phase 1b: grid over the compression modes, pin the winner.
        if self._comp_candidates:
            mode = self._comp_candidates.pop(0)
            self._comp_scores[mode] = score
            if self._comp_candidates:
                self._apply_compression(self._comp_candidates[0])
                return
            self._comp_best = max(self._comp_scores,
                                  key=self._comp_scores.get)
            self._apply_compression(self._comp_best)
            _log.info(
                f"autotune: compression pinned to {self._comp_best!r} "
                f"({self._comp_scores[self._comp_best] / MB:.1f} MB/s)")
            if self._pf_candidates:
                self._apply_zero_prefetch(self._pf_candidates[0])
            return
        # Phase 1c: grid over the ZeRO stage-3 gather prefetch depths,
        # pin the winner.
        if self._pf_candidates:
            depth = self._pf_candidates.pop(0)
            self._pf_scores[depth] = score
            if self._pf_candidates:
                self._apply_zero_prefetch(self._pf_candidates[0])
                return
            self._pf_best = max(self._pf_scores,
                                key=self._pf_scores.get)
            self._apply_zero_prefetch(self._pf_best)
            _log.info(
                f"autotune: zero-3 prefetch depth pinned to "
                f"{self._pf_best} "
                f"({self._pf_scores[self._pf_best] / MB:.1f} MB/s)")
            return
        # Phase 2: numeric GP over (fusion, cycle).
        self._bayes.add_sample([fusion_mb, cycle_ms], score)
        self._samples_taken += 1
        if self._samples_taken >= self._max_samples:
            best_x, best_y = self._bayes.best()
            self._tuning = False
            self._best_score = best_y
            self._apply(best_x[0], best_x[1])
            _log.info(
                f"autotune converged: fusion={best_x[0]:.1f}MB "
                f"cycle={best_x[1]:.1f}ms ({best_y / MB:.1f} MB/s)")
            return
        nxt = self._bayes.suggest()
        self._apply(nxt[0], nxt[1])

    def _log_sample(self, score: float) -> None:
        if not self._log_file:
            return
        self._log_rows += 1
        fusion_mb, cycle_ms = self._current
        hier = self._cat_combos[0] if self._cat_combos else \
            (self._cat_best if self._cat_best is not None else -1)
        comp = self._current_compression or "-"
        with open(self._log_file, "a") as f:
            f.write(f"{self._log_rows},{fusion_mb:.2f},"
                    f"{cycle_ms:.2f},{hier},{comp},{score:.0f}\n")

    def _apply(self, fusion_mb: float, cycle_ms: float) -> None:
        self._current = (float(fusion_mb), float(cycle_ms))
        if self._core is not None:
            self._core.set_parameters(
                cycle_time_ms=float(cycle_ms),
                fusion_threshold=int(fusion_mb * MB))
        if self._xla_cap_setter is not None:
            self._xla_cap_setter(int(fusion_mb * MB))

    def _apply_hier(self, flags: int) -> None:
        if self._core is not None:
            self._core.set_hier_flags(int(flags))

    def _apply_stripes(self, stripes: int) -> None:
        if self._core is not None:
            self._core.set_stripes(int(stripes))

    def _apply_compression(self, mode: str) -> None:
        self._current_compression = mode
        if self._compression_setter is not None:
            self._compression_setter(mode)

    def _apply_zero_prefetch(self, depth: int) -> None:
        if self._zero_prefetch_setter is not None:
            self._zero_prefetch_setter(int(depth))

    # introspection
    @property
    def current(self):
        return self._current

    @property
    def samples_taken(self) -> int:
        return self._samples_taken

    @property
    def hier_flags(self) -> Optional[int]:
        """The pinned categorical decision (None before phase 1 ends)."""
        return self._cat_best

    @property
    def stripes(self) -> Optional[int]:
        """The pinned cross-host stripe count (None before the stripe
        grid ends or when it never ran)."""
        return self._stripe_best

    @property
    def compression(self) -> Optional[str]:
        """The pinned compression mode (None before phase 1b ends)."""
        return self._comp_best

    @property
    def zero_prefetch(self) -> Optional[int]:
        """The pinned stage-3 gather prefetch depth (None before the
        prefetch grid ends or when it never ran)."""
        return self._pf_best
