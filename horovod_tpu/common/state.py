"""Process-global runtime state and the basics API.

Capability parity with the reference's ``horovod/common/basics.py:22-211``
(init/shutdown/rank/size/local/cross queries) and ``global_state.h:42-122``,
re-designed TPU-first:

- The world is a ``jax.sharding.Mesh`` over all addressable TPU chips, not a
  set of MPI ranks. Every *chip* is a participant; ``size()`` is the number
  of chips in the mesh.
- The reference's GLOBAL/LOCAL/CROSS communicator hierarchy
  (``common.h:111-115``, ``mpi_context.h:78-84``) maps onto TPU topology:
  LOCAL = the chips driven by this process (ICI-connected), CROSS = the
  process/slice grid reached over DCN. ``local_size()``/``cross_size()``
  follow that mapping.
- Multi-host initialization goes through ``jax.distributed`` (gRPC
  coordination service) instead of MPI_Init; the launcher provides the
  coordinator address via ``HOROVOD_CONTROLLER_ADDR/PORT`` env, playing the
  role of the reference's Gloo rendezvous (``gloo_context.cc:40-54``).
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from . import config as _config
from . import logging as _log
from .exceptions import NotInitializedError

# Mesh axis names. "hvd" is the flat data-parallel axis used by the
# Horovod-parity API; hierarchical ops split it into cross ("dcn") x
# local ("ici").
AXIS_GLOBAL = "hvd"
AXIS_CROSS = "dcn"
AXIS_LOCAL = "ici"


class _GlobalState:
    """Singleton mirroring the reference's ``HorovodGlobalState``."""

    def __init__(self):
        self.lock = threading.Lock()
        self.initialized = False
        self.config: Optional[_config.RuntimeConfig] = None
        self.mesh = None  # flat 1-D Mesh over all participating devices
        self.hier_mesh = None  # 2-D Mesh (cross, local) over the same devices
        self.devices: Sequence = ()
        self.local_devices: Sequence = ()
        self.size = 0
        self.local_size = 0
        self.cross_size = 0
        self.rank = 0
        self.local_rank = 0
        self.cross_rank = 0
        self.process_count = 1
        self.process_index = 0
        self.is_homogeneous = True
        self.engine = None  # ops.eager.EagerEngine, attached at init
        self.timeline = None
        self.autotuner = None
        self.elastic_enabled = False
        self.last_joined = -1

    def reset(self):
        self.__init__()


_state = _GlobalState()


def global_state() -> _GlobalState:
    return _state


def _maybe_init_distributed() -> None:
    """Join the multi-process world if the launcher set one up.

    The launcher exports HOROVOD_SIZE (process count), HOROVOD_RANK
    (process index) and HOROVOD_CONTROLLER_ADDR/PORT (the gRPC coordination
    service endpoint) — the TPU-native analog of the reference's env-driven
    Gloo rendezvous (``gloo_context.cc:40-54``).
    """
    import jax

    nproc = _config.size()
    # NOTE: no jax.process_count()/jax.devices() here — any backend query
    # initializes XLA, after which jax.distributed.initialize refuses to
    # run. Use the distributed client's own state to detect re-init.
    from .compat import distributed_is_initialized

    if nproc <= 1 or distributed_is_initialized():
        return
    rank = _config.rank()
    addr = _config.controller_addr()
    port = _config.controller_base_port()
    _log.debug(f"joining distributed world: {rank}/{nproc} via {addr}:{port}")
    jax.distributed.initialize(
        coordinator_address=f"{addr}:{port}",
        num_processes=nproc,
        process_id=rank,
    )


def init(comm=None, devices=None):
    """Initialize the runtime.

    ``comm`` accepts a list of process indices to restrict the world to a
    subset of launched processes (parity with ``hvd.init(comm=[ranks])``,
    reference ``basics.py:33-65``); on TPU the subset must be
    slice-aligned, so we only support the full world or a device subset via
    ``devices``.
    """
    import jax
    from jax.sharding import Mesh

    with _state.lock:
        if _state.initialized:
            return

        _maybe_init_distributed()

        _state.config = _config.RuntimeConfig.from_env()

        if devices is None:
            all_devices = list(jax.devices())
        else:
            all_devices = list(devices)
        if comm is not None:
            # Restrict to the devices owned by the given process subset.
            keep = set(comm)
            all_devices = [d for d in all_devices if d.process_index in keep]

        local = [d for d in all_devices if d.process_index == jax.process_index()]

        _state.devices = all_devices
        _state.local_devices = local
        _state.size = len(all_devices)
        _state.local_size = len(local)
        _state.process_count = jax.process_count()
        _state.process_index = jax.process_index()
        _state.cross_size = max(
            1, len({d.process_index for d in all_devices})
        )
        _state.cross_rank = _state.process_index
        # rank = lowest participant id owned by this process; participant ids
        # follow mesh order (process-major, so contiguous per process).
        _state.rank = (
            all_devices.index(local[0]) if local else 0
        )
        _state.local_rank = 0
        sizes = {}
        for d in all_devices:
            sizes[d.process_index] = sizes.get(d.process_index, 0) + 1
        _state.is_homogeneous = len(set(sizes.values())) <= 1

        mesh_devices = np.array(all_devices, dtype=object)
        _state.mesh = Mesh(mesh_devices, (AXIS_GLOBAL,))
        if _state.is_homogeneous and _state.local_size > 0:
            hier = mesh_devices.reshape(_state.cross_size, _state.local_size)
            _state.hier_mesh = Mesh(hier, (AXIS_CROSS, AXIS_LOCAL))

        from ..ops.eager import EagerEngine

        _state.engine = EagerEngine(_state)

        if _state.config.timeline_filename:
            from .timeline import Timeline

            _state.timeline = Timeline(
                _state.config.timeline_filename,
                mark_cycles=_state.config.timeline_mark_cycles,
            )
            if _state.engine.native_core is not None:
                # Record per-rank negotiation ticks while the timeline is
                # active (reference NegotiateRankReady).
                _state.engine.native_core.set_record_negotiation(True)

        if _state.config.autotune and _state.engine.native_core is None:
            _log.warning(
                "HOROVOD_AUTOTUNE requested but the native runtime is "
                "unavailable (direct mode has no tunable cycle/fusion "
                "machinery); autotuning disabled")
        elif _state.config.autotune and _state.rank != 0:
            # The tuner runs only on the coordinator (as in the reference);
            # its chosen (cycle_ms, fusion_bytes) ride every response
            # broadcast and are applied by the native worker cycle
            # (Controller::SynchronizeParameters parity, controller.cc:33-47;
            # see csrc/hvd/controller.cc WorkerCycle).
            _log.debug("autotune: tuner on coordinator; this rank applies "
                       "synced parameters")
        elif _state.config.autotune:
            from .parameter_manager import ParameterManager

            cfg = _state.config

            def _publish_xla_cap(nbytes: int) -> None:
                # Publish the tuned threshold into the live config, where
                # common/fusion.resolve_bucket_cap("auto") reads it — the
                # tuner's (fusion MB, cycle ms) point governs the XLA
                # plane's bucket cap as well as the host plane's cycle
                # fusion (tensor-fusion v2; steps built after this pick
                # the new cap up). SINGLE-CONTROLLER ONLY (gated below):
                # in a multi-process world this config lives on rank 0
                # alone — "auto" steps rebuilt after tuning would bucket
                # on rank 0 but stay monolithic elsewhere, divergent
                # collective sequences in one SPMD program. Workers
                # receive tuned parameters through the native response
                # sync, which does not touch their Python RuntimeConfig.
                cfg.fusion_threshold_bytes = int(nbytes)
                cfg.fusion_threshold_explicit = True

            def _publish_compression(mode: str) -> None:
                # Same live-config publish as the bucket cap, for the
                # compression mode: resolve_compression("auto") reads it,
                # so "auto"-built steps adopt the tuner's pick at their
                # next build. SINGLE-CONTROLLER ONLY (same divergence
                # argument as the cap).
                cfg.compression = mode
                cfg.compression_explicit = True

            # The tuner explores compression ONLY when the user opted in
            # (HOROVOD_COMPRESSION explicitly set to a non-none mode):
            # compression changes numerics, and silently quantizing
            # gradients because it benched faster is not the tuner's
            # call. The grid then answers "does the requested mode
            # actually pay on this model?" — none vs the configured mode.
            # The samples are real A/Bs: the eager engine resolves the
            # live mode per program build (ops/eager.py
            # _exec_grouped_allreduce, mode in the cache key), so each
            # published candidate recompiles the negotiated collectives
            # with that wire format before the sample is scored — and
            # the score's nbytes are *application* bytes, invariant
            # across modes, so bytes/sec genuinely ranks the modes by
            # collective speed.
            comp_candidates = ()
            if cfg.compression_explicit and cfg.compression != "none":
                comp_candidates = ("none", cfg.compression)

            # Stripe grid (docs/cross-transport.md): only when the user
            # opted in (HOROVOD_STRIPES > 1) — the tuner then answers
            # "does the configured striping actually pay on this
            # fabric?" by A/B-ing single-socket vs K stripes through
            # the frame-synced set_stripes apply. The hierarchy gate
            # below (tune_hierarchical) keeps it off worlds with no
            # cross leader leg to stripe.
            stripe_candidates = ()
            if _config.stripes() > 1:
                stripe_candidates = (1, _config.stripes())

            def _publish_zero_prefetch(depth: int) -> None:
                # Same live-config publish as the bucket cap, for the
                # stage-3 gather prefetch depth:
                # fusion.resolve_prefetch_depth("auto") reads it, so
                # "auto"-built stage-3 steps re-resolve and recompile at
                # the new depth on their next call. SINGLE-CONTROLLER
                # ONLY (same divergence argument as the cap). Depth
                # never changes numerics — only the gather dataflow
                # chain — so the tuner may pick freely.
                cfg.zero_prefetch = int(depth)
                cfg.zero_prefetch_explicit = True

            # Prefetch grid (docs/zero.md): only when ZeRO stage 3 is in
            # force — on stage-1/2 worlds there are no forward gathers
            # to pace, and the grid would score noise against noise.
            # Depths 0 (serialized), 1 (default), 2: the marginal win of
            # deeper in-flight windows decays fast while the gathered-
            # buffer watermark grows linearly.
            zero_prefetch_candidates = ()
            if _config.zero_stage() == 3:
                zero_prefetch_candidates = (0, 1, 2)

            if _state.process_count > 1:
                _log.debug(
                    "autotune: XLA bucket-cap/compression/prefetch "
                    "publish disabled in multi-process worlds (set "
                    "HOROVOD_FUSION_THRESHOLD / HOROVOD_COMPRESSION / "
                    "HOROVOD_ZERO_PREFETCH explicitly — same env "
                    "everywhere — to govern the compiled path)")
                _publish_xla_cap = None
                _publish_compression = None
                comp_candidates = ()
                _publish_zero_prefetch = None
                zero_prefetch_candidates = ()

            core = _state.engine.native_core
            _state.autotuner = ParameterManager(
                core, warmup_samples=cfg.autotune_warmup_samples,
                steps_per_sample=cfg.autotune_steps_per_sample,
                max_samples=cfg.autotune_bayes_opt_max_samples,
                gp_noise=cfg.autotune_gaussian_process_noise,
                log_file=cfg.autotune_log,
                initial_cycle_ms=cfg.cycle_time_ms,
                initial_fusion_bytes=cfg.fusion_threshold_bytes,
                # Categorical phase only when the hierarchy actually
                # spans hosts — with cross_size 1 the hier variants can
                # only lose (or win by noise), and the grid would burn
                # 4 sample windows on a meaningless choice.
                tune_hierarchical=(_state.hier_mesh is not None
                                   and _state.cross_size > 1),
                xla_cap_setter=_publish_xla_cap,
                compression_setter=(_publish_compression
                                    if comp_candidates else None),
                compression_candidates=comp_candidates,
                stripe_candidates=stripe_candidates,
                zero_prefetch_setter=(_publish_zero_prefetch
                                      if zero_prefetch_candidates else None),
                zero_prefetch_candidates=zero_prefetch_candidates)

        _state.initialized = True

        # Metrics exporter (docs/metrics.md): ONLY when the operator set
        # HOROVOD_METRICS_EXPORT — unset keeps init byte-identical to
        # pre-metrics builds (no thread, no file, no timeline counter
        # events; regression-tested).
        from . import metrics as _metrics

        _metrics.maybe_start_pump()

        _log.info(
            f"horovod_tpu initialized: size={_state.size} "
            f"local_size={_state.local_size} cross_size={_state.cross_size} "
            f"platform={all_devices[0].platform if all_devices else 'none'}"
        )


def shutdown():
    """Tear down the runtime (parity: ``horovod_shutdown``)."""
    with _state.lock:
        if not _state.initialized:
            return
        from . import metrics as _metrics

        # Stop the exporter BEFORE the engine/timeline go away: the
        # final flush still sees a live core and an open timeline.
        _metrics.stop_pump()
        if _state.engine is not None:
            _state.engine.shutdown()
        if _state.timeline is not None:
            _state.timeline.close()
        _state.reset()


def is_initialized() -> bool:
    return _state.initialized


def _require_init(name: str) -> _GlobalState:
    if not _state.initialized:
        raise NotInitializedError(name)
    return _state


def size() -> int:
    """Number of participants (TPU chips) in the world."""
    return _require_init("size").size


def local_size() -> int:
    """Number of participants driven by this process (ICI-local group)."""
    return _require_init("local_size").local_size


def cross_size() -> int:
    """Number of processes / DCN endpoints (one per host or slice)."""
    return _require_init("cross_size").cross_size


def rank() -> int:
    """Lowest participant id owned by this process.

    With one process per host driving N chips, ranks are ``process_index*N``;
    rank 0 is always the coordinator process, so ``if hvd.rank() == 0:``
    checkpointing idioms from the reference work unchanged.
    """
    return _require_init("rank").rank


def local_rank() -> int:
    return _require_init("local_rank").local_rank


def cross_rank() -> int:
    return _require_init("cross_rank").cross_rank


def is_homogeneous() -> bool:
    return _require_init("is_homogeneous").is_homogeneous


def mesh():
    """The flat 1-D ``jax.sharding.Mesh`` over all participants."""
    return _require_init("mesh").mesh


def hierarchical_mesh():
    """The (cross, local) 2-D mesh: DCN x ICI, or None if inhomogeneous."""
    return _require_init("hierarchical_mesh").hier_mesh


# ---- capability predicates (parity: operations.cc:690-760) -----------------


def mpi_threads_supported() -> bool:
    return False


def mpi_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def xla_built() -> bool:
    """Always true: XLA collectives are the native backend."""
    return True


def tpu_available() -> bool:
    import jax

    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except RuntimeError:
        return False
