"""Runtime configuration: one env-var layer, Horovod-compatible knob names.

The reference converges three config layers on env vars (SURVEY §5; knob
names in ``common.h:62-88``, parsed in ``operations.cc:407-504``). We keep
the same user-facing names (HOROVOD_*) so reference users find every knob,
and add TPU-specific ones under the same prefix.
"""

from __future__ import annotations

import dataclasses
import os
import random

# ---- knob names (reference: common.h:62-88) --------------------------------
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE = "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_COMPRESSION = "HOROVOD_COMPRESSION"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
HOROVOD_LOG_HIDE_TIME = "HOROVOD_LOG_HIDE_TIME"
# launch-time topology (reference: gloo_context.cc:40-54)
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_CONTROLLER_ADDR = "HOROVOD_CONTROLLER_ADDR"
HOROVOD_CONTROLLER_PORT = "HOROVOD_CONTROLLER_PORT"
HOROVOD_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
HOROVOD_HOSTNAME = "HOROVOD_HOSTNAME"
HOROVOD_SECRET_KEY = "HOROVOD_SECRET_KEY"
HOROVOD_ELASTIC_PREEMPT_SIGNAL = "HOROVOD_ELASTIC_PREEMPT_SIGNAL"
HOROVOD_NATIVE = "HOROVOD_NATIVE"
HOROVOD_NATIVE_SANITIZE = "HOROVOD_NATIVE_SANITIZE"
# TPU-specific additions
HOROVOD_TPU_MESH_AXES = "HOROVOD_TPU_MESH_AXES"
HOROVOD_TPU_DONUT_SIZE = "HOROVOD_TPU_DONUT_SIZE"
HOROVOD_ELASTIC = "HOROVOD_ELASTIC"
HOROVOD_HOST_VIA_XLA = "HOROVOD_HOST_VIA_XLA"
HOROVOD_HOST_VIA_XLA_THRESHOLD = "HOROVOD_HOST_VIA_XLA_THRESHOLD"
DEFAULT_HOST_VIA_XLA_THRESHOLD = 1 << 20  # 1 MiB fused response
HOROVOD_ELASTIC_REJOIN_GRACE = "HOROVOD_ELASTIC_REJOIN_GRACE"
# Shared-memory intra-host transport (csrc/hvd/shm_transport.cc behind
# the op_manager registry; docs/shm-transport.md)
HOROVOD_SHM = "HOROVOD_SHM"
HOROVOD_SHM_SLOT_BYTES = "HOROVOD_SHM_SLOT_BYTES"
HOROVOD_SHM_FALLBACK = "HOROVOD_SHM_FALLBACK"
# Striped multi-socket cross-host transport (csrc/hvd/stripe_transport.cc
# behind the op_manager registry; docs/cross-transport.md)
HOROVOD_STRIPES = "HOROVOD_STRIPES"
HOROVOD_CHUNK_BYTES = "HOROVOD_CHUNK_BYTES"
HOROVOD_STRIPE_FALLBACK = "HOROVOD_STRIPE_FALLBACK"
# Unified metrics plane (common/metrics.py, csrc/hvd/metrics.cc;
# docs/metrics.md)
HOROVOD_METRICS_EXPORT = "HOROVOD_METRICS_EXPORT"
HOROVOD_METRICS_INTERVAL_MS = "HOROVOD_METRICS_INTERVAL_MS"
HOROVOD_STRAGGLER_MS = "HOROVOD_STRAGGLER_MS"
HOROVOD_STRAGGLER_PATIENCE = "HOROVOD_STRAGGLER_PATIENCE"
DEFAULT_METRICS_INTERVAL_MS = 5000
DEFAULT_STRAGGLER_MS = 100
DEFAULT_STRAGGLER_PATIENCE = 3
# Hierarchical control plane: per-host leader negotiation + delta-first
# wire protocol (csrc/hvd/controller.cc; docs/control-plane.md)
HOROVOD_HIER_CONTROL = "HOROVOD_HIER_CONTROL"
# Liveness plane: heartbeats, failure detection, graceful drain
# (common/liveness.py, csrc/hvd/controller.cc; docs/liveness.md)
HOROVOD_HEARTBEAT_MS = "HOROVOD_HEARTBEAT_MS"
HOROVOD_LIVENESS_TIMEOUT_MS = "HOROVOD_LIVENESS_TIMEOUT_MS"
HOROVOD_DRAIN_GRACE_MS = "HOROVOD_DRAIN_GRACE_MS"
DEFAULT_LIVENESS_TIMEOUT_MS = 10000
DEFAULT_DRAIN_GRACE_MS = 5000
# Native-core-consumed knobs with no Python-side reader: registered
# here anyway so the knob surface stays ONE table (docs/env-vars.md;
# hvdlint's native-knob-discipline check fails an unregistered C++
# read). The launchers WRITE the job key; csrc reads both.
HOROVOD_JOB_KEY = "HOROVOD_JOB_KEY"
HOROVOD_RING_TREE_THRESHOLD = "HOROVOD_RING_TREE_THRESHOLD"
DEFAULT_RING_TREE_THRESHOLD = 16384  # csrc/hvd/ring_ops.cc TreeThresholdBytes
HOROVOD_MAX_FRAME_BYTES = "HOROVOD_MAX_FRAME_BYTES"
DEFAULT_MAX_FRAME_BYTES = 1073741824  # 1 GiB; csrc/hvd/socket.cc MaxFrameBytes
# Fault injection + retry/backoff + blacklist (common/faults.py;
# docs/fault-injection.md)
HOROVOD_FAULT_SPEC = "HOROVOD_FAULT_SPEC"
HOROVOD_RETRY_PREFIX = "HOROVOD_RETRY"
HOROVOD_ELASTIC_BLACKLIST_STRIKES = "HOROVOD_ELASTIC_BLACKLIST_STRIKES"
HOROVOD_ELASTIC_PAROLE_WINDOW = "HOROVOD_ELASTIC_PAROLE_WINDOW"
DEFAULT_BLACKLIST_STRIKES = 3
DEFAULT_PAROLE_WINDOW_SECONDS = 300.0
# Self-healing data plane: epoch-fenced in-place link reconnection
# (csrc/hvd/ring_ops.cc HealCrossStep; docs/self-healing.md) + the
# seeded multi-fault chaos scheduler (tools/chaos_sched.py)
HOROVOD_LINK_RETRY_ATTEMPTS = "HOROVOD_LINK_RETRY_ATTEMPTS"
HOROVOD_LINK_RETRY_BACKOFF_MS = "HOROVOD_LINK_RETRY_BACKOFF_MS"
HOROVOD_LINK_RETRY_DEADLINE_MS = "HOROVOD_LINK_RETRY_DEADLINE_MS"
HOROVOD_CHAOS_SPEC = "HOROVOD_CHAOS_SPEC"
# ZeRO partitioning plane (zero.py; docs/zero.md): which tensors are
# partitioned 1/d across the mesh, and how far ahead the stage-3
# parameter gathers may run.
HOROVOD_ZERO_STAGE = "HOROVOD_ZERO_STAGE"
HOROVOD_ZERO_PREFETCH = "HOROVOD_ZERO_PREFETCH"
DEFAULT_ZERO_STAGE = 2
DEFAULT_ZERO_PREFETCH = 1
DEFAULT_LINK_RETRY_ATTEMPTS = 3
DEFAULT_LINK_RETRY_BACKOFF_MS = 100
# Sized well below DEFAULT_LIVENESS_TIMEOUT_MS on purpose: healing must
# surface a truly dead peer to the evict path inside the liveness
# window, never mask it (docs/self-healing.md sizing rule).
DEFAULT_LINK_RETRY_DEADLINE_MS = 3000

DEFAULT_FUSION_THRESHOLD_BYTES = 64 * 1024 * 1024  # reference operations.cc:423
DEFAULT_CYCLE_TIME_MS = 5.0  # reference operations.cc:431
DEFAULT_CACHE_CAPACITY = 1024
DEFAULT_STALL_WARNING_SECONDS = 60.0  # reference stall_inspector.h:75


def native_controller_port(default: int = 29500) -> int:
    """The native controller's TCP port. ``HOROVOD_CONTROLLER_PORT`` is the
    *base* coordination port (``jax.distributed`` / gRPC takes it); the
    native controller always binds base+1. Every derivation of the +1
    convention goes through here."""
    return _get_int(HOROVOD_CONTROLLER_PORT, default) + 1


def _get_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _get_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


def _get_int_explicit(name: str, default: int):
    """(value, explicit): the parsed env int and whether it counts as an
    explicit setting. Unset OR unparseable → (default, False) — an
    unparseable value must not count as explicit, or it would silently
    flip the XLA plane's "auto" bucket cap to the 64 MB host-plane
    default. The single parse shared by both fusion-threshold fields."""
    v = os.environ.get(name)
    try:
        return (int(v), True) if v is not None else (default, False)
    except ValueError:
        return default, False


# On-wire gradient compression modes (common/compression.py;
# docs/compression.md). "ef16" = fp16 wire + error-feedback residuals.
COMPRESSION_CHOICES = ("none", "fp16", "bf16", "ef16")


def _get_choice_explicit(name: str, choices, default: str):
    """(value, explicit) for an enumerated env knob. Unset OR an unknown
    value → (default, False) — a typo'd mode must not count as explicit
    (same tolerance contract as ``_get_int_explicit``), but it is worth
    a warning: silently training uncompressed under a misspelled
    ``HOROVOD_COMPRESSION`` would be a nasty surprise."""
    v = os.environ.get(name)
    if v is None:
        return default, False
    v = v.strip().lower()
    if v in choices:
        return v, True
    from . import logging as _log

    _log.warning(f"{name}={v!r} is not one of {sorted(choices)}; "
                 f"ignoring (using {default!r})")
    return default, False


def parse_compression_env() -> str:
    """The env-level compression mode ("none" when unset/invalid) — the
    raw-env half of ``compression.resolve_compression('auto')``'s
    precedence (live config first, then this)."""
    v, _ = _get_choice_explicit(HOROVOD_COMPRESSION, COMPRESSION_CHOICES,
                                "none")
    return v


def _get_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v is not None else default
    except ValueError:
        return default


# ---- launch-topology + identity accessors ----------------------------------
#
# One accessor per knob: every consumer shares one default and one parse
# (the env-discipline check in tools/hvdlint rejects raw reads anywhere
# else, and docs/env-vars.md is generated from these shapes). Boolean
# knobs all go through ``_get_bool`` — the same grammar as the native
# core's ``EnvFlag`` parser ("1"/"true"/"yes"/"on" enable, anything else
# disables, case/whitespace-insensitive).


def rank() -> int:
    """This process's launch-time global rank (0 when unlaunched)."""
    return _get_int(HOROVOD_RANK, 0)


def rank_string():
    """The raw ``HOROVOD_RANK`` value, ``None`` when not launched —
    for consumers that want presence (log prefixes), not a parsed 0."""
    return os.environ.get(HOROVOD_RANK)


def size() -> int:
    """Launch-time world size (1 when unlaunched)."""
    return _get_int(HOROVOD_SIZE, 1)


def local_rank() -> int:
    """Launch-time local (per-host) rank (0 when unlaunched)."""
    return _get_int(HOROVOD_LOCAL_RANK, 0)


def cross_rank(default: int) -> int:
    """Node index from the launcher; the caller supplies the derived
    fallback (rank // local_size under homogeneous packing)."""
    return _get_int(HOROVOD_CROSS_RANK, default)


def cross_size(default: int) -> int:
    """Node count from the launcher; fallback derived like cross_rank."""
    return _get_int(HOROVOD_CROSS_SIZE, default)


def controller_addr() -> str:
    """The coordination-service host (gRPC base + native controller)."""
    return os.environ.get(HOROVOD_CONTROLLER_ADDR, "127.0.0.1")


def controller_base_port() -> int:
    """The *base* coordination port (jax.distributed/gRPC binds it; the
    native controller binds base+1 via ``native_controller_port``)."""
    return _get_int(HOROVOD_CONTROLLER_PORT, 29500)


def rendezvous_addr():
    """Elastic rendezvous KV host, ``None`` when not under the elastic
    driver (empty counts as unset)."""
    return os.environ.get(HOROVOD_RENDEZVOUS_ADDR) or None


def rendezvous_port():
    """Elastic rendezvous KV port as an int, ``None`` when unset or
    unparseable (matching ``rendezvous_addr``'s None-when-absent).

    Unparseable-but-set warns loudly: callers guard with ``if addr and
    port`` and degrade to non-elastic operation, which must not look
    identical to the launcher never exporting the port (the pre-accessor
    code raised ValueError here; a silent None would send debugging in
    the wrong direction)."""
    v = os.environ.get(HOROVOD_RENDEZVOUS_PORT)
    if not v:
        return None
    try:
        return int(v)
    except ValueError:
        from . import logging as _hvd_logging
        _hvd_logging.warning(
            f"{HOROVOD_RENDEZVOUS_PORT}={v!r} is not a valid port; "
            "elastic rendezvous registration disabled")
        return None


def rendezvous_port_string():
    """The raw ``HOROVOD_GLOO_RENDEZVOUS_PORT`` value, ``None`` when
    unset/empty — for error messages that must show an unparseable value
    instead of misreporting it as missing (``rendezvous_port()`` maps
    both cases to None)."""
    return os.environ.get(HOROVOD_RENDEZVOUS_PORT) or None


def hostname(default=None):
    """This slot's advertised hostname. The ssh launcher exports a
    per-slot value; scheduler launches leave it unset, so callers pass
    the fallback that is right for their plane (loopback, localhost, or
    ``socket.gethostname()``)."""
    return os.environ.get(HOROVOD_HOSTNAME, default)


def secret_key_b64():
    """The elastic driver's base64 notification key; ``None`` when this
    process was not launched by the elastic driver."""
    return os.environ.get(HOROVOD_SECRET_KEY) or None


def preempt_signal_spec() -> str:
    """The opt-in preemption signal (name or number; empty = opt-out).
    Truthiness of the return is the opt-in check; parsing to a signal
    number happens at the one consumer (elastic.state)."""
    return os.environ.get(HOROVOD_ELASTIC_PREEMPT_SIGNAL, "").strip()


def elastic_enabled() -> bool:
    """Whether this world runs under the elastic driver (same
    ``_get_bool`` grammar as ``RuntimeConfig.elastic`` — previously the
    host-world check counted ANY non-empty value, so ``HOROVOD_ELASTIC=0``
    enabled elastic; that drift is what this accessor retires)."""
    return _get_bool(HOROVOD_ELASTIC)


def native_enabled() -> bool:
    """Whether the native (C++) host plane may load. Default on;
    ``_get_bool`` grammar means "0"/"false"/"no"/"off" all disable —
    the raw reads this replaces special-cased only "0"/"false", so
    ``HOROVOD_NATIVE=no`` silently stayed enabled."""
    return _get_bool(HOROVOD_NATIVE, default=True)


NATIVE_SANITIZE_CHOICES = ("tsan", "asan")


def native_sanitize() -> str:
    """Sanitizer variant of the native core to build and load ("" = the
    production artifact). "tsan"/"asan" select ``libhvdtpu_{tsan,asan}.so``
    (``csrc/Makefile`` variant targets), built beside — never instead
    of — the normal library. Read at first library load per process;
    docs/static-analysis.md has the build/run recipe (an instrumented
    .so needs its sanitizer runtime present in the host process)."""
    v = os.environ.get(HOROVOD_NATIVE_SANITIZE, "").strip().lower()
    if v in ("", "0", "none", "off"):
        return ""
    if v in NATIVE_SANITIZE_CHOICES:
        return v
    from . import logging as _log

    _log.warning(f"{HOROVOD_NATIVE_SANITIZE}={v!r} is not one of "
                 f"{sorted(NATIVE_SANITIZE_CHOICES)}; ignoring "
                 f"(loading the uninstrumented library)")
    return ""


def log_level_name() -> str:
    """Lower-cased ``HOROVOD_LOG_LEVEL`` ("warning" default)."""
    return os.environ.get(HOROVOD_LOG_LEVEL, "warning").strip().lower()


def log_hide_time() -> bool:
    """Drop timestamps from log lines (``_get_bool`` grammar — the raw
    read accepted only "1"/"true", missing "yes"/"on")."""
    return _get_bool(HOROVOD_LOG_HIDE_TIME)


def rejoin_grace_env():
    """Operator override for the elastic rejoin grace, ``None`` when
    unset/empty (the driver-published KV value applies then)."""
    if not os.environ.get(HOROVOD_ELASTIC_REJOIN_GRACE):
        return None
    return _get_float(HOROVOD_ELASTIC_REJOIN_GRACE, 10.0)


# ---- fault injection (common/faults.py; docs/fault-injection.md) ----------
#
# HOROVOD_FAULT_SPEC grammar:  spec(;spec)*
#   spec  = point(:key=value)*
#   point = dotted fault-point name, e.g. "ring.exec" (see faults.CATALOG)
#   keys  = rank  (int; only this rank fires — default: every rank)
#           step  (int; fire on the Nth hit of the point in this process,
#                  0-based — default: every hit)
#           kind  (raise | delay_ms | exit | drop_conn — default: raise)
#           ms    (float; delay for kind=delay_ms — default 100)
#           code  (int; exit status for kind=exit — default 13)
#           times (int; max fires — default 1 when step given, else
#                  unlimited)
#
# e.g. HOROVOD_FAULT_SPEC="ring.exec:rank=1:step=3:kind=exit"
# Parsing is strict: a malformed spec raises instead of silently injecting
# nothing — a chaos test whose fault never fires "passes" vacuously.

FAULT_KINDS = ("raise", "delay_ms", "exit", "drop_conn")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    point: str
    rank: int = -1          # -1 = any rank
    step: int = -1          # -1 = every hit
    kind: str = "raise"
    ms: float = 100.0
    code: int = 13
    times: int = 0          # 0 = unlimited


def parse_fault_spec(text: str) -> tuple:
    """Parse a ``HOROVOD_FAULT_SPEC`` string into ``FaultSpec`` tuples.

    Raises ``ValueError`` on any malformed field (loud-by-design, see
    grammar comment above)."""
    specs = []
    for chunk in text.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        fields = chunk.split(":")
        point = fields[0].strip()
        if not point:
            raise ValueError(f"fault spec {chunk!r}: empty point name")
        kw = {"point": point}
        for field in fields[1:]:
            if "=" not in field:
                raise ValueError(
                    f"fault spec {chunk!r}: field {field!r} is not "
                    f"key=value")
            key, _, val = field.partition("=")
            key = key.strip()
            val = val.strip()
            try:
                if key in ("rank", "step", "code", "times"):
                    kw[key] = int(val)
                elif key == "ms":
                    kw[key] = float(val)
                elif key == "kind":
                    if val not in FAULT_KINDS:
                        raise ValueError(
                            f"unknown kind {val!r} (choices: "
                            f"{', '.join(FAULT_KINDS)})")
                    kw[key] = val
                else:
                    raise ValueError(f"unknown key {key!r}")
            except ValueError as e:
                raise ValueError(f"fault spec {chunk!r}: {e}") from None
        if "times" not in kw and kw.get("step", -1) >= 0:
            kw["times"] = 1  # a step-pinned fault is one-shot by default
        specs.append(FaultSpec(**kw))
    return tuple(specs)


def parse_fault_spec_env() -> tuple:
    """The active fault specs from ``HOROVOD_FAULT_SPEC`` (empty tuple
    when unset — the zero-cost-disabled case)."""
    text = os.environ.get(HOROVOD_FAULT_SPEC)
    return parse_fault_spec(text) if text else ()


# ---- seeded chaos schedules (tools/chaos_sched.py CLI; docs/self-healing.md)
#
# HOROVOD_CHAOS_SPEC grammar:  key=value(,key=value)*
#   seed   (int, REQUIRED)  rng seed — the whole schedule is a pure
#                           function of the spec string, so one string
#                           reproduces the same faults every run/rank
#   n      (int, REQUIRED)  number of faults to draw
#   kinds  (a|b|...)        draw pool (default "drop_conn|delay_ms" — the
#                           non-fatal kinds a healing world must absorb;
#                           "exit" must be opted into)
#   points (p|q|...)        fault-point pool (default
#                           "ring.exec|ring.hier.cross")
#   ranks  (0|1|...)        rank pool (default: every rank, 0..size-1)
#   steps  (lo-hi)          inclusive hit-index window per fault
#                           (default 0-10)
#   ms     (float)          delay for drawn kind=delay_ms faults
#                           (default 50)
#   code   (int)            exit status for drawn kind=exit faults
#                           (default 13)
#
# e.g. HOROVOD_CHAOS_SPEC="seed=42,n=5,kinds=drop_conn|delay_ms,steps=0-8"
# Parsing is strict like parse_fault_spec: malformed raises, never
# silently injects nothing.

CHAOS_DEFAULT_KINDS = "drop_conn|delay_ms"
CHAOS_DEFAULT_POINTS = "ring.exec|ring.hier.cross"


def parse_chaos_spec(text: str, size: int = 0) -> tuple:
    """Compile a ``HOROVOD_CHAOS_SPEC`` string into concrete
    ``FaultSpec`` tuples, deterministically from its seed.

    Draw order per fault is fixed (point, rank, step, kind), so the
    schedule is stable across runs, ranks, and Python versions. ``size``
    bounds the default rank pool; 0 falls back to the launch-time
    ``HOROVOD_SIZE``. Every compiled fault is one-shot (``times=1``) —
    n faults means at most n firings. Raises ``ValueError`` on any
    malformed or unknown field (loud-by-design, like
    ``parse_fault_spec``)."""
    fields = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"chaos spec field {part!r} is not key=value")
        key, _, val = part.partition("=")
        fields[key.strip()] = val.strip()
    try:
        seed = int(fields.pop("seed"))
        n = int(fields.pop("n"))
    except KeyError as e:
        raise ValueError(f"chaos spec {text!r}: missing required "
                         f"field {e.args[0]}") from None
    if n < 0:
        raise ValueError(f"chaos spec {text!r}: n must be >= 0")
    kinds = tuple(k.strip() for k in
                  fields.pop("kinds", CHAOS_DEFAULT_KINDS).split("|"))
    for k in kinds:
        if k not in FAULT_KINDS:
            raise ValueError(f"chaos spec {text!r}: unknown kind {k!r} "
                             f"(choices: {', '.join(FAULT_KINDS)})")
    points = tuple(p.strip() for p in
                   fields.pop("points", CHAOS_DEFAULT_POINTS).split("|"))
    ranks_txt = fields.pop("ranks", "")
    if ranks_txt:
        ranks = tuple(int(r) for r in ranks_txt.split("|"))
    else:
        world = size if size > 0 else max(1, _get_int(HOROVOD_SIZE, 1))
        ranks = tuple(range(world))
    steps_txt = fields.pop("steps", "0-10")
    lo, sep, hi = steps_txt.partition("-")
    if not sep:
        raise ValueError(f"chaos spec {text!r}: steps must be lo-hi")
    step_lo, step_hi = int(lo), int(hi)
    if step_lo < 0 or step_hi < step_lo:
        raise ValueError(f"chaos spec {text!r}: bad steps window "
                         f"{steps_txt!r}")
    ms = float(fields.pop("ms", 50.0))
    code = int(fields.pop("code", 13))
    if fields:
        raise ValueError(f"chaos spec {text!r}: unknown key(s) "
                         f"{', '.join(sorted(fields))}")
    rng = random.Random(seed)
    specs = []
    for _ in range(n):
        point = rng.choice(points)
        rank = rng.choice(ranks)
        step = rng.randint(step_lo, step_hi)
        kind = rng.choice(kinds)
        specs.append(FaultSpec(point=point, rank=rank, step=step,
                               kind=kind, ms=ms, code=code, times=1))
    return tuple(specs)


def parse_chaos_spec_env(size: int = 0) -> tuple:
    """The compiled chaos schedule from ``HOROVOD_CHAOS_SPEC`` (empty
    tuple when unset — the zero-cost-disabled case)."""
    text = chaos_spec()
    return parse_chaos_spec(text, size=size) if text else ()


# ---- shared retry/backoff policy (common/faults.py Retrier) ----------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with full jitter + an overall deadline.

    ``max_attempts=0`` means unlimited (bounded by ``deadline``);
    ``deadline=0`` means no overall deadline (bounded by attempts)."""

    max_attempts: int = 3
    base_delay: float = 0.5
    max_delay: float = 15.0
    multiplier: float = 2.0
    deadline: float = 0.0
    jitter: bool = True


_RETRY_FIELD_ENV = {
    "max_attempts": ("MAX_ATTEMPTS", int),
    "base_delay": ("BASE_DELAY", float),
    "max_delay": ("MAX_DELAY", float),
    "multiplier": ("MULTIPLIER", float),
    "deadline": ("DEADLINE", float),
}


def retry_policy_from_env(scope: str = "", pinned=(),
                          **defaults) -> RetryPolicy:
    """Build a ``RetryPolicy`` with env precedence per field:

        HOROVOD_RETRY_<SCOPE>_<FIELD>  >  HOROVOD_RETRY_<FIELD>  >  defaults

    ``scope`` names the call site ("KV", "RENDEZVOUS", "DRIVER", ...);
    the scoped spelling lets operators tune one seam without loosening
    every other. ``pinned`` names fields the env may NOT override — the
    values that encode a correctness contract rather than a tuning knob
    (e.g. the rejoin poll's unlimited attempts, a caller-passed short
    deadline): a global HOROVOD_RETRY_MAX_ATTEMPTS=3 must bound flaky KV
    reads without silently truncating the elastic rejoin grace.
    Unparseable values fall back a level (same tolerance contract as
    ``_get_int_explicit``)."""
    kw = dict(defaults)
    scope = scope.strip().upper().replace(".", "_")
    for field, (suffix, conv) in _RETRY_FIELD_ENV.items():
        if field in pinned:
            continue
        names = [f"{HOROVOD_RETRY_PREFIX}_{suffix}"]
        if scope:
            names.insert(0, f"{HOROVOD_RETRY_PREFIX}_{scope}_{suffix}")
        for name in names:
            v = os.environ.get(name)
            if v is None:
                continue
            try:
                kw[field] = conv(v)
                break
            except ValueError:
                continue
    return RetryPolicy(**kw)


def hier_control_enabled() -> bool:
    """Whether negotiation runs the hierarchical control plane (default
    off): per-host leaders aggregate their members' request frames and
    speak for the group, so the coordinator does O(hosts) socket work
    per cycle instead of O(ranks), and fully-cached cycles ride compact
    cache-id delta frames (docs/control-plane.md). Off, the flat TCP
    star is byte-identical to previous releases. A dispatch knob: must
    agree across ranks. The native core parses the same variable with
    its EnvFlag mirror of ``_get_bool``."""
    return _get_bool(HOROVOD_HIER_CONTROL)


def shm_enabled() -> bool:
    """Whether the shared-memory intra-host transport is on (default
    off): the hierarchical collectives' local legs then move bytes
    through cross-process-mmap'd shm rings with zero socket syscalls,
    TCP PeerLink staying the registered fallback
    (docs/shm-transport.md). A dispatch knob: must agree across ranks.
    The native core parses the same variable with its EnvFlag mirror of
    ``_get_bool``."""
    return _get_bool(HOROVOD_SHM)


def shm_slot_bytes():
    """Operator override for the shm ring-buffer slot size in bytes,
    ``None`` when unset (the native core then derives the slot from the
    fusion cap, clamped to [64 KiB, 256 MiB] — one fused response per
    slot write). Must agree across ranks: segment layout is part of the
    attach validation."""
    v = os.environ.get(HOROVOD_SHM_SLOT_BYTES)
    if not v:
        return None
    try:
        n = int(v)
    except ValueError:
        return None
    return n if n > 0 else None


def shm_fallback_enabled() -> bool:
    """Whether a failed shm attach (or a poisoned channel mid-world)
    falls through to the TCP leg (default on; results are byte-identical
    either way). Disabled, transport failures surface as hard collective
    errors — for deployments that would rather fail fast than silently
    ride loopback TCP."""
    return _get_bool(HOROVOD_SHM_FALLBACK, default=True)


def job_key() -> str:
    """The per-job isolation token ("" when unset). The LAUNCHERS set it
    (run/launch.py and run/elastic/runner.py default it to a random hex
    token in every worker's env); the native controller consumes it —
    hellos carrying a different key are rejected, so two jobs sharing
    one host cannot cross-connect through the default controller port
    (csrc/hvd/operations.cc hashes it FNV-1a into the hello)."""
    return os.environ.get(HOROVOD_JOB_KEY, "")


def ring_tree_threshold() -> int:
    """Small-payload routing threshold for the host ring, in wire bytes
    (default 16 KiB): allreduces at or under it take the binomial-tree
    latency path instead of the chunked bandwidth-optimal ring
    (docs/hierarchical.md). Consumed by the native core
    (csrc/hvd/ring_ops.cc, read once per process); a dispatch knob —
    must agree across ranks."""
    v = _get_int(HOROVOD_RING_TREE_THRESHOLD, DEFAULT_RING_TREE_THRESHOLD)
    return v if v >= 0 else DEFAULT_RING_TREE_THRESHOLD


def max_frame_bytes() -> int:
    """Upper bound, in bytes, on any length-prefixed control/data frame a
    peer can make this process allocate (default 1 GiB — the historical
    hard-coded cap). Consumed by the native socket layer
    (``csrc/hvd/socket.cc`` ``Socket::RecvFrame*``): a frame header
    announcing more than this is rejected and the connection aborted, so
    one corrupt or hostile peer byte can never drive a multi-GiB
    allocation (docs/protocol-models.md, codec-audit section). Clamped to
    [64 KiB, 1 GiB]; must comfortably exceed the fusion threshold plus
    framing overhead or legitimate fused responses are rejected as
    oversized."""
    v = _get_int(HOROVOD_MAX_FRAME_BYTES, DEFAULT_MAX_FRAME_BYTES)
    return max(64 * 1024, min(DEFAULT_MAX_FRAME_BYTES, v))


def stripes() -> int:
    """Parallel TCP connections per cross-host leader pair (default 1 =
    the single-socket path, zero registry overhead). K > 1 registers the
    striped backend (csrc/hvd/stripe_transport.cc) ahead of single-socket
    TCP for the cross legs: chunks round-robin across the K connections
    with per-piece sequence headers, the standard fix for one TCP window
    not filling a fat NIC (docs/cross-transport.md). A dispatch knob:
    must agree across ranks. The native core parses the same variable
    (clamped to [1, 32], matching its poll set)."""
    return max(1, min(32, _get_int(HOROVOD_STRIPES, 1)))


def chunk_bytes():
    """Operator override for the striped transport's pipeline chunk in
    bytes, ``None`` when unset (the native core then uses 256 KiB). The
    unit round-robined across stripes and handed to the pipelined ring
    step's per-piece accumulate hook; the native parse clamps to
    [4 KiB, 16 MiB] and rounds to a 64-byte multiple so piece boundaries
    never split an element. Like ``HOROVOD_STRIPES``, must agree across
    ranks: the receiver derives piece spans from its own value, so a
    mismatch desyncs the stripe streams and aborts the collective."""
    v = os.environ.get(HOROVOD_CHUNK_BYTES)
    if not v:
        return None
    try:
        n = int(v)
    except ValueError:
        return None
    return n if n > 0 else None


def stripe_fallback_enabled() -> bool:
    """Whether a stripe connect failure falls through to single-socket
    TCP in lock-step (default on; results are byte-identical either
    way). Disabled, the failure is a hard collective error — for
    deployments that would rather fail fast than silently lose the
    striped bandwidth (the stripe sibling of ``shm_fallback_enabled``)."""
    return _get_bool(HOROVOD_STRIPE_FALLBACK, default=True)


def zero_stage() -> int:
    """ZeRO partitioning stage for ``zero.py`` states built with
    ``zero_stage="auto"`` (docs/zero.md): 1 shards only optimizer state
    (gradients mean-reduced in full, the classic stage-1 memory shape),
    2 additionally partitions gradients (per-bucket reduce-scatter lands
    each gradient directly in its owning rank's shard — the layout this
    module has always compiled, hence the default), 3 additionally
    partitions parameters (persisted only as the 1/d fp32 master shard;
    the forward pass all-gathers each fusion bucket just in time).
    Clamped to [1, 3]. The stage is stamped into the ``ZeroTrainState``
    at init — a step resolving a different stage is rejected, so this
    knob can never silently flip a live state's layout."""
    return max(1, min(3, _get_int(HOROVOD_ZERO_STAGE, DEFAULT_ZERO_STAGE)))


def zero_prefetch_env():
    """(depth, explicit) for the stage-3 gather prefetch depth
    (docs/zero.md): how many parameter all-gathers beyond the bucket
    currently being consumed may be in flight. 0 fully serializes the
    gathers (bucket i+1's gather waits on bucket i's); depth p chains
    each gather to the gather p+1 buckets earlier, bounding transient
    gathered-parameter memory at ~(p+1) buckets while leaving
    consecutive gathers dataflow-independent for the latency-hiding
    scheduler to overlap with compute. Clamped to [0, 8]. The raw-env
    half of ``fusion.resolve_prefetch_depth("auto")`` — the live config
    (autotuner-pinned value) takes precedence."""
    v, explicit = _get_int_explicit(HOROVOD_ZERO_PREFETCH,
                                    DEFAULT_ZERO_PREFETCH)
    return max(0, min(8, v)), explicit


def link_retry_attempts() -> int:
    """How many times a failed cross-host data link redials in place
    before the failure escalates (csrc/hvd/ring_ops.cc ``HealCrossStep``;
    docs/self-healing.md). 0 disables healing entirely — every link
    failure is the pre-healing hard error. The native core parses the
    same variable with the same default."""
    return max(0, _get_int(HOROVOD_LINK_RETRY_ATTEMPTS,
                           DEFAULT_LINK_RETRY_ATTEMPTS))


def link_retry_backoff_ms() -> int:
    """Sleep between in-place link redial attempts, in ms. Flat (not
    exponential) on purpose: the whole ladder must fit inside
    ``link_retry_deadline_ms``, which is itself a fraction of the
    liveness window."""
    return max(1, _get_int(HOROVOD_LINK_RETRY_BACKOFF_MS,
                           DEFAULT_LINK_RETRY_BACKOFF_MS))


def link_retry_deadline_ms() -> int:
    """Overall wall-clock budget for healing one link failure, in ms.
    SIZE IT WELL BELOW ``HOROVOD_LIVENESS_TIMEOUT_MS`` (default 3000 vs
    10000): a peer that cannot be redialed inside this budget surfaces
    as exactly the pre-healing transport error, so the liveness evict /
    elastic path fires on schedule — healing must never mask a real
    death past the liveness window (docs/self-healing.md sizing rule)."""
    return max(1, _get_int(HOROVOD_LINK_RETRY_DEADLINE_MS,
                           DEFAULT_LINK_RETRY_DEADLINE_MS))


def chaos_spec() -> str:
    """The seeded chaos schedule (tools/chaos_sched.py grammar:
    ``seed=<int>,n=<int>[,kinds=a|b][,points=p|q][,ranks=0|1]
    [,steps=lo-hi][,ms=<float>]``), empty string when unset — the
    zero-cost-disabled case. Compiled deterministically from the seed
    into concrete ``FaultSpec`` entries at ``faults`` arm time, so one
    spec string reproduces the exact same multi-fault schedule on every
    run and every rank (docs/self-healing.md, chaos-spec section)."""
    return os.environ.get(HOROVOD_CHAOS_SPEC, "").strip()


def metrics_export_path():
    """Prometheus-textfile exporter target (docs/metrics.md), ``None``
    when unset/empty — the default, under which NO exporter thread
    starts, no file is written, and no timeline counter events are
    emitted: programs are byte-identical to pre-metrics builds
    (regression-tested). Set to a file path to have rank 0's exporter
    thread atomically rewrite it every ``HOROVOD_METRICS_INTERVAL_MS``
    in node-exporter textfile format."""
    return os.environ.get(HOROVOD_METRICS_EXPORT) or None


def metrics_interval_ms() -> int:
    """How often the metrics exporter thread snapshots and publishes
    (textfile rewrite + timeline counter events). Only meaningful with
    ``HOROVOD_METRICS_EXPORT`` set."""
    return max(100, _get_int(HOROVOD_METRICS_INTERVAL_MS,
                             DEFAULT_METRICS_INTERVAL_MS))


def straggler_ms() -> int:
    """EWMA lag (ms behind the ready group's fastest rank) at which the
    coordinator's straggler detector fires a STRAGGLER_WARNING naming
    the rank (docs/metrics.md has the sizing rule). The native core
    parses the same variable via EnvLL at world init."""
    return max(1, _get_int(HOROVOD_STRAGGLER_MS, DEFAULT_STRAGGLER_MS))


def straggler_patience() -> int:
    """How many CONSECUTIVE ready groups a rank must arrive last before
    a warning can fire — one slow step is noise, `patience` slow steps
    in a row with the threshold-crossing EWMA is attribution. The
    native core parses the same variable via EnvLL at world init."""
    return max(1, _get_int(HOROVOD_STRAGGLER_PATIENCE,
                           DEFAULT_STRAGGLER_PATIENCE))


def heartbeat_ms() -> int:
    """Liveness heartbeat interval in ms; 0 (the default) disables the
    entire liveness plane — no heartbeat threads, no timed gathers, no
    driver-side eviction: behavior is byte-identical to pre-liveness
    builds (regression-tested). Must agree across ranks, like every
    dispatch knob (docs/liveness.md)."""
    return max(0, _get_int(HOROVOD_HEARTBEAT_MS, 0))


def liveness_timeout_ms() -> int:
    """Silence (no frame, no heartbeat) after which a rank is EVICTED;
    SUSPECT fires at half of it. Only meaningful with heartbeats armed.
    Must exceed the longest blocking host-plane collective or a busy
    rank gets falsely evicted (docs/liveness.md has the sizing rule)."""
    return max(1, _get_int(HOROVOD_LIVENESS_TIMEOUT_MS,
                           DEFAULT_LIVENESS_TIMEOUT_MS))


def drain_grace_ms() -> int:
    """How long a preempted worker gets to finish its drain protocol
    (commit + DRAIN farewell) before it force-exits; the drain-armed
    watchdog makes "graceful" bounded so a wedged drain can't outlive
    its host's preemption deadline (docs/liveness.md)."""
    return max(1, _get_int(HOROVOD_DRAIN_GRACE_MS, DEFAULT_DRAIN_GRACE_MS))


def blacklist_strikes() -> int:
    """Failures a host absorbs before its blacklist turns permanent."""
    return max(1, _get_int(HOROVOD_ELASTIC_BLACKLIST_STRIKES,
                           DEFAULT_BLACKLIST_STRIKES))


def parole_window_seconds() -> float:
    """How long a host returning from blacklist cooldown must run clean
    before its strike count resets (0 disables strike decay)."""
    return _get_float(HOROVOD_ELASTIC_PAROLE_WINDOW,
                      DEFAULT_PAROLE_WINDOW_SECONDS)


@dataclasses.dataclass
class RuntimeConfig:
    """Snapshot of all runtime knobs, read once at ``hvd.init()``.

    Mirrors the env parse block of the reference background loop
    (``operations.cc:407-504``) as a dataclass instead of scattered globals.
    """

    fusion_threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES
    # True when the threshold was explicitly set (env var present) or has
    # been autotuned. The XLA-plane bucket cap ("auto" resolution in
    # common/fusion.py) only engages then: the *default* 64 MB exists for
    # the host plane's cycle fusion, and silently bucketing the compiled
    # path by default would change programs under users' feet.
    fusion_threshold_explicit: bool = False
    # On-wire gradient compression mode (common/compression.py). Explicit
    # means env-set or autotuner-pinned; resolve_compression("auto") only
    # engages then — unset keeps every compiled program byte-identical to
    # the uncompressed path (same contract as the fusion threshold).
    compression: str = "none"
    compression_explicit: bool = False
    # Stage-3 gather prefetch depth (zero.py; docs/zero.md). Explicit
    # means env-set or autotuner-pinned; resolve_prefetch_depth("auto")
    # prefers this over the raw env exactly like the fusion threshold.
    zero_prefetch: int = DEFAULT_ZERO_PREFETCH
    zero_prefetch_explicit: bool = False
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    cache_capacity: int = DEFAULT_CACHE_CAPACITY
    timeline_filename: str = ""
    timeline_mark_cycles: bool = False
    autotune: bool = False
    autotune_log: str = ""
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    stall_check_disable: bool = False
    stall_warning_seconds: float = DEFAULT_STALL_WARNING_SECONDS
    stall_shutdown_seconds: float = 0.0
    elastic: bool = False
    host_via_xla: bool = False
    host_via_xla_threshold: int = DEFAULT_HOST_VIA_XLA_THRESHOLD

    @classmethod
    def from_env(cls) -> "RuntimeConfig":
        fusion_bytes, fusion_explicit = _get_int_explicit(
            HOROVOD_FUSION_THRESHOLD, DEFAULT_FUSION_THRESHOLD_BYTES)
        compression, compression_explicit = _get_choice_explicit(
            HOROVOD_COMPRESSION, COMPRESSION_CHOICES, "none")
        prefetch, prefetch_explicit = zero_prefetch_env()
        return cls(
            fusion_threshold_bytes=fusion_bytes,
            fusion_threshold_explicit=fusion_explicit,
            compression=compression,
            compression_explicit=compression_explicit,
            zero_prefetch=prefetch,
            zero_prefetch_explicit=prefetch_explicit,
            cycle_time_ms=_get_float(HOROVOD_CYCLE_TIME, DEFAULT_CYCLE_TIME_MS),
            cache_capacity=_get_int(HOROVOD_CACHE_CAPACITY, DEFAULT_CACHE_CAPACITY),
            timeline_filename=os.environ.get(HOROVOD_TIMELINE, ""),
            timeline_mark_cycles=_get_bool(HOROVOD_TIMELINE_MARK_CYCLES),
            autotune=_get_bool(HOROVOD_AUTOTUNE),
            autotune_log=os.environ.get(HOROVOD_AUTOTUNE_LOG, ""),
            autotune_warmup_samples=_get_int(HOROVOD_AUTOTUNE_WARMUP_SAMPLES, 3),
            autotune_steps_per_sample=_get_int(HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE, 10),
            autotune_bayes_opt_max_samples=_get_int(
                HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, 20
            ),
            autotune_gaussian_process_noise=_get_float(
                HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, 0.8
            ),
            hierarchical_allreduce=_get_bool(HOROVOD_HIERARCHICAL_ALLREDUCE),
            hierarchical_allgather=_get_bool(HOROVOD_HIERARCHICAL_ALLGATHER),
            stall_check_disable=_get_bool(HOROVOD_STALL_CHECK_DISABLE),
            stall_warning_seconds=_get_float(
                HOROVOD_STALL_CHECK_TIME_SECONDS, DEFAULT_STALL_WARNING_SECONDS
            ),
            stall_shutdown_seconds=_get_float(HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, 0.0),
            elastic=_get_bool(HOROVOD_ELASTIC),
            host_via_xla=_get_bool(HOROVOD_HOST_VIA_XLA),
            host_via_xla_threshold=_get_int(
                HOROVOD_HOST_VIA_XLA_THRESHOLD,
                DEFAULT_HOST_VIA_XLA_THRESHOLD),
        )
