"""Runtime configuration: one env-var layer, Horovod-compatible knob names.

The reference converges three config layers on env vars (SURVEY §5; knob
names in ``common.h:62-88``, parsed in ``operations.cc:407-504``). We keep
the same user-facing names (HOROVOD_*) so reference users find every knob,
and add TPU-specific ones under the same prefix.
"""

from __future__ import annotations

import dataclasses
import os

# ---- knob names (reference: common.h:62-88) --------------------------------
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE = "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_COMPRESSION = "HOROVOD_COMPRESSION"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
HOROVOD_LOG_HIDE_TIME = "HOROVOD_LOG_HIDE_TIME"
# launch-time topology (reference: gloo_context.cc:40-54)
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_CONTROLLER_ADDR = "HOROVOD_CONTROLLER_ADDR"
HOROVOD_CONTROLLER_PORT = "HOROVOD_CONTROLLER_PORT"
HOROVOD_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
# TPU-specific additions
HOROVOD_TPU_MESH_AXES = "HOROVOD_TPU_MESH_AXES"
HOROVOD_TPU_DONUT_SIZE = "HOROVOD_TPU_DONUT_SIZE"
HOROVOD_ELASTIC = "HOROVOD_ELASTIC"
HOROVOD_HOST_VIA_XLA = "HOROVOD_HOST_VIA_XLA"
HOROVOD_HOST_VIA_XLA_THRESHOLD = "HOROVOD_HOST_VIA_XLA_THRESHOLD"
DEFAULT_HOST_VIA_XLA_THRESHOLD = 1 << 20  # 1 MiB fused response
HOROVOD_ELASTIC_REJOIN_GRACE = "HOROVOD_ELASTIC_REJOIN_GRACE"

DEFAULT_FUSION_THRESHOLD_BYTES = 64 * 1024 * 1024  # reference operations.cc:423
DEFAULT_CYCLE_TIME_MS = 5.0  # reference operations.cc:431
DEFAULT_CACHE_CAPACITY = 1024
DEFAULT_STALL_WARNING_SECONDS = 60.0  # reference stall_inspector.h:75


def native_controller_port(default: int = 29500) -> int:
    """The native controller's TCP port. ``HOROVOD_CONTROLLER_PORT`` is the
    *base* coordination port (``jax.distributed`` / gRPC takes it); the
    native controller always binds base+1. Every derivation of the +1
    convention goes through here."""
    return _get_int(HOROVOD_CONTROLLER_PORT, default) + 1


def _get_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _get_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


def _get_int_explicit(name: str, default: int):
    """(value, explicit): the parsed env int and whether it counts as an
    explicit setting. Unset OR unparseable → (default, False) — an
    unparseable value must not count as explicit, or it would silently
    flip the XLA plane's "auto" bucket cap to the 64 MB host-plane
    default. The single parse shared by both fusion-threshold fields."""
    v = os.environ.get(name)
    try:
        return (int(v), True) if v is not None else (default, False)
    except ValueError:
        return default, False


# On-wire gradient compression modes (common/compression.py;
# docs/compression.md). "ef16" = fp16 wire + error-feedback residuals.
COMPRESSION_CHOICES = ("none", "fp16", "bf16", "ef16")


def _get_choice_explicit(name: str, choices, default: str):
    """(value, explicit) for an enumerated env knob. Unset OR an unknown
    value → (default, False) — a typo'd mode must not count as explicit
    (same tolerance contract as ``_get_int_explicit``), but it is worth
    a warning: silently training uncompressed under a misspelled
    ``HOROVOD_COMPRESSION`` would be a nasty surprise."""
    v = os.environ.get(name)
    if v is None:
        return default, False
    v = v.strip().lower()
    if v in choices:
        return v, True
    from . import logging as _log

    _log.warning(f"{name}={v!r} is not one of {sorted(choices)}; "
                 f"ignoring (using {default!r})")
    return default, False


def parse_compression_env() -> str:
    """The env-level compression mode ("none" when unset/invalid) — the
    raw-env half of ``compression.resolve_compression('auto')``'s
    precedence (live config first, then this)."""
    v, _ = _get_choice_explicit(HOROVOD_COMPRESSION, COMPRESSION_CHOICES,
                                "none")
    return v


def _get_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v is not None else default
    except ValueError:
        return default


@dataclasses.dataclass
class RuntimeConfig:
    """Snapshot of all runtime knobs, read once at ``hvd.init()``.

    Mirrors the env parse block of the reference background loop
    (``operations.cc:407-504``) as a dataclass instead of scattered globals.
    """

    fusion_threshold_bytes: int = DEFAULT_FUSION_THRESHOLD_BYTES
    # True when the threshold was explicitly set (env var present) or has
    # been autotuned. The XLA-plane bucket cap ("auto" resolution in
    # common/fusion.py) only engages then: the *default* 64 MB exists for
    # the host plane's cycle fusion, and silently bucketing the compiled
    # path by default would change programs under users' feet.
    fusion_threshold_explicit: bool = False
    # On-wire gradient compression mode (common/compression.py). Explicit
    # means env-set or autotuner-pinned; resolve_compression("auto") only
    # engages then — unset keeps every compiled program byte-identical to
    # the uncompressed path (same contract as the fusion threshold).
    compression: str = "none"
    compression_explicit: bool = False
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS
    cache_capacity: int = DEFAULT_CACHE_CAPACITY
    timeline_filename: str = ""
    timeline_mark_cycles: bool = False
    autotune: bool = False
    autotune_log: str = ""
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 20
    autotune_gaussian_process_noise: float = 0.8
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    stall_check_disable: bool = False
    stall_warning_seconds: float = DEFAULT_STALL_WARNING_SECONDS
    stall_shutdown_seconds: float = 0.0
    elastic: bool = False
    host_via_xla: bool = False
    host_via_xla_threshold: int = DEFAULT_HOST_VIA_XLA_THRESHOLD

    @classmethod
    def from_env(cls) -> "RuntimeConfig":
        fusion_bytes, fusion_explicit = _get_int_explicit(
            HOROVOD_FUSION_THRESHOLD, DEFAULT_FUSION_THRESHOLD_BYTES)
        compression, compression_explicit = _get_choice_explicit(
            HOROVOD_COMPRESSION, COMPRESSION_CHOICES, "none")
        return cls(
            fusion_threshold_bytes=fusion_bytes,
            fusion_threshold_explicit=fusion_explicit,
            compression=compression,
            compression_explicit=compression_explicit,
            cycle_time_ms=_get_float(HOROVOD_CYCLE_TIME, DEFAULT_CYCLE_TIME_MS),
            cache_capacity=_get_int(HOROVOD_CACHE_CAPACITY, DEFAULT_CACHE_CAPACITY),
            timeline_filename=os.environ.get(HOROVOD_TIMELINE, ""),
            timeline_mark_cycles=_get_bool(HOROVOD_TIMELINE_MARK_CYCLES),
            autotune=_get_bool(HOROVOD_AUTOTUNE),
            autotune_log=os.environ.get(HOROVOD_AUTOTUNE_LOG, ""),
            autotune_warmup_samples=_get_int(HOROVOD_AUTOTUNE_WARMUP_SAMPLES, 3),
            autotune_steps_per_sample=_get_int(HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE, 10),
            autotune_bayes_opt_max_samples=_get_int(
                HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, 20
            ),
            autotune_gaussian_process_noise=_get_float(
                HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, 0.8
            ),
            hierarchical_allreduce=_get_bool(HOROVOD_HIERARCHICAL_ALLREDUCE),
            hierarchical_allgather=_get_bool(HOROVOD_HIERARCHICAL_ALLGATHER),
            stall_check_disable=_get_bool(HOROVOD_STALL_CHECK_DISABLE),
            stall_warning_seconds=_get_float(
                HOROVOD_STALL_CHECK_TIME_SECONDS, DEFAULT_STALL_WARNING_SECONDS
            ),
            stall_shutdown_seconds=_get_float(HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, 0.0),
            elastic=_get_bool(HOROVOD_ELASTIC),
            host_via_xla=_get_bool(HOROVOD_HOST_VIA_XLA),
            host_via_xla_threshold=_get_int(
                HOROVOD_HOST_VIA_XLA_THRESHOLD,
                DEFAULT_HOST_VIA_XLA_THRESHOLD),
        )
