"""ctypes binding to the native core runtime (libhvdtpu.so).

Plays the role of the reference's ``HorovodBasics`` ctypes layer
(``common/basics.py:22-211``): loads the shared library, exposes the C API,
and bridges the XLA-plane execution callback. The native library owns the
background cycle thread, tensor queue, controller negotiation, fusion
planning, response cache, and stall inspection (``csrc/hvd/*``); Python owns
only XLA program execution.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from . import config as _config
from . import logging as _log

_LIB_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "lib")
_CSRC_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "csrc")


def _lib_path() -> str:
    """The artifact for the selected build variant: the sanitizer
    variants live BESIDE the production .so (``libhvdtpu_{tsan,asan}.so``,
    csrc/Makefile) so an instrumented run never clobbers or masquerades
    as the normal build."""
    san = _config.native_sanitize()
    name = f"libhvdtpu_{san}.so" if san else "libhvdtpu.so"
    return os.path.join(_LIB_DIR, name)

# dtype codes must match csrc/hvd/common.h DataType
DTYPE_CODES = {
    "uint8": 0,
    "int8": 1,
    "uint16": 2,
    "int16": 3,
    "int32": 4,
    "int64": 5,
    "float16": 6,
    "float32": 7,
    "float64": 8,
    "bool": 9,
    "bfloat16": 10,
}

OP_ALLREDUCE = 0
OP_ALLGATHER = 1
OP_BROADCAST = 2
OP_JOIN = 3
OP_REDUCESCATTER = 4
OP_ALLTOALL = 5
OP_BARRIER = 6

PLANE_XLA = 0
PLANE_HOST = 1

_EXEC_CB_TYPE = ctypes.CFUNCTYPE(None, ctypes.POINTER(ctypes.c_char),
                                 ctypes.c_int, ctypes.c_long)
# hvd_enqueue_cb's per-handle completion callback:
# done(done_arg, handle, ok, reason)
_DONE_CB_TYPE = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_longlong,
                                 ctypes.c_int, ctypes.c_char_p)


def _build_library() -> bool:
    try:
        san = _config.native_sanitize()
        cmd = ["make", "-C", _CSRC_DIR] + ([san] if san else [])
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        return os.path.exists(_lib_path())
    # hvdlint: ignore[exception-discipline] -- build probe: the native
    # core is optional and no collective exists before it loads
    except Exception as e:  # compiler missing etc.
        _log.warning(f"native runtime build failed: {e}")
        return False


_lib = None
# Every registered CFUNCTYPE trampoline stays referenced forever: the C++
# cycle thread may hold a superseded pointer across a re-registration
# (host_staging replacing the host world's placeholder), and freeing it
# would turn that in-flight call into a jump to freed memory.
_keepalive_cbs = []


def load_library():
    """Load (building if necessary) the native library; None on failure
    or when disabled. The HOROVOD_NATIVE gate is checked before the cache
    so disabling it mid-process (tests, a re-init after a bad native
    world) is honored even after an earlier load."""
    global _lib
    if not _config.native_enabled():
        return None
    if _lib is not None:
        return _lib
    lib_path = _lib_path()
    if not os.path.exists(lib_path) and not _build_library():
        return None
    try:
        lib = ctypes.CDLL(lib_path)
        return _bind_prototypes(lib)
    except (OSError, AttributeError) as e:
        # A stale .so from an older build (missing symbols) or a
        # corrupt/wrong-arch one: rebuild once, then either bind the
        # fresh library or degrade to direct mode — never crash init.
        _log.warning(f"native library unusable ({e}); rebuilding")
        if not _build_library():
            return None
        try:
            _lib = None
            lib = ctypes.CDLL(lib_path)
            return _bind_prototypes(lib)
        except (OSError, AttributeError) as e2:
            _log.warning(f"native library still unusable after rebuild "
                         f"({e2}); using direct mode")
            return None


def _bind_prototypes(lib):
    global _lib
    lib.hvd_init.restype = ctypes.c_int
    lib.hvd_init.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_double, ctypes.c_longlong, ctypes.c_int,
        ctypes.c_double, ctypes.c_double, ctypes.c_int, ctypes.c_int,
        ctypes.c_int,
    ]
    lib.hvd_shutdown.restype = None
    lib.hvd_enqueue.restype = ctypes.c_longlong
    lib.hvd_enqueue.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int, ctypes.c_double, ctypes.c_double,
        ctypes.c_int,
    ]
    lib.hvd_enqueue_chips.restype = ctypes.c_longlong
    lib.hvd_enqueue_chips.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int, ctypes.c_double, ctypes.c_double,
        ctypes.c_int,
    ]
    lib.hvd_test.restype = ctypes.c_int
    lib.hvd_test.argtypes = [ctypes.c_longlong, ctypes.c_char_p,
                             ctypes.c_int]
    lib.hvd_wait.restype = ctypes.c_int
    lib.hvd_wait.argtypes = [ctypes.c_longlong, ctypes.c_char_p,
                             ctypes.c_int]
    lib.hvd_response_done.restype = None
    lib.hvd_response_done.argtypes = [ctypes.c_long, ctypes.c_int,
                                      ctypes.c_char_p]
    lib.hvd_register_exec_callback.restype = None
    lib.hvd_register_exec_callback.argtypes = [_EXEC_CB_TYPE]
    lib.hvd_pending_count.restype = ctypes.c_int
    lib.hvd_set_host_via_xla.restype = None
    lib.hvd_set_host_via_xla.argtypes = [ctypes.c_longlong]
    lib.hvd_inflight_ptrs.restype = ctypes.c_int
    lib.hvd_inflight_ptrs.argtypes = [
        ctypes.c_long, ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p),
    ]
    lib.hvd_inflight_handle.restype = ctypes.c_longlong
    lib.hvd_inflight_handle.argtypes = [ctypes.c_long, ctypes.c_char_p]
    lib.hvd_store_result.restype = ctypes.c_int
    lib.hvd_store_result.argtypes = [
        ctypes.c_longlong, ctypes.c_void_p, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
    ]
    lib.hvd_join.restype = ctypes.c_longlong
    lib.hvd_join.argtypes = []
    lib.hvd_last_joined.restype = ctypes.c_int
    lib.hvd_last_joined.argtypes = []
    lib.hvd_result_bytes.restype = ctypes.c_longlong
    lib.hvd_result_bytes.argtypes = [ctypes.c_longlong]
    lib.hvd_result_dims.restype = ctypes.c_int
    lib.hvd_result_dims.argtypes = [ctypes.c_longlong,
                                    ctypes.POINTER(ctypes.c_longlong),
                                    ctypes.c_int]
    lib.hvd_result_fetch.restype = ctypes.c_int
    lib.hvd_result_fetch.argtypes = [ctypes.c_longlong, ctypes.c_void_p,
                                     ctypes.c_longlong]
    lib.hvd_set_parameters.restype = None
    lib.hvd_set_parameters.argtypes = [ctypes.c_double, ctypes.c_longlong]
    lib.hvd_set_hier_flags.restype = None
    lib.hvd_set_hier_flags.argtypes = [ctypes.c_int]
    lib.hvd_get_hier_flags.restype = ctypes.c_int
    lib.hvd_get_cycle_time_ms.restype = ctypes.c_double
    lib.hvd_cache_hits.restype = ctypes.c_longlong
    lib.hvd_stall_report.restype = ctypes.c_int
    lib.hvd_stall_report.argtypes = [ctypes.POINTER(ctypes.c_char),
                                     ctypes.c_int]
    lib.hvd_drain.restype = None
    lib.hvd_drain.argtypes = []
    lib.hvd_liveness_report.restype = ctypes.c_int
    lib.hvd_liveness_report.argtypes = [ctypes.POINTER(ctypes.c_char),
                                        ctypes.c_int]
    lib.hvd_set_record_negotiation.restype = None
    lib.hvd_set_record_negotiation.argtypes = [ctypes.c_int]
    lib.hvd_drain_negotiation.restype = ctypes.c_int
    lib.hvd_drain_negotiation.argtypes = [ctypes.POINTER(ctypes.c_char),
                                          ctypes.c_int]
    lib.hvd_get_fusion_threshold.restype = ctypes.c_longlong
    lib.hvd_ring_bytes_sent.restype = ctypes.c_longlong
    lib.hvd_ring_bytes_sent.argtypes = []
    lib.hvd_ring_local_bytes.restype = ctypes.c_longlong
    lib.hvd_ring_local_bytes.argtypes = []
    lib.hvd_ring_cross_bytes.restype = ctypes.c_longlong
    lib.hvd_ring_cross_bytes.argtypes = []
    lib.hvd_ring_shm_bytes.restype = ctypes.c_longlong
    lib.hvd_ring_shm_bytes.argtypes = []
    lib.hvd_shm_active.restype = ctypes.c_int
    lib.hvd_shm_active.argtypes = []
    lib.hvd_ring_stripe_bytes.restype = ctypes.c_longlong
    lib.hvd_ring_stripe_bytes.argtypes = []
    lib.hvd_ring_cross_ns.restype = ctypes.c_longlong
    lib.hvd_ring_cross_ns.argtypes = []
    lib.hvd_ring_stripe_count.restype = ctypes.c_int
    lib.hvd_ring_stripe_count.argtypes = []
    lib.hvd_set_stripes.restype = None
    lib.hvd_set_stripes.argtypes = [ctypes.c_int]
    lib.hvd_host_hier_flags.restype = ctypes.c_int
    lib.hvd_host_hier_flags.argtypes = []
    lib.hvd_metrics_snapshot.restype = ctypes.c_int
    lib.hvd_metrics_snapshot.argtypes = [ctypes.POINTER(ctypes.c_char),
                                         ctypes.c_int, ctypes.c_int]
    # Contract-only bindings: no NativeCore wrapper uses these yet (the
    # topology getters are served by Python-side state; the callback
    # enqueue is reached through hvd_enqueue), but declaring
    # restype/argtypes for EVERY extern "C" export keeps the ctypes
    # surface in lock-step with operations.cc — hvdlint's
    # binding-contract check cross-checks existence and arity both ways,
    # so a renamed export or drifted signature fails the lint, not a
    # 3 a.m. load.
    lib.hvd_initialized.restype = ctypes.c_int
    lib.hvd_initialized.argtypes = []
    lib.hvd_rank.restype = ctypes.c_int
    lib.hvd_rank.argtypes = []
    lib.hvd_size.restype = ctypes.c_int
    lib.hvd_size.argtypes = []
    lib.hvd_local_rank.restype = ctypes.c_int
    lib.hvd_local_rank.argtypes = []
    lib.hvd_local_size.restype = ctypes.c_int
    lib.hvd_local_size.argtypes = []
    lib.hvd_cross_rank.restype = ctypes.c_int
    lib.hvd_cross_rank.argtypes = []
    lib.hvd_cross_size.restype = ctypes.c_int
    lib.hvd_cross_size.argtypes = []
    lib.hvd_enqueue_cb.restype = ctypes.c_longlong
    lib.hvd_enqueue_cb.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int, ctypes.c_double, ctypes.c_double,
        ctypes.c_int, _DONE_CB_TYPE, ctypes.c_void_p,
    ]
    _lib = lib
    return _lib


# ---- response wire parsing (mirror of csrc/hvd/message.cc) -----------------


@dataclass
class NativeResponse:
    op: int
    reduce_op: int
    dtype: int
    plane: int
    root_rank: int
    error: str
    prescale: float
    postscale: float
    names: List[str] = field(default_factory=list)
    shapes: List[Tuple[int, ...]] = field(default_factory=list)
    # allgather only: per-tensor per-rank first-dim sizes (ragged support)
    first_dims: List[Tuple[int, ...]] = field(default_factory=list)
    # autotuned hierarchical-dispatch flags stamped into this frame
    # (bit0 = allreduce, bit1 = allgather; -1 = untuned -> env config)
    hier_flags: int = -1
    # autotuned cross-host stripe count riding the same piggyback
    # (-1 = untuned; consumed by the native cycle loop, carried here so
    # the parse stays a faithful mirror of the wire layout)
    stripes: int = -1
    # world incarnation the coordinator stamped (docs/self-healing.md);
    # a worker holding a different epoch is split-brained and shuts
    # down. -1 = no hint.
    epoch: int = -1


class FrameRejected(ValueError):
    """A structurally invalid response frame (truncated, bad magic, or a
    count/length field outside the wire contract). The mirror of the C++
    ``DeserializeResponseList`` returning false: the two codecs must
    accept and reject IDENTICALLY — the differential fuzzer in
    tests/test_hvdmc.py holds them to it."""


class _Cursor:
    """Bounds-checked little-endian reader — the Python twin of
    ``hvd::Reader`` (csrc/hvd/message.h). Every read past the end and
    every out-of-range count raises ``FrameRejected`` instead of
    ``struct.error``/``IndexError``, and count-driven loops are bounded
    by the bytes actually present, so a hostile length field can never
    drive a huge allocation or a multi-million-iteration spin."""

    def __init__(self, data: bytes):
        self.d = data
        self.o = 0

    def _take(self, n: int) -> int:
        o = self.o
        if o + n > len(self.d):
            raise FrameRejected(f"truncated frame: {n} bytes needed at "
                                f"offset {o} of {len(self.d)}")
        self.o = o + n
        return o

    def remaining(self) -> int:
        return len(self.d) - self.o

    def u8(self):
        return self.d[self._take(1)]

    def i32(self):
        return struct.unpack_from("<i", self.d, self._take(4))[0]

    def i64(self):
        return struct.unpack_from("<q", self.d, self._take(8))[0]

    def f64(self):
        return struct.unpack_from("<d", self.d, self._take(8))[0]

    def s(self):
        n = self.i32()
        if n < 0 or n > self.remaining():
            raise FrameRejected(f"bad string length {n} at offset "
                                f"{self.o}")
        return self.d[self._take(n): self.o].decode(errors="replace")

    def count(self, limit: int = 1 << 24) -> int:
        """A count-prefixed list header: mirror of the C++
        ``n < 0 || n > (1 << 24)`` rejections."""
        n = self.i32()
        if n < 0 or n > limit:
            raise FrameRejected(f"count {n} outside [0, {limit}]")
        return n


def parse_response_list(data: bytes) -> List[NativeResponse]:
    """Parse one response broadcast frame; raises ``FrameRejected`` on
    any structurally invalid input — byte-for-byte the same accept/
    reject verdicts as the C++ ``DeserializeResponseList`` (asserted by
    the differential codec fuzzer, docs/protocol-models.md)."""
    c = _Cursor(data)
    if c.u8() != 0xA2:
        raise FrameRejected("bad response magic")
    # Tuned-parameter piggyback (mirror of SerializeResponseList):
    # cycle/fusion hints ride every response frame and are applied in the
    # C++ worker cycle; the hierarchical-dispatch flags are stamped into
    # each frame at PerformOperation time and consumed HERE — the
    # executor must dispatch this frame's responses with exactly these
    # flags to stay in lockstep with every other rank.
    c.f64()
    c.i64()
    hier_flags = c.i32()
    stripes = c.i32()
    epoch = c.i64()
    out = []
    for _ in range(c.count()):
        r = NativeResponse(op=c.u8(), reduce_op=c.u8(), dtype=c.u8(),
                           plane=c.u8(), root_rank=c.i32(), error=c.s(),
                           prescale=c.f64(), postscale=c.f64(),
                           hier_flags=hier_flags, stripes=stripes,
                           epoch=epoch)
        for _ in range(c.count()):
            r.names.append(c.s())
            ndim = c.i32()
            if ndim < 0 or ndim >= 256:
                # Mirror of ReadShape: out-of-range rank rejects the
                # frame (skipping would misalign every later field).
                raise FrameRejected(f"shape rank {ndim} outside [0, 256)")
            r.shapes.append(tuple(c.i64() for _ in range(ndim)))
        for _ in range(c.count()):
            nr = c.count()
            r.first_dims.append(tuple(c.i64() for _ in range(nr)))
        out.append(r)
    return out


@dataclass
class NativeDelta:
    """One parsed delta control frame (hierarchical control plane,
    docs/control-plane.md): a fully-cached cycle's submissions as a
    response-cache-id bitset."""
    rank: int
    cached_ids: Tuple[int, ...]
    shutdown: bool
    drain: bool


@dataclass
class NativeAggMember:
    rank: int
    kind: int  # 0 = request-list body, 1 = delta body
    body: bytes


@dataclass
class NativeAggregate:
    """One parsed leader->coordinator aggregate frame: every member's
    control frame embedded verbatim as a length-prefixed body."""
    members: List[NativeAggMember]
    shutdown: bool
    drain: bool


def parse_delta_frame(data: bytes) -> NativeDelta:
    """Parse one delta control frame; raises ``FrameRejected`` on any
    structurally invalid input — verdict-identical to the C++
    ``DeserializeDeltaFrame`` (held to it by the differential fuzzer)."""
    c = _Cursor(data)
    if c.u8() != 0xA5:
        raise FrameRejected("bad delta magic")
    flags = c.u8()
    rank = c.i32()
    base = c.i32()
    nbits = c.i32()
    if rank < 0 or base < 0 or nbits < 0 or nbits > (1 << 24):
        raise FrameRejected(f"delta header out of range: rank {rank}, "
                            f"base {base}, span {nbits}")
    nbytes = (nbits + 7) // 8
    if c.remaining() < nbytes:
        raise FrameRejected(f"truncated delta bitset: {nbytes} bytes "
                            f"needed, {c.remaining()} present")
    bits = c.d[c.o:c.o + nbytes]
    ids = tuple(base + i for i in range(nbits)
                if bits[i // 8] & (1 << (i % 8)))
    return NativeDelta(rank=rank, cached_ids=ids,
                       shutdown=bool(flags & 1), drain=bool(flags & 2))


def parse_aggregate_frame(data: bytes) -> NativeAggregate:
    """Parse one aggregate control frame; raises ``FrameRejected`` on
    any structurally invalid input — verdict-identical to the C++
    ``DeserializeAggregateFrame``."""
    c = _Cursor(data)
    if c.u8() != 0xA4:
        raise FrameRejected("bad aggregate magic")
    flags = c.u8()
    members = []
    # Same clamp family as the C++ side: a host holds at most a few
    # hundred ranks, 2^16 members in one aggregate is hostile.
    for _ in range(c.count(limit=1 << 16)):
        rank = c.i32()
        kind = c.u8()
        n = c.i32()
        if n < 0 or n > c.remaining():
            raise FrameRejected(f"bad aggregate body length {n}")
        body = c.d[c._take(n): c.o]
        if rank < 0 or kind not in (0, 1):
            raise FrameRejected(f"bad aggregate member: rank {rank}, "
                                f"kind {kind}")
        members.append(NativeAggMember(rank=rank, kind=kind, body=body))
    return NativeAggregate(members=members, shutdown=bool(flags & 1),
                           drain=bool(flags & 2))


@dataclass
class NativeResume:
    """One parsed link resume frame (docs/self-healing.md): after a
    cross-host data link redials in place, each end announces its world
    epoch and how many frames it has sent/received, so both sides agree
    which in-flight chunk to replay and which to discard."""
    epoch: int
    rank: int
    send_seq: int
    recv_seq: int


def parse_resume_frame(data: bytes) -> NativeResume:
    """Parse one link resume frame; raises ``FrameRejected`` on any
    structurally invalid input — verdict-identical to the C++
    ``DeserializeResume`` (a negative rank or seq rejects: counters only
    ever grow from zero, so a negative one is a desynced stream)."""
    c = _Cursor(data)
    if c.u8() != 0xA6:
        raise FrameRejected("bad resume magic")
    epoch = c.i64()
    rank = c.i32()
    send_seq = c.i64()
    recv_seq = c.i64()
    if rank < 0 or send_seq < 0 or recv_seq < 0:
        raise FrameRejected(f"resume fields out of range: rank {rank}, "
                            f"send_seq {send_seq}, recv_seq {recv_seq}")
    return NativeResume(epoch=epoch, rank=rank, send_seq=send_seq,
                        recv_seq=recv_seq)


# ---- high-level wrapper ----------------------------------------------------


class NativeCore:
    """One per process. Wraps init/shutdown/enqueue/wait + exec callback."""

    def __init__(self):
        self.lib = load_library()
        self.available = self.lib is not None
        self._executor = None
        self._neg_buf = None  # lazily-allocated drain buffer (hot path)

    def init(self, rank: int, size: int, local_rank: int, local_size: int,
             cross_rank: int, cross_size: int, coordinator_addr: str,
             coordinator_port: int, my_host: str, cycle_time_ms: float,
             fusion_threshold: int, cache_capacity: int,
             stall_warning_sec: float, stall_shutdown_sec: float,
             stall_check_enabled: bool, exec_callback,
             heartbeat_ms: int = 0, liveness_timeout_ms: int = 0) -> bool:
        """exec_callback(responses: List[NativeResponse], response_id: int)
        is invoked from the native background thread; it must be quick
        (push to an executor queue). ``heartbeat_ms=0`` (the default)
        keeps the controller's pre-liveness blocking protocol; > 0 arms
        heartbeat frames + the timed gather (docs/liveness.md)."""
        if not self.available:
            return False
        self.register_exec_callback(exec_callback)
        rc = self.lib.hvd_init(
            rank, size, local_rank, local_size, cross_rank, cross_size,
            coordinator_addr.encode(), coordinator_port, my_host.encode(),
            cycle_time_ms, fusion_threshold, cache_capacity,
            stall_warning_sec, stall_shutdown_sec,
            1 if stall_check_enabled else 0, heartbeat_ms,
            liveness_timeout_ms)
        return rc == 0

    def register_exec_callback(self, exec_callback) -> None:
        """(Re-)install the executor callback. Callable after init too —
        the host-staging executor replaces the host world's reject-XLA
        placeholder when HOROVOD_HOST_VIA_XLA activates."""

        def _cb(data_ptr, length, response_id):
            try:
                raw = ctypes.string_at(data_ptr, length)
                exec_callback(parse_response_list(raw), response_id)
            # hvdlint: ignore[exception-discipline] -- an exception must
            # never cross into the C++ cycle thread; response_done(False)
            # is the error channel every waiting rank raises from
            except Exception as e:
                _log.error(f"exec callback error: {e}")
                self.response_done(response_id, False, str(e))

        trampoline = _EXEC_CB_TYPE(_cb)
        _keepalive_cbs.append(trampoline)
        self.lib.hvd_register_exec_callback(trampoline)

    def set_host_via_xla(self, threshold: int) -> None:
        """Route fused host-plane allreduces >= threshold bytes to the
        executor callback for XLA-plane staging; -1 disables."""
        if self.available:
            self.lib.hvd_set_host_via_xla(threshold)

    def inflight_ptrs(self, response_id: int, name: str):
        """Raw (data_ptr, output_ptr) of one named entry of an in-flight
        response; None when this rank holds no such entry (joined)."""
        data = ctypes.c_void_p()
        out = ctypes.c_void_p()
        r = self.lib.hvd_inflight_ptrs(response_id, name.encode(),
                                       ctypes.byref(data), ctypes.byref(out))
        if r != 1:
            return None
        return data.value, out.value

    def inflight_handle(self, response_id: int, name: str) -> int:
        """Native handle of one named in-flight entry (-1 if absent)."""
        return int(self.lib.hvd_inflight_handle(response_id, name.encode()))

    def store_result(self, handle: int, data: bytes,
                     dims: Tuple[int, ...]) -> None:
        """Deposit an executor-allocated result for ``handle`` (staged
        allgather); the caller fetches it via ``result_fetch``."""
        arr = (ctypes.c_longlong * len(dims))(*dims)
        self.lib.hvd_store_result(handle, data, len(data), arr, len(dims))

    def shutdown(self):
        if self.available:
            self.lib.hvd_shutdown()

    def drain(self):
        """Mark this rank's departure as a graceful DRAIN (preemption):
        the final controller frame sent during the following
        ``shutdown()`` carries the drain flag, so the coordinator logs a
        clean departure — zero blacklist strikes — instead of a crash."""
        if self.available:
            self.lib.hvd_drain()

    def enqueue(self, name: str, op: int, reduce_op: int, dtype_code: int,
                shape: Tuple[int, ...], data_ptr: Optional[int] = None,
                output_ptr: Optional[int] = None, root_rank: int = -1,
                prescale: float = 1.0, postscale: float = 1.0,
                plane: int = PLANE_XLA,
                chip_dims: Optional[Tuple[int, ...]] = None) -> int:
        """``chip_dims`` (allgather, XLA plane): first dims of the chips
        this process drives, possibly ragged; they ride the Request so the
        coordinator publishes the per-chip dim table in the response."""
        arr = (ctypes.c_longlong * len(shape))(*shape)
        if chip_dims:
            cd = (ctypes.c_longlong * len(chip_dims))(*chip_dims)
            h = self.lib.hvd_enqueue_chips(
                name.encode(), op, reduce_op, dtype_code, arr, len(shape),
                cd, len(chip_dims), data_ptr or None, output_ptr or None,
                root_rank, prescale, postscale, plane)
        else:
            h = self.lib.hvd_enqueue(
                name.encode(), op, reduce_op, dtype_code, arr, len(shape),
                data_ptr or None, output_ptr or None, root_rank, prescale,
                postscale, plane)
        return int(h)

    def test(self, handle: int) -> Tuple[int, str]:
        buf = ctypes.create_string_buffer(1024)
        r = self.lib.hvd_test(handle, buf, 1024)
        return r, buf.value.decode(errors="replace")

    def wait(self, handle: int) -> Tuple[int, str]:
        buf = ctypes.create_string_buffer(1024)
        r = self.lib.hvd_wait(handle, buf, 1024)
        return r, buf.value.decode(errors="replace")

    def response_done(self, response_id: int, ok: bool, error: str = ""):
        self.lib.hvd_response_done(response_id, 1 if ok else 0,
                                   error.encode())

    def pending_count(self) -> int:
        return int(self.lib.hvd_pending_count())

    def join(self) -> int:
        """Enqueue a JOIN; returns a handle resolved when all ranks join."""
        return int(self.lib.hvd_join())

    def result_fetch(self, handle: int):
        """Fetch an executor-allocated result (ragged allgather): returns
        (bytes, per_rank_first_dims) and erases the stored buffer, or None
        if the handle has no stored result."""
        n = int(self.lib.hvd_result_bytes(handle))
        if n < 0:
            return None
        ndims = int(self.lib.hvd_result_dims(handle, None, 0))
        dims = (ctypes.c_longlong * max(ndims, 1))()
        if ndims > 0:
            self.lib.hvd_result_dims(handle, dims, ndims)
        buf = ctypes.create_string_buffer(max(n, 1))
        rc = int(self.lib.hvd_result_fetch(handle, buf, n))
        if rc != 1:
            return None
        return bytes(buf.raw[:n]), tuple(int(dims[i]) for i in range(ndims))

    def last_joined(self) -> int:
        return int(self.lib.hvd_last_joined())

    def set_parameters(self, cycle_time_ms: float = -1.0,
                       fusion_threshold: int = -1):
        """Autotuner hook: apply new tunables to the running world."""
        self.lib.hvd_set_parameters(cycle_time_ms, fusion_threshold)

    def set_hier_flags(self, flags: int) -> None:
        """Autotuner hook (coordinator): propose categorical
        hierarchical-dispatch flags (bit0 = allreduce, bit1 = allgather);
        they ride the next response broadcast to every rank."""
        self.lib.hvd_set_hier_flags(flags)

    def get_hier_flags(self) -> int:
        return int(self.lib.hvd_get_hier_flags())

    def get_parameters(self) -> Tuple[float, int]:
        return (float(self.lib.hvd_get_cycle_time_ms()),
                int(self.lib.hvd_get_fusion_threshold()))

    # Drain flags for ``metrics_snapshot`` (mirror of
    # hvd_metrics_snapshot's contract in csrc/hvd/operations.cc).
    METRICS_DRAIN_LIVENESS = 1
    METRICS_DRAIN_STRAGGLER = 2

    def metrics_snapshot(self, drain_flags: int = 0) -> dict:
        """THE unified native metrics read (docs/metrics.md): every
        counter and histogram as one parsed JSON document —
        ``{"counters": {...}, "histograms": {...}, "straggler": {...}}``
        (+ ``"reports"`` when a drain flag consumed one). New native
        measurements appear here; they do not grow new getters. A
        too-small buffer is retried at the size the native side reports,
        with drained reports restored in between — nothing is lost."""
        import json as _json

        cap = 1 << 16
        for _ in range(4):
            buf = ctypes.create_string_buffer(cap)
            n = int(self.lib.hvd_metrics_snapshot(buf, cap, drain_flags))
            if n >= 0:
                if n == 0:
                    return {}
                return _json.loads(buf.raw[:n].decode(errors="replace"))
            cap = -n + 1
        return {}

    def cache_hits(self) -> int:
        """Requests this rank sent as 4-byte cache ids (fast path).
        Routed through the unified snapshot — the single native
        observability path; the legacy ``hvd_cache_hits`` symbol stays
        bound (and exported) for out-of-tree callers only."""
        snap = self.metrics_snapshot()
        return int(snap.get("counters", {}).get("cache_hits", 0))

    def ring_bytes_sent(self) -> int:
        """Payload bytes this rank has sent on the host data plane (ring
        + VHDD peer links). Test hook for traffic-complexity assertions."""
        return int(self.lib.hvd_ring_bytes_sent())

    def ring_local_bytes(self) -> int:
        """Host-plane bytes this rank sent to SAME-host peers (loopback
        links of the hierarchical paths)."""
        return int(self.lib.hvd_ring_local_bytes())

    def ring_cross_bytes(self) -> int:
        """Host-plane bytes this rank sent to peers on OTHER hosts — the
        scarce cross-host budget the hierarchical paths minimize."""
        return int(self.lib.hvd_ring_cross_bytes())

    def ring_shm_bytes(self) -> int:
        """Payload bytes this rank moved over the shared-memory
        transport (the zero-socket-syscall intra-host legs,
        docs/shm-transport.md). With shm active the local TCP counter
        collapses to ~0 and this one carries the entire local leg."""
        return int(self.lib.hvd_ring_shm_bytes())

    def shm_active(self) -> bool:
        """True when this rank's shm transport is plausibly carrying
        traffic: its segment is live and not every peer attach has
        failed (the transport choice bench.py records). False with
        HOROVOD_SHM off, on init failure, in a world with no same-host
        peers, or once all attaches fell back to TCP."""
        return bool(self.lib.hvd_shm_active())

    def ring_stripe_bytes(self) -> int:
        """Payload bytes this rank moved over the striped cross-host
        transport (docs/cross-transport.md) — a subset of
        ``ring_cross_bytes``, which stays byte-identical to the
        single-socket path (stripe headers ride no counter)."""
        return int(self.lib.hvd_ring_stripe_bytes())

    def ring_cross_ns(self) -> int:
        """Wall-clock nanoseconds this rank spent inside cross-host
        leader-leg exchanges (send + receive + pipelined accumulate,
        whichever transport carried them) — the leg-local timing the
        ``--cross-leg`` A/B compares."""
        return int(self.lib.hvd_ring_cross_ns())

    def ring_stripe_count(self) -> int:
        """The stripe count in ACTIVE use: K once at least one leader
        pair carries striped traffic, 0 with striping off
        (HOROVOD_STRIPES unset/1) or once every pair fell back to
        single-socket TCP (the transport choice bench.py records)."""
        return int(self.lib.hvd_ring_stripe_count())

    def set_stripes(self, stripes: int) -> None:
        """Autotuner hook (coordinator): propose a cross-host stripe
        count; it rides the next response broadcast and every rank
        applies it at that frame boundary, so both sides of every
        leader pair renegotiate their cross transport in lock-step."""
        self.lib.hvd_set_stripes(stripes)

    def host_hier_flags(self) -> int:
        """The EFFECTIVE host-plane hierarchical dispatch (bit0 =
        allreduce, bit1 = allgather): the autotuner's synced value when
        present, else the env default — unlike ``get_hier_flags``, which
        reports only the tuned value (-1 until a tuner syncs one)."""
        return int(self.lib.hvd_host_hier_flags())

    def set_record_negotiation(self, enabled: bool) -> None:
        """Record per-rank submission ticks on the coordinator (reference
        Timeline::NegotiateRankReady, controller.cc:797-809)."""
        self.lib.hvd_set_record_negotiation(1 if enabled else 0)

    def drain_negotiation(self):
        """Drained ticks as (rank, mono_ns, tensor_name) tuples. Loops
        until the native side reports empty (it requeues whole events that
        did not fit, so partial drains never lose ticks)."""
        buf = self._neg_buf
        if buf is None:
            buf = self._neg_buf = ctypes.create_string_buffer(1 << 16)
        out = []
        while True:
            n = self.lib.hvd_drain_negotiation(buf, len(buf))
            if n <= 0:
                break
            for line in buf.raw[:n].decode(errors="replace").splitlines():
                parts = line.split(" ", 2)
                if len(parts) == 3:
                    out.append((int(parts[0]), int(parts[1]), parts[2]))
        return out

    def stall_report(self) -> str:
        """Accumulated stall-inspector warnings (coordinator); consumed on
        read. Loops until the native side drains so no tail is lost."""
        buf = ctypes.create_string_buffer(65536)
        parts = []
        while True:
            n = self.lib.hvd_stall_report(buf, len(buf))
            if n <= 0:
                break
            parts.append(buf.raw[:n].decode(errors="replace"))
            if n < len(buf) - 1:
                break
        return "".join(parts)

    def liveness_report(self) -> str:
        """Accumulated liveness events (SUSPECT/EVICT/DRAIN/RECOVER lines
        from the controller's liveness plane, docs/liveness.md); consumed
        on read. Routed through the unified snapshot's drain flag — the
        single native observability path; the snapshot's retry contract
        restores an undelivered drain, so no tail is ever lost. (The
        legacy ``hvd_liveness_report`` symbol stays bound, for
        out-of-tree callers only: a .so missing the snapshot symbol
        never binds at all.)"""
        snap = self.metrics_snapshot(self.METRICS_DRAIN_LIVENESS)
        return str(snap.get("reports", {}).get("liveness", ""))
