"""Exception types for the TPU-native runtime.

Capability parity with the reference's ``horovod/common/exceptions.py:18-31``
(``HorovodInternalError`` and ``HostsUpdatedInterrupt``), re-grounded in the
TPU failure model: XLA compilation failures, ICI collective deadlines, and
TPU-VM preemption notices all funnel into these two user-visible types so the
elastic retry loop (``horovod_tpu.common.elastic``) can distinguish
"state may be corrupt, restore" from "world changed, re-init and continue".
"""


class HorovodTpuError(Exception):
    """Base class for all framework errors."""


class HorovodInternalError(HorovodTpuError):
    """Internal error raised when a collective routine fails.

    Treated as recoverable by elastic mode: worker state is assumed corrupt
    and is restored from the last commit.
    """


class HostsUpdatedInterrupt(HorovodTpuError):
    """The set of participating hosts/slices changed (e.g. TPU-VM preemption).

    In elastic mode the current results are assumed valid; training continues
    after a re-initialization against the new world.
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class PreemptionInterrupt(HostsUpdatedInterrupt):
    """This worker's host received a preemption notice (TPU-VM
    maintenance/SIGTERM) and must leave, gracefully.

    Raised at the next ``state.commit()`` after the preemption signal
    (``elastic.state.register_preemption_signal``). Unlike its parent —
    "the world changed, re-init and keep training" — the elastic retry
    loop answers this with the drain protocol (docs/liveness.md): commit
    elastic state, send the DRAIN farewell frame, and exit cleanly so
    the driver charges the departing host zero blacklist strikes.
    """

    def __init__(self):
        # The doomed rank never syncs again; skip_sync documents that.
        super().__init__(skip_sync=True)


class NotInitializedError(HorovodTpuError):
    """An API requiring ``hvd.init()`` was called before initialization."""

    def __init__(self, name: str = ""):
        msg = (
            "horovod_tpu has not been initialized; call hvd.init() first"
            + (f" (required by {name})" if name else "")
        )
        super().__init__(msg)


class TensorShapeMismatchError(HorovodTpuError):
    """Cross-rank consistency validation failed (shape/dtype/op mismatch).

    Mirrors the reference controller's ``ConstructResponse`` error reporting
    (``controller.cc:378-611``): mismatched requests produce an error status
    delivered to every participating rank rather than a hang.
    """


class DuplicateTensorNameError(HorovodTpuError):
    """A tensor with the same name was submitted twice before completion.

    Mirrors the duplicate-name rejection of the reference tensor queue
    (``common.h:161-164``, ``tensor_queue.cc``).
    """
