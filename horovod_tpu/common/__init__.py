from . import config, exceptions, logging  # noqa: F401
from .exceptions import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
