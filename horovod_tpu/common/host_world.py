"""Process-level collective world for host (CPU) tensors.

The JAX-native API counts TPU *chips* as participants (``common/state.py``).
Framework bindings for host-resident tensors (PyTorch, TensorFlow CPU paths)
instead follow the reference's model: one *process* per rank
(``horovod/torch/mpi_ops.py``), with collectives running on the native host
data plane — the C++ ring over TCP (``csrc/hvd/ring_ops.cc``), our
TPU-native replacement for the reference's MPI/Gloo CPU ops
(``ops/mpi_operations.cc``, ``ops/gloo_operations.cc``).

A single ``NativeCore`` (one controller world) is shared with the XLA-plane
eager engine when both are active in the same process: the controller
negotiates both planes' tensors in the same cycle loop, exactly as the
reference's single background thread serves CPU and GPU entries.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Optional, Tuple

import numpy as np

from . import config as _config
from . import faults as _faults
from . import logging as _log
from . import native as _native
from .exceptions import HorovodInternalError, NotInitializedError

NUMPY_DTYPE_CODES = dict(_native.DTYPE_CODES)

# Enqueue tracing (diagnostics): read once, like the C++ side's static
# HVD_TRACE check, so the hot path tests a bool.
_TRACE = bool(os.environ.get("HVD_TRACE"))

# Scheduler-provided rank env fallbacks, tried in order when HOROVOD_* is
# absent: jsrun/Spectrum MPI (JSM/PMIX/OMPI) and Slurm. This lets jsrun-
# or srun-spawned workers join without the ssh launcher having exported the
# topology block (reference parity: under jsrun MPI supplies rank
# discovery, ``run/js_run.py``).
_SCHED_RANK = ("JSM_NAMESPACE_RANK", "PMIX_RANK", "OMPI_COMM_WORLD_RANK",
               "SLURM_PROCID")
_SCHED_SIZE = ("JSM_NAMESPACE_SIZE", "OMPI_COMM_WORLD_SIZE", "SLURM_NTASKS")
_SCHED_LOCAL_RANK = ("JSM_NAMESPACE_LOCAL_RANK",
                     "OMPI_COMM_WORLD_LOCAL_RANK", "SLURM_LOCALID")
_SCHED_LOCAL_SIZE = ("JSM_NAMESPACE_LOCAL_SIZE",
                     "OMPI_COMM_WORLD_LOCAL_SIZE", "SLURM_NTASKS_PER_NODE")


def _rejoin_grace_seconds(addr=None, port=None) -> float:
    """How long a surviving elastic worker waits for the driver to advance
    the rendezvous round before concluding the failure was transient and
    re-joining the current round. Must comfortably cover blacklist
    cooldown + plan activation. Only the driver knows its cooldown range,
    so it publishes the derived grace under ``config/rejoin_grace`` in the
    rendezvous KV and workers read it from there; the
    HOROVOD_ELASTIC_REJOIN_GRACE env knob, when set, overrides. Read per
    (re-)init, like every other runtime knob."""
    grace = _config.rejoin_grace_env()
    if grace is not None:
        return grace
    if addr and port:
        from ..run.http.http_client import read_data_from_kvstore
        try:
            blob = read_data_from_kvstore(addr, int(port), "config",
                                          "rejoin_grace", timeout=2.0,
                                          retries=1)
            if blob:
                return float(blob.decode())
        except (OSError, ValueError):
            pass
    return 10.0


def _excluded_from_plan_error() -> "HorovodInternalError":
    return HorovodInternalError(
        "this worker is no longer in the rendezvous plan (slot removed or "
        "host blacklisted)")


def _sched_env(primary: str, fallbacks, default: str) -> str:
    v = os.environ.get(primary)
    if v is not None:
        return v
    for name in fallbacks:
        v = os.environ.get(name)
        if v is not None:
            # Slurm compound counts look like "16(x2)"; the leading int is
            # the per-node value.
            return v.split("(")[0]
    return default


class HostWorld:
    """Process-rank collective world over the native host data plane."""

    def __init__(self):
        self._lock = threading.Lock()
        self.initialized = False
        self.rank = 0
        self.size = 1
        self.local_rank = 0
        self.local_size = 1
        self.cross_rank = 0
        self.cross_size = 1
        self._core: Optional[_native.NativeCore] = None
        self._owns_core = False
        self._staging = None  # host_staging.HostStagingExecutor when active
        # True when this rank is a local leader on a hierarchical
        # multi-host world — the rank whose background thread carries the
        # cross-host leg of the two-level collectives. Gates the
        # ring.hier.cross fault point (chaos-testing leader death).
        self._hier_cross_seam = False
        # True when the shm transport is armed for this world
        # (HOROVOD_SHM on, same-host peers exist). Gates the
        # ring.shm.exec fault point (docs/shm-transport.md).
        self._shm_seam = False
        # True when the striped cross-host transport is armed
        # (HOROVOD_STRIPES > 1, cross-host leader pairs exist). Gates
        # the ring.stripe.exec fault point (docs/cross-transport.md).
        self._stripe_seam = False
        # (addr, port) fetched from the elastic rendezvous KV this round;
        # overrides the launch-time HOROVOD_CONTROLLER_ADDR/PORT env, which
        # goes stale once rank 0 migrates to a different host.
        self._elastic_controller: Optional[Tuple[str, int]] = None
        # The rendezvous round this process last joined. Survives shutdown
        # (reinit = shutdown + init must not forget it): a surviving worker
        # re-initializing after a collective failure has to wait for the
        # driver's *next* round — re-joining its own old round would pair
        # it against a plan the failure already invalidated.
        self._last_rendezvous_round: Optional[int] = None

    # -- lifecycle -----------------------------------------------------------

    def init(self, comm=None):
        with self._lock:
            if self.initialized:
                return
            self.rank = int(_sched_env(_config.HOROVOD_RANK, _SCHED_RANK,
                                       "0"))
            self.size = int(_sched_env(_config.HOROVOD_SIZE, _SCHED_SIZE,
                                       "1"))
            self.local_rank = int(
                _sched_env(_config.HOROVOD_LOCAL_RANK, _SCHED_LOCAL_RANK,
                           "0"))
            self.local_size = int(
                _sched_env(_config.HOROVOD_LOCAL_SIZE, _SCHED_LOCAL_SIZE,
                           "1"))
            # Cross (node-level) topology: explicit env from the ssh
            # launcher wins; under scheduler launches derive it from the
            # per-node packing (homogeneous layout, the same assumption the
            # reference's rankfile makes).
            ls = max(1, self.local_size)
            self.cross_rank = _config.cross_rank(self.rank // ls)
            self.cross_size = _config.cross_size(
                max(1, (self.size + ls - 1) // ls))
            self._maybe_elastic_rerendezvous()
            if comm is not None:
                # Parity with hvd.init(comm=[ranks]) (basics.py:33-65):
                # restrict to a subset of the launched world.
                if self.rank not in comm:
                    raise ValueError(
                        f"process rank {self.rank} not in comm {comm}")
                self.size = len(comm)
                self.rank = sorted(comm).index(self.rank)

            # The forced-failure hooks are scoped to ONE world: clear any
            # previous world's arming so an exhausted step-targeted
            # ring.shm.attach / ring.stripe.connect spec doesn't keep a
            # re-initialized (elastic-recovered) world degraded forever.
            os.environ.pop("HVD_SHM_FORCE_ATTACH_FAIL", None)
            os.environ.pop("HVD_STRIPE_FORCE_CONNECT_FAIL", None)
            if _config.shm_enabled() and self.size > 1 and \
                    self.local_size > 1:
                try:
                    _faults.point("ring.shm.attach", rank=self.rank)
                except _faults.FaultInjected as e:
                    # An absorbed raise (see also ring.stripe.connect):
                    # a raise here SIMULATES an shm attach failure —
                    # this rank's native attaches are forced to fail,
                    # so the registered TCP backend carries its local
                    # legs, byte-identically (docs/shm-transport.md).
                    # The FALLBACK is the path under test;
                    # kind=exit/delay keep their usual semantics.
                    os.environ["HVD_SHM_FORCE_ATTACH_FAIL"] = "1"
                    from . import metrics as _metrics

                    _metrics.inc("shm.attach_fallback")
                    _log.warning(
                        f"ring.shm.attach fault armed: forcing shm "
                        f"attach failure; TCP carries the local legs "
                        f"({e})")
            if _config.stripes() > 1 and self.size > 1 and \
                    self.cross_size > 1:
                try:
                    _faults.point("ring.stripe.connect", rank=self.rank)
                except _faults.FaultInjected as e:
                    # The stripe sibling of ring.shm.attach's absorbed
                    # raise: force THIS rank's native stripe dials to
                    # fail, so the cross legs negotiate down to
                    # single-socket TCP in lock-step, byte-identically
                    # (docs/cross-transport.md). Under strict mode
                    # (HOROVOD_STRIPE_FALLBACK=0) the failed dial is a
                    # hard collective error instead.
                    os.environ["HVD_STRIPE_FORCE_CONNECT_FAIL"] = "1"
                    from . import metrics as _metrics

                    _metrics.inc("stripe.connect_fallback")
                    _log.warning(
                        f"ring.stripe.connect fault armed: forcing "
                        f"stripe connect failure; single-socket TCP "
                        f"carries the cross legs ({e})")
            core = self._borrow_engine_core()
            if core is not None:
                self._core, self._owns_core = core, False
            elif self.size > 1:
                self._core = self._init_own_core()
                if self._core is None:
                    raise HorovodInternalError(
                        "multi-process host world requires the native "
                        "runtime (libhvdtpu.so); build failed or "
                        "HOROVOD_NATIVE=0")
                self._owns_core = True
            else:
                # size-1 world: every collective is an identity op locally;
                # no controller or ring needed.
                self._core = None
            self._staging = None
            cfg = _config.RuntimeConfig.from_env()
            self._hier_cross_seam = (
                self.size > 1 and self.cross_size > 1
                and self.local_rank == 0
                and (cfg.hierarchical_allreduce or
                     cfg.hierarchical_allgather))
            self._shm_seam = (_config.shm_enabled() and self.size > 1
                              and self.local_size > 1)
            self._stripe_seam = (_config.stripes() > 1 and self.size > 1
                                 and self.cross_size > 1)
            if self._core is not None:
                from . import host_staging

                # Opt-in fast fabric for large host tensors
                # (HOROVOD_HOST_VIA_XLA=1): fused allreduces above the
                # threshold stage through the XLA plane instead of the
                # TCP ring. Called on every multi-process world — ranks
                # without the knob (or with a borrowed engine core) vote
                # "no" in the unanimity agreement rather than skipping
                # it, so per-host env drift degrades to the ring instead
                # of deadlocking the voters.
                self._staging = host_staging.maybe_activate(
                    self, self._core, owns_exec_slot=self._owns_core)
            self.initialized = True

    def _maybe_elastic_rerendezvous(self):
        """Elastic mode: the launcher's env block is only the *initial*
        world; after membership changes the elastic driver publishes a new
        slot plan in the rendezvous KV, so every (re-)init fetches this
        worker's current rank layout from there (the reference workers do
        the same against the elastic rendezvous handler,
        ``run/elastic/rendezvous.py:22-45``)."""
        self._elastic_controller = None
        if not _config.elastic_enabled():
            return
        addr = _config.rendezvous_addr()
        port = _config.rendezvous_port()
        hostname = _config.hostname()
        if not (addr and port and hostname):
            return
        from ..run.elastic.rendezvous import fetch_slot_info

        # A surviving worker re-initializing after a failure *prefers* a
        # newer round: worker-death failures make the driver rebuild the
        # plan (blacklist cooldown + activation, typically a few seconds),
        # and re-joining the invalidated round would deadlock against the
        # replacement worker holding the new one. But the preference is a
        # bounded grace, not a hard wait — a *transient* collective failure
        # (no process died, plan unchanged) advances nothing, and everyone
        # simply re-joins the current round.
        # First init never waits on the round loop, so only re-inits pay
        # the KV read for the driver-published grace.
        if self._last_rendezvous_round is None:
            try:
                fetched = fetch_slot_info(addr, int(port), hostname,
                                          self.local_rank, rank=self.rank)
            # hvdlint: ignore[exception-discipline] -- first init only:
            # the launch-time env block is still authoritative, so an
            # unreachable rendezvous degrades to it (re-inits DO raise)
            except Exception as e:
                _log.warning(f"elastic rendezvous unreachable at first "
                             f"init; using env topology: {e}")
                return
            if fetched is None:
                return  # first init: launch-time env is authoritative
        else:
            last = self._last_rendezvous_round
            latest = [None]

            def fetch_newer():
                try:
                    got = fetch_slot_info(addr, int(port), hostname,
                                          self.local_rank, rank=self.rank)
                except Exception as e:
                    # Re-init: the env endpoint may point at a deposed
                    # rank 0 — falling back to it silently would be a
                    # blind 120 s connect; surface the failure to the
                    # elastic retry loop instead.
                    raise HorovodInternalError(
                        f"elastic re-rendezvous failed: {e}") from e
                if got is None:
                    # The current plan excludes us (host blacklisted /
                    # slot removed). Proceeding on stale env topology
                    # would join the new round with an old rank and could
                    # overwrite a legitimate worker's slot in the
                    # coordinator's tables.
                    raise _excluded_from_plan_error()
                latest[0] = got
                return got if got[1] > last else None

            # max_attempts/deadline are pinned: unlimited polling for the
            # whole grace IS the rejoin contract (the grace has its own
            # knob, HOROVOD_ELASTIC_REJOIN_GRACE) — a global
            # HOROVOD_RETRY_MAX_ATTEMPTS must not truncate it into a
            # stale-round rejoin mid plan-rebuild.
            retrier = _faults.retrier(
                "REJOIN", name="elastic.rejoin", rank=self.rank,
                pinned=("max_attempts", "deadline"),
                max_attempts=0, base_delay=0.25, max_delay=1.0,
                deadline=max(_rejoin_grace_seconds(addr, port), 0.001))
            try:
                fetched = retrier.poll(fetch_newer)
            except _faults.RetryExhausted:
                # Grace expired with the round unchanged: the failure was
                # transient and everyone re-joins the current round.
                fetched = latest[0]
        info, rendezvous_round = fetched
        (self.rank, self.size, self.local_rank, self.local_size,
         self.cross_rank, self.cross_size) = info
        self._last_rendezvous_round = rendezvous_round
        self._exchange_controller_endpoint(addr, int(port), hostname,
                                           rendezvous_round)
        # The notification service must exist before training starts so
        # the driver can reach us on the next membership change.
        from ..run.elastic.worker import notification_manager

        notification_manager.init()

    def _exchange_controller_endpoint(self, addr: str, port: int,
                                      hostname: str, rendezvous_round: int):
        """Rank 0 publishes its controller endpoint in the rendezvous KV;
        everyone else polls for it. The launch-time env endpoint points at
        the *initial* rank-0 host (the launcher's guess); after host churn
        moves rank 0, only the KV knows the live coordinator. Keys are
        scoped by rendezvous round so layout and coordinator can't pair
        across rounds. Failure raises ``HorovodInternalError`` — the
        elastic retry loop re-rendezvouses; silently falling back to the
        known-stale env endpoint would trade a clear error for a blind
        120 s connect to a host that may no longer be rank 0."""
        from ..run.elastic.rendezvous import publish_controller_endpoint

        ctrl_port = _config.native_controller_port()
        try:
            if self.rank == 0:
                publish_controller_endpoint(addr, port, hostname, ctrl_port,
                                            rendezvous_round)
                # Rank 0 only listens; the addr field is unused by it.
                self._elastic_controller = ("0.0.0.0", ctrl_port)
                return
            ep = self._poll_controller_endpoint(addr, port, hostname,
                                                rendezvous_round)
        except HorovodInternalError:
            raise
        except Exception as e:
            raise HorovodInternalError(
                f"elastic controller rendezvous failed: {e}") from e
        self._elastic_controller = ep

    def _poll_controller_endpoint(self, addr: str, port: int, hostname: str,
                                  rendezvous_round: int) -> Tuple[str, int]:
        """Wait for this round's controller endpoint, watching for the
        round moving on underneath us: if the driver supersedes the round
        we fetched (another failure, more churn) while we wait, raise
        immediately so the elastic retry loop re-rendezvouses against the
        live round instead of burning the full timeout on a coordinator
        that will never publish. Schedule + 120 s default deadline come
        from the shared Retrier under the ``RENDEZVOUS`` scope."""
        from ..run.elastic.rendezvous import (
            fetch_controller_endpoint, fetch_slot_info)

        def fetch_once():
            ep = fetch_controller_endpoint(addr, port, rendezvous_round,
                                           timeout=2.0, rank=self.rank)
            if ep is not None:
                return ep
            current = fetch_slot_info(addr, port, hostname,
                                      self.local_rank, rank=self.rank)
            if current is None:
                raise _excluded_from_plan_error()
            if current[1] != rendezvous_round:
                raise HorovodInternalError(
                    f"rendezvous advanced to round {current[1]} while "
                    f"waiting for round {rendezvous_round}'s controller")
            return None

        # Unlimited attempts within the deadline IS the wait contract;
        # only the deadline and the poll cadence are tuning knobs.
        retrier = _faults.retrier(
            "RENDEZVOUS", name="controller.endpoint", rank=self.rank,
            pinned=("max_attempts",),
            max_attempts=0, base_delay=0.25, max_delay=2.0, deadline=120.0)
        try:
            return retrier.poll(fetch_once)
        except _faults.RetryExhausted:
            raise HorovodInternalError(
                f"controller endpoint for rendezvous round "
                f"{rendezvous_round} never appeared in the KV (rank 0 "
                f"crashed before publishing?)") from None

    @staticmethod
    def _borrow_engine_core():
        from . import state as _state

        st = _state.global_state()
        if st.initialized and st.engine is not None and \
                getattr(st.engine, "_native", False):
            return st.engine._core
        return None

    def _try_init_core(self, core) -> bool:
        cfg = _config.RuntimeConfig.from_env()
        if self._elastic_controller is not None:
            addr, ctrl_port = self._elastic_controller
        else:
            addr = _config.controller_addr()
            ctrl_port = _config.native_controller_port()
        # The ssh launcher exports a per-slot HOROVOD_HOSTNAME; scheduler
        # launchers (jsrun/srun) give every rank the same env, so fall back
        # to the actual hostname — advertising 127.0.0.1 would point peers'
        # ring connections at the wrong machine on multi-host worlds.
        my_host = _config.hostname()
        if not my_host:
            my_host = socket.gethostname() if self.size > 1 else "127.0.0.1"

        def reject_xla(responses, rid):
            core.response_done(rid, False,
                               "no XLA executor in host-only world")

        return core.init(
            rank=self.rank, size=self.size, local_rank=self.local_rank,
            local_size=self.local_size, cross_rank=self.cross_rank,
            cross_size=self.cross_size, coordinator_addr=addr,
            coordinator_port=ctrl_port, my_host=my_host,
            cycle_time_ms=cfg.cycle_time_ms,
            fusion_threshold=cfg.fusion_threshold_bytes,
            cache_capacity=cfg.cache_capacity,
            stall_warning_sec=cfg.stall_warning_seconds,
            stall_shutdown_sec=cfg.stall_shutdown_seconds,
            stall_check_enabled=not cfg.stall_check_disable,
            exec_callback=reject_xla,
            heartbeat_ms=_config.heartbeat_ms(),
            liveness_timeout_ms=_config.liveness_timeout_ms())

    def _init_own_core(self):
        core = _native.NativeCore()
        if not core.available:
            return None
        if not self._try_init_core(core):
            # Distinct from "library missing": the world join itself failed
            # (coordinator unreachable, hello timeout, job-key mismatch) —
            # report that, and as HorovodInternalError so the elastic retry
            # loop treats it as a recoverable rendezvous failure.
            raise HorovodInternalError(
                f"native controller world join failed (rank {self.rank} of "
                f"{self.size}): coordinator unreachable or worker-connect "
                f"timeout")
        return core

    def drain(self):
        """Graceful-drain farewell (docs/liveness.md): mark this rank's
        departure as a clean DRAIN on the native controller, then shut
        the world down. The coordinator's liveness stream records DRAIN
        for this rank — the launcher charges zero blacklist strikes —
        while survivors recover through the normal elastic retry path.
        A no-op beyond shutdown when the native plane is absent."""
        with self._lock:
            if self.initialized and self._core is not None and \
                    self._owns_core:
                self._core.drain()
        self.shutdown()

    def shutdown(self):
        with self._lock:
            if not self.initialized:
                return
            if self._core is not None and self._owns_core:
                if self._staging is not None:
                    self._core.set_host_via_xla(-1)
                    self._staging.close()
                self._core.shutdown()
            self._core = None
            self._staging = None
            self._elastic_controller = None
            self._hier_cross_seam = False
            self._shm_seam = False
            self._stripe_seam = False
            self.initialized = False
            self.rank, self.size = 0, 1
            self.local_rank, self.local_size = 0, 1
            self.cross_rank, self.cross_size = 0, 1

    def require_init(self):
        if not self.initialized:
            raise NotInitializedError("host collective API")

    @property
    def native(self) -> bool:
        return self._core is not None

    # -- raw buffer collectives ---------------------------------------------

    def enqueue(self, name: str, op: int, reduce_op: int, dtype_code: int,
                shape: Tuple[int, ...], data_ptr: int, output_ptr: int,
                root_rank: int = -1, prescale: float = 1.0,
                postscale: float = 1.0) -> int:
        self.require_init()
        _faults.point("host_world.enqueue", rank=self.rank)
        if self._core is None:
            raise HorovodInternalError(
                "native host plane unavailable in this process")
        if _TRACE:
            import sys as _sys
            import traceback as _tb
            caller = "|".join(
                f"{f.name}:{f.lineno}" for f in _tb.extract_stack()[-5:-1])
            print(f"[pytrace rank={self.rank} size={self.size}] "
                  f"enqueue {name} <- {caller}", file=_sys.stderr, flush=True)
        return self._core.enqueue(
            name, op, reduce_op, dtype_code, shape, data_ptr=data_ptr,
            output_ptr=output_ptr, root_rank=root_rank, prescale=prescale,
            postscale=postscale, plane=_native.PLANE_HOST)

    def test(self, handle: int) -> Tuple[int, str]:
        core = self._core
        if core is None:
            raise HorovodInternalError(
                "native host plane unavailable (shut down?)")
        return core.test(handle)

    def wait(self, handle: int) -> Tuple[int, str]:
        core = self._core
        if core is None:
            raise HorovodInternalError(
                "native host plane unavailable (shut down?)")
        # The blocking seam of a ring collective: a kind=exit fault here
        # kills the worker mid-step, after its tensor was submitted —
        # the canonical chaos-test death (docs/fault-injection.md).
        _faults.point("ring.exec", rank=self.rank)
        if self._hier_cross_seam:
            # Local leader of a hierarchical world: this process's
            # background thread carries the cross-host leg, so a fault
            # here is "the leader died mid cross-exchange" — the
            # highest-blast-radius death the two-level path adds.
            _faults.point("ring.hier.cross", rank=self.rank)
        if self._shm_seam:
            # Shm-transport world: a kill/delay/raise here lands while
            # bytes may be mid-flight in the shm rings — the shm analog
            # of ring.exec (docs/shm-transport.md).
            _faults.point("ring.shm.exec", rank=self.rank)
        if self._stripe_seam:
            # Striped cross-transport world: a kill/delay/raise here
            # lands while chunks may be mid-flight across the stripe
            # sockets — the stripe analog of ring.exec
            # (docs/cross-transport.md).
            _faults.point("ring.stripe.exec", rank=self.rank)
        return core.wait(handle)

    # -- small helper collectives (numpy, blocking) --------------------------

    def allgather_np(self, arr: np.ndarray, name: str) -> np.ndarray:
        """Blocking equal-shape allgather of a small numpy array."""
        self.require_init()
        if self.size == 1:
            return arr.copy()
        arr = np.asarray(arr, order="C")
        out = np.zeros((self.size,) + arr.shape, dtype=arr.dtype)
        code = NUMPY_DTYPE_CODES[str(arr.dtype)]
        h = self.enqueue(name, _native.OP_ALLGATHER, 1, code, arr.shape,
                         arr.ctypes.data, out.ctypes.data)
        r, err = self.wait(h)
        if r < 0:
            raise HorovodInternalError(err)
        return out

    def result_fetch(self, handle: int):
        """Fetch an executor-allocated result (see NativeCore.result_fetch)."""
        core = self._core
        if core is None:
            raise HorovodInternalError(
                "native host plane unavailable (shut down?)")
        return core.result_fetch(handle)

    def allgatherv_np(self, arr: np.ndarray, name: str):
        """Ragged allgather (MPI_Allgatherv semantics, reference
        ``ops/mpi_operations.cc:140-175``): per-rank dim-0 sizes may
        differ. Returns (concatenated array, per-rank sizes). The native
        executor allocates the output once the response's per-rank dims
        arrive — no size pre-exchange, no padding."""
        self.require_init()
        arr = np.ascontiguousarray(arr)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        if self.size == 1 or self._core is None:
            return arr.copy(), np.asarray([arr.shape[0]], np.int64)
        code = NUMPY_DTYPE_CODES[str(arr.dtype)]
        h = self.enqueue(name, _native.OP_ALLGATHER, 1, code, arr.shape,
                         arr.ctypes.data, 0)
        r, err = self.wait(h)
        if r < 0:
            raise HorovodInternalError(err)
        fetched = self._core.result_fetch(h)
        if fetched is None:
            raise HorovodInternalError(
                f"allgather result missing for '{name}'")
        raw, dims = fetched
        out = np.frombuffer(bytearray(raw), dtype=arr.dtype).reshape(
            (int(sum(dims)),) + arr.shape[1:])
        return out, np.asarray(dims, np.int64)

    def broadcast_np(self, arr: np.ndarray, root_rank: int,
                     name: str) -> np.ndarray:
        self.require_init()
        if self.size == 1:
            return arr.copy()
        arr = np.asarray(arr, order="C")
        out = arr.copy()
        code = NUMPY_DTYPE_CODES[str(arr.dtype)]
        h = self.enqueue(name, _native.OP_BROADCAST, 1, code, arr.shape,
                         arr.ctypes.data, out.ctypes.data,
                         root_rank=root_rank)
        r, err = self.wait(h)
        if r < 0:
            raise HorovodInternalError(err)
        return out

    def join(self) -> int:
        """Graceful departure (reference hvd.join, operations.cc:937-961):
        this process stops submitting and contributes zeros to the others'
        reductions until every process joins. Returns the last joined
        rank."""
        self.require_init()
        if self.size == 1 or self._core is None:
            return self.size - 1
        h = self._core.join()
        if h < 0:
            raise HorovodInternalError("join enqueue failed")
        r, err = self._core.wait(h)
        if r < 0:
            raise HorovodInternalError(err)
        return self._core.last_joined()

    def barrier(self, name: str = "host.barrier"):
        self.require_init()
        if self.size == 1 or self._core is None:
            return
        z = np.zeros(1, np.uint8)
        h = self.enqueue(name, _native.OP_BARRIER, 1, 0, z.shape,
                         z.ctypes.data, z.ctypes.data)
        r, err = self.wait(h)
        if r < 0:
            raise HorovodInternalError(err)


_world = HostWorld()


def world() -> HostWorld:
    return _world
