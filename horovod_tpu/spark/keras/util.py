"""Keras (de)serialization helpers (parity: ``horovod/spark/keras/util.py``
+ ``serialization.py``): models and optimizers move driver→worker as bytes.
"""

from __future__ import annotations

import io
import os
import tempfile


def serialize_model(model) -> bytes:
    """Keras 3 native .keras archive as bytes."""
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.keras")
        model.save(path)
        with open(path, "rb") as f:
            return f.read()


def deserialize_model(blob: bytes, custom_objects=None):
    import keras

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.keras")
        with open(path, "wb") as f:
            f.write(blob)
        return keras.models.load_model(
            path, custom_objects=custom_objects, compile=True)


def serialize_optimizer(optimizer) -> bytes:
    import json

    import keras

    cfg = keras.optimizers.serialize(optimizer)
    return json.dumps(cfg).encode()


def deserialize_optimizer(blob: bytes):
    import json

    import keras

    return keras.optimizers.deserialize(json.loads(blob.decode()))
