"""Per-worker Keras training function for the Keras Estimator (parity:
``horovod/spark/keras/remote.py`` ``RemoteTrainer``).

The reference builds a Petastorm reader over the store's Parquet shards and
trains with hvd callbacks; here the reader is the pyarrow row-group shard
reader and the collective plumbing is ``horovod_tpu.keras``.
"""

from __future__ import annotations

import os
from typing import Dict


def make_remote_trainer(serialized_model: bytes, optimizer_bytes,
                        loss, metrics, batch_size: int, epochs: int,
                        meta: Dict, checkpoint_path: str,
                        custom_objects=None, verbose: int = 0,
                        shuffle_buffer_size: int = 0,
                        train_steps_per_epoch=None,
                        validation_steps_per_epoch=None,
                        callbacks=None):
    """Build the function executed on every worker."""

    def trainer():
        import numpy as np

        import horovod_tpu.keras as hvd
        from ..common.util import read_shard, to_arrays
        from .util import deserialize_model

        hvd.init()
        try:
            model = deserialize_model(serialized_model,
                                      custom_objects=custom_objects)
            opt = model.optimizer
            if optimizer_bytes is not None:
                from .util import deserialize_optimizer
                opt = deserialize_optimizer(optimizer_bytes)
            plain_opt = opt  # kept for the wrapper-free checkpoint below
            opt = hvd.DistributedOptimizer(opt)
            model.compile(optimizer=opt, loss=loss, metrics=metrics or None)

            pdf = read_shard(meta["train_data_path"], hvd.rank(), hvd.size())
            if shuffle_buffer_size:
                pdf = pdf.sample(frac=1.0, random_state=hvd.rank())
            xs = to_arrays(pdf, meta["feature_cols"], meta)
            ys = to_arrays(pdf, meta["label_cols"], meta)
            x = xs[0] if len(xs) == 1 else xs
            y = ys[0] if len(ys) == 1 else ys

            val = None
            if meta.get("val_data_path"):
                vdf = read_shard(meta["val_data_path"], hvd.rank(),
                                 hvd.size())
                if len(vdf):
                    vx = to_arrays(vdf, meta["feature_cols"], meta)
                    vy = to_arrays(vdf, meta["label_cols"], meta)
                    val = (vx[0] if len(vx) == 1 else vx,
                           vy[0] if len(vy) == 1 else vy)

            cbs = [hvd.callbacks.BroadcastGlobalVariablesCallback(0),
                   hvd.callbacks.MetricAverageCallback()]
            cbs.extend(callbacks or [])

            history = model.fit(
                x, y, batch_size=batch_size, epochs=epochs,
                validation_data=val, verbose=verbose, callbacks=cbs,
                steps_per_epoch=train_steps_per_epoch,
                validation_steps=validation_steps_per_epoch)

            result = {"history": {k: [float(v) for v in vs]
                                  for k, vs in history.history.items()}}
            if hvd.rank() == 0:
                os.makedirs(os.path.dirname(checkpoint_path), exist_ok=True)
                # Strip the dynamic Distributed* wrapper before saving so
                # the archive deserializes anywhere (the reference's
                # serialization.py plays the same role).
                model.compile(
                    optimizer=type(plain_opt).from_config(opt.get_config()),
                    loss=loss, metrics=metrics or None)
                model.save(checkpoint_path)
                result["checkpoint"] = checkpoint_path
            return result
        finally:
            hvd.shutdown()

    return trainer
