"""Per-worker Keras training function for the Keras Estimator (parity:
``horovod/spark/keras/remote.py`` ``RemoteTrainer``).

The reference builds a Petastorm reader over the store's Parquet shards and
trains with hvd callbacks; here the reader is the pyarrow row-group shard
reader and the collective plumbing is ``horovod_tpu.keras``.
"""

from __future__ import annotations

import os
from typing import Dict


def make_remote_trainer(serialized_model: bytes, optimizer_bytes,
                        loss, metrics, batch_size: int, epochs: int,
                        meta: Dict, checkpoint_path: str,
                        custom_objects=None, verbose: int = 0,
                        shuffle_buffer_size: int = 0,
                        train_steps_per_epoch=None,
                        validation_steps_per_epoch=None,
                        callbacks=None, loss_weights=None,
                        sample_weight_col=None, transformation_fn=None,
                        gradient_compression=None,
                        train_reader_num_workers=None):
    """Build the function executed on every worker."""

    def trainer():
        import horovod_tpu.keras as hvd
        from ..common.reader import ShardReader
        from .util import deserialize_model

        hvd.init()
        try:
            model = deserialize_model(serialized_model,
                                      custom_objects=custom_objects)
            opt = model.optimizer
            if optimizer_bytes is not None:
                from .util import deserialize_optimizer
                opt = deserialize_optimizer(optimizer_bytes)
            plain_opt = opt  # kept for the wrapper-free checkpoint below
            opt = hvd.DistributedOptimizer(
                opt, compression=(gradient_compression
                                  or hvd.Compression.none))
            model.compile(optimizer=opt, loss=loss,
                          loss_weights=loss_weights,
                          metrics=metrics or None)

            # Streaming shard reader (the reference streams through
            # Petastorm make_keras_dataset; bounded memory per worker).
            reader = ShardReader(
                meta["train_data_path"], meta, hvd.rank(), hvd.size(),
                batch_size=batch_size, shuffle=bool(shuffle_buffer_size),
                transform_fn=transformation_fn,
                sample_weight_col=sample_weight_col,
                num_workers=train_reader_num_workers or 0)
            if reader.rows == 0:
                # Fail loudly (the launcher aborts the job) rather than
                # spin in fit() waiting for batches that never come.
                raise ValueError(
                    f"rank {hvd.rank()}'s training shard is empty: the "
                    "dataset has fewer row groups than workers; increase "
                    "num_partitions (or reduce the world size)")

            def unwrap(cols):
                return cols[0] if len(cols) == 1 else tuple(cols)

            def gen():
                epoch = 0
                while True:  # keras pulls steps_per_epoch * epochs batches
                    for batch in reader.batches(epoch):
                        if sample_weight_col:
                            xs, ys, ws = batch
                            yield unwrap(xs), unwrap(ys), ws[0]
                        else:
                            xs, ys = batch
                            yield unwrap(xs), unwrap(ys)
                    epoch += 1

            # Validation is evaluated whole (fit holds it in memory
            # anyway), so the simple whole-shard read serves it; only the
            # training pass streams.
            val = None
            from ..common.util import read_val_arrays

            # Same transform as the training stream — val metrics on
            # untransformed data would be garbage (shared helper with
            # the torch remote).
            arrays = read_val_arrays(meta, hvd.rank(), hvd.size(),
                                     transformation_fn)
            if arrays is not None:
                val = (unwrap(arrays[0]), unwrap(arrays[1]))

            cbs = [hvd.callbacks.BroadcastGlobalVariablesCallback(0),
                   hvd.callbacks.MetricAverageCallback()]
            cbs.extend(callbacks or [])

            history = model.fit(
                gen(), epochs=epochs,
                steps_per_epoch=(train_steps_per_epoch
                                 or reader.steps_per_epoch()),
                validation_data=val, verbose=verbose, callbacks=cbs,
                validation_steps=validation_steps_per_epoch)

            result = {"history": {k: [float(v) for v in vs]
                                  for k, vs in history.history.items()}}
            if hvd.rank() == 0:
                os.makedirs(os.path.dirname(checkpoint_path), exist_ok=True)
                # Strip the dynamic Distributed* wrapper before saving so
                # the archive deserializes anywhere (the reference's
                # serialization.py plays the same role).
                model.compile(
                    optimizer=type(plain_opt).from_config(opt.get_config()),
                    loss=loss, loss_weights=loss_weights,
                    metrics=metrics or None)
                model.save(checkpoint_path)
                result["checkpoint"] = checkpoint_path
            return result
        finally:
            hvd.shutdown()

    return trainer
