"""Keras Spark ML Estimator (parity: ``horovod/spark/keras/estimator.py:103``
KerasEstimator / ``:375`` KerasModel).

``fit`` materializes the DataFrame to the Store as Parquet, runs the remote
training function on the backend (in-process local SPMD by default,
``horovod_tpu.spark.run`` when pyspark is present), and returns a
``KerasModel`` that serves batch inference via ``transform``.
"""

from __future__ import annotations

from typing import Optional

from ..common.backend import Backend
from ..common.estimator import (HorovodEstimator, HorovodModel,
                                install_accessors)
from ..common.store import Store
from ..common.util import to_arrays
from .remote import make_remote_trainer
from .util import deserialize_model, serialize_model, serialize_optimizer


class KerasEstimator(HorovodEstimator):
    """Train a Keras model over Store-backed Parquet data.

    Mirrors the reference's param surface (``keras/estimator.py:103-170``):
    model, optimizer, loss, loss_weights, metrics, gradient_compression,
    custom_objects, feature_cols, label_cols, sample_weight_col,
    batch_size, epochs, validation, callbacks, transformation_fn, store,
    num_proc, verbose, shuffle_buffer_size, train/validation steps,
    run_id — each with the Spark-ML camelCase accessor pair
    (``setEpochs``/``getEpochs``, ...).
    """

    # Framework-specific params (reference keras/estimator.py:159).
    _EXTRA_PARAM_DEFS = {
        "custom_objects": ("CustomObjects", None),
    }

    def __init__(self, model=None, optimizer=None, loss=None, metrics=None,
                 feature_cols=None, label_cols=None, batch_size: int = 32,
                 epochs: int = 1, validation=None, callbacks=None,
                 store: Optional[Store] = None, num_proc: Optional[int] = None,
                 backend: Optional[Backend] = None, custom_objects=None,
                 verbose: int = 0, shuffle_buffer_size: int = 0,
                 train_steps_per_epoch=None, validation_steps_per_epoch=None,
                 run_id: Optional[str] = None, **kwargs):
        super().__init__(model=model, loss=loss, metrics=metrics,
                         feature_cols=feature_cols, label_cols=label_cols,
                         batch_size=batch_size, epochs=epochs,
                         validation=validation, callbacks=callbacks,
                         store=store, num_proc=num_proc,
                         optimizer=optimizer, backend=backend,
                         custom_objects=custom_objects,
                         verbose=verbose,
                         shuffle_buffer_size=shuffle_buffer_size,
                         train_steps_per_epoch=train_steps_per_epoch,
                         validation_steps_per_epoch=validation_steps_per_epoch,
                         run_id=run_id, **kwargs)
        self._backend = backend

    _checkpoint_filename = "model.keras"

    def _make_trainer(self, meta, checkpoint_path):
        model = self.getOrDefault("model")
        # Compile driver-side so loss/metrics serialize with the archive.
        opt = (self.getOrDefault("optimizer")
               or getattr(model, "optimizer", None))
        if opt is None:
            raise ValueError("optimizer is required (pass optimizer= or a "
                             "compiled model)")
        model.compile(optimizer=opt, loss=self.getOrDefault("loss"),
                      loss_weights=self.getOrDefault("loss_weights"),
                      metrics=self.getOrDefault("metrics") or None)
        return make_remote_trainer(
            serialize_model(model), serialize_optimizer(opt),
            self.getOrDefault("loss"), self.getOrDefault("metrics"),
            self.getOrDefault("batch_size"), self.getOrDefault("epochs"),
            meta, checkpoint_path,
            custom_objects=self.getOrDefault("custom_objects"),
            verbose=self.getOrDefault("verbose"),
            shuffle_buffer_size=self.getOrDefault("shuffle_buffer_size"),
            train_steps_per_epoch=self.getOrDefault("train_steps_per_epoch"),
            validation_steps_per_epoch=self.getOrDefault(
                "validation_steps_per_epoch"),
            callbacks=self.getOrDefault("callbacks"),
            loss_weights=self.getOrDefault("loss_weights"),
            sample_weight_col=self.getOrDefault("sample_weight_col"),
            transformation_fn=self.getOrDefault("transformation_fn"),
            gradient_compression=self.getOrDefault("gradient_compression"),
            train_reader_num_workers=self.getOrDefault(
                "train_reader_num_workers"))

    def _load_model(self, store, checkpoint_path):
        return deserialize_model(
            store.read(checkpoint_path),
            custom_objects=self.getOrDefault("custom_objects"))

    def _make_model(self, trained, history, run_id, meta) -> "KerasModel":
        return KerasModel(model=trained,
                          feature_cols=self.getOrDefault("feature_cols"),
                          label_cols=self.getOrDefault("label_cols"),
                          run_id=run_id, history=history, _metadata=meta)


install_accessors(KerasEstimator)


class KerasModel(HorovodModel):
    """Trained-model wrapper (parity: ``keras/estimator.py:375``)."""

    def __init__(self, model=None, feature_cols=None, label_cols=None,
                 run_id=None, history=None, _metadata=None):
        super().__init__(model, feature_cols, label_cols, run_id)
        self.history = history
        self._metadata = _metadata

    def transform(self, df):
        """Append ``<label>__output`` prediction columns. Accepts a pandas
        DataFrame (Spark DataFrames convert via ``toPandas`` upstream)."""
        import numpy as np

        from ..common.util import _to_pandas

        pdf = _to_pandas(df).copy()
        meta = self._metadata or {
            "columns": {c: {"shape": [], "dtype": "float32", "size": 1}
                        for c in self.feature_cols}}
        xs = to_arrays(pdf, self.feature_cols, meta)
        preds = self.model.predict(xs[0] if len(xs) == 1 else xs, verbose=0)
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        for col, p in zip(self.label_cols, preds):
            p = np.asarray(p)
            pdf[f"{col}__output"] = (
                list(p) if p.ndim > 1 and p.shape[-1] > 1 else p.reshape(-1))
        return pdf
