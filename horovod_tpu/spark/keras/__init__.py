"""Keras Spark Estimator package (parity: ``horovod/spark/keras/``)."""

from .estimator import KerasEstimator, KerasModel  # noqa: F401
