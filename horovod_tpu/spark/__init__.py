"""Spark integration (parity: ``horovod/spark/``).

``horovod_tpu.spark.run(fn, ...)`` runs ``fn`` once per Spark executor with
the full collective world initialized, mirroring ``horovod.spark.run``
(``spark/runner.py:131``): the driver parallelizes one task per executor,
tasks register their host with the launcher's driver service, and workers
are launched across those hosts with the standard topology env. Estimators
(``keras_estimator``/``torch_estimator``) wrap training as Spark ML stages
backed by a ``Store`` (``spark/common/store.py``).

PySpark is not part of the TPU image; every entry point gates on its
availability with a clear error, while the Store layer (plain filesystem)
works standalone.
"""

from __future__ import annotations

from typing import Optional

from .common.store import HDFSStore, LocalStore, Store  # noqa: F401


def __getattr__(name):
    # Lazy so importing horovod_tpu.spark never drags in keras/torch.
    if name in ("KerasEstimator", "KerasModel"):
        from .keras import KerasEstimator, KerasModel

        return {"KerasEstimator": KerasEstimator,
                "KerasModel": KerasModel}[name]
    if name in ("TorchEstimator", "TorchModel"):
        from .torch import TorchEstimator, TorchModel

        return {"TorchEstimator": TorchEstimator,
                "TorchModel": TorchModel}[name]
    raise AttributeError(name)


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark, which is not installed in "
            "this environment. Use horovod_tpu.run (the horovodrun-"
            "equivalent launcher) for non-Spark clusters.") from e


def run(fn, args=(), kwargs=None, num_proc: Optional[int] = None,
        start_timeout: Optional[int] = None, env=None,
        stdout=None, stderr=None, verbose: int = 1,
        nics=None, prefix_output_with_timestamp: bool = False,
        use_ssh: bool = False):
    """Run ``fn`` on ``num_proc`` Spark executors (parity:
    ``spark/runner.py:131``).

    Default transport is **in-executor** (reference semantics,
    ``spark/runner.py:40-262``): one long-lived Spark task per rank
    starts an authenticated task service, the driver sends the pickled
    fn over it, and fn runs as a subprocess of the executor — its Python
    env, cwd, and resource limits — with no inter-host ssh anywhere.
    fn's output streams into the executor's logs (where Spark surfaces
    worker output); the ``stdout``/``stderr`` capture params apply only
    to the ssh path. fn runs unbounded — ``start_timeout`` covers
    registration, not training.

    ``use_ssh=True`` keeps the previous behavior (collect executor
    hostnames, relaunch over ssh from the driver); it requires the
    driver to have passwordless ssh to every executor host, which many
    Spark clusters do not allow — the error you get without it is an
    ssh/launch timeout, not a Spark failure.
    """
    _require_pyspark()
    import pyspark

    sc = pyspark.SparkContext._active_spark_context
    if sc is None:
        raise ValueError("run() requires an active SparkContext")
    if num_proc is None:
        num_proc = sc.defaultParallelism

    if use_ssh:
        from ..run import run as _local_run
        import socket

        hosts = sc.parallelize(range(num_proc), num_proc) \
            .map(lambda _: socket.gethostname()).collect()
        counts = {}
        for h in hosts:
            counts[h] = counts.get(h, 0) + 1
        hosts_str = ",".join(f"{h}:{n}" for h, n in sorted(counts.items()))
        return _local_run(fn, args=args, kwargs=kwargs, np=num_proc,
                          hosts=hosts_str, env=env, verbose=bool(verbose))

    import threading

    from ..run.common.util import secret
    from .exec import (SparkDriverService, run_via_task_services,
                       shutdown_registered_tasks, task_main)

    key = secret.make_secret_key()
    driver = SparkDriverService(num_proc, key, nics=nics)
    driver_addresses = driver.addresses()
    timeout = float(start_timeout or 120)

    def _spark_task(index, _iterator):
        # No lifetime cap: training runs unbounded, and every driver exit
        # path (success, failure, probe error) sends ShutdownRequest.
        yield task_main(index, driver_addresses, key, nics=nics)

    collect_result = {}

    def _collect():
        try:
            collect_result["states"] = sc.parallelize(
                range(num_proc), num_proc) \
                .mapPartitionsWithIndex(_spark_task).collect()
        except Exception as e:  # surfaced after the exec round
            collect_result["error"] = e

    spark_thread = threading.Thread(target=_collect, daemon=True)
    spark_thread.start()
    try:
        driver.wait_for_initial_registration(timeout)
        results = run_via_task_services(
            driver, fn, args, kwargs, num_proc, key, env=env)
    except Exception:
        # Exit paths that never reach run_via_task_services (registration
        # timeout with a partial world) still owe ShutdownRequest to the
        # tasks that DID register — without it they serve forever and leak
        # their executor slots. Idempotent on the paths that already shut
        # down inside run_via_task_services.
        shutdown_registered_tasks(driver, num_proc, key)
        raise
    finally:
        spark_thread.join(timeout=30)
        driver.shutdown()
    if "error" in collect_result:
        raise collect_result["error"]
    return results
