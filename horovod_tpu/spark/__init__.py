"""Spark integration (parity: ``horovod/spark/``).

``horovod_tpu.spark.run(fn, ...)`` runs ``fn`` once per Spark executor with
the full collective world initialized, mirroring ``horovod.spark.run``
(``spark/runner.py:131``): the driver parallelizes one task per executor,
tasks register their host with the launcher's driver service, and workers
are launched across those hosts with the standard topology env. Estimators
(``keras_estimator``/``torch_estimator``) wrap training as Spark ML stages
backed by a ``Store`` (``spark/common/store.py``).

PySpark is not part of the TPU image; every entry point gates on its
availability with a clear error, while the Store layer (plain filesystem)
works standalone.
"""

from __future__ import annotations

from typing import Optional

from .common.store import HDFSStore, LocalStore, Store  # noqa: F401


def __getattr__(name):
    # Lazy so importing horovod_tpu.spark never drags in keras/torch.
    if name in ("KerasEstimator", "KerasModel"):
        from .keras import KerasEstimator, KerasModel

        return {"KerasEstimator": KerasEstimator,
                "KerasModel": KerasModel}[name]
    if name in ("TorchEstimator", "TorchModel"):
        from .torch import TorchEstimator, TorchModel

        return {"TorchEstimator": TorchEstimator,
                "TorchModel": TorchModel}[name]
    raise AttributeError(name)


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark, which is not installed in "
            "this environment. Use horovod_tpu.run (the horovodrun-"
            "equivalent launcher) for non-Spark clusters.") from e


def run(fn, args=(), kwargs=None, num_proc: Optional[int] = None,
        start_timeout: Optional[int] = None, env=None,
        stdout=None, stderr=None, verbose: int = 1,
        nics=None, prefix_output_with_timestamp: bool = False):
    """Run ``fn`` on ``num_proc`` Spark executors (parity:
    ``spark/runner.py:131``). Each task initializes the collective world
    before calling ``fn`` and returns its result to the driver."""
    _require_pyspark()
    import pyspark

    from ..run import run as _local_run

    sc = pyspark.SparkContext._active_spark_context
    if sc is None:
        raise ValueError("run() requires an active SparkContext")
    if num_proc is None:
        num_proc = sc.defaultParallelism

    # One task per executor: each discovers its hostname; the driver then
    # launches the collective job across those hosts through the standard
    # launcher path (the reference piggybacks mpirun_rsh over Spark RPC,
    # spark/mpi_run.py; on TPU pods ssh/local exec is the transport).
    import socket

    hosts = sc.parallelize(range(num_proc), num_proc) \
        .map(lambda _: socket.gethostname()).collect()
    counts = {}
    for h in hosts:
        counts[h] = counts.get(h, 0) + 1
    hosts_str = ",".join(f"{h}:{n}" for h, n in sorted(counts.items()))
    return _local_run(fn, args=args, kwargs=kwargs, np=num_proc,
                      hosts=hosts_str, env=env, verbose=bool(verbose))
