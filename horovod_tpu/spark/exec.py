"""In-executor execution over the HMAC task services (parity:
``horovod/spark/runner.py:40-262`` + ``spark/driver/mpirun_rsh.py`` +
``spark/task/mpirun_exec_fn.py``).

The reference runs the user fn *inside* the Spark executors: each task
starts a service, registers with the driver, and the launcher reaches the
executors through Spark's own connectivity (mpirun rsh piggybacked on the
task services) — no inter-host ssh, and fn sees the executor's exact
Python env, working directory, and resource cgroup. This module is the
TPU-native equivalent on this repo's authenticated pickle-over-TCP
services (``run/driver/driver_service.py``, ``run/common/util/network.py``):

driver                                  executor (one task per rank)
------                                  ----------------------------
SparkDriverService                       SparkTaskService starts
  <- RegisterTaskRequest(index, addrs, hostname)
probe routable addrs                     ...
  -> FreePortRequest (task 0)            picks the controller base port
  -> ExecuteRequest(env, payload)        subprocess runs fn (task_exec)
  -> ResultRequest (poll)                state: running -> done/failed
  -> ShutdownRequest                     service exits, Spark task returns

Everything here is pyspark-independent so the full path is testable with
a plain process pool standing in for the executors.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..run.common.util import network
from ..run.common.util.hosts import SlotInfo
from ..run.driver.driver_service import (
    HorovodRunDriverService, RegisterTaskRequest, probe_routable_addresses)
from ..run.launch import slot_env

try:  # cloudpickle handles closures/lambdas; plain pickle is the last
    import cloudpickle as _pickle  # noqa: F401
except ImportError:
    try:
        from pyspark import cloudpickle as _pickle  # noqa: F401
    except ImportError:  # module-level fns only
        import pickle as _pickle


# -- protocol ----------------------------------------------------------------


class RegisterTaskHostnameRequest:
    def __init__(self, index: int, hostname: str):
        self.index = index
        self.hostname = hostname


class FreePortRequest:
    pass


class FreePortResponse:
    def __init__(self, base_port: int):
        self.base_port = base_port


class ExecuteRequest:
    def __init__(self, env: Dict[str, str], payload: bytes):
        self.env = env          # HOROVOD_* topology block
        self.payload = payload  # pickled (fn, args, kwargs)


class ResultRequest:
    pass


class ResultResponse:
    def __init__(self, state: str, result: Optional[bytes], error: str):
        self.state = state      # idle | running | done | failed
        self.result = result
        self.error = error


class ShutdownRequest:
    pass


# -- driver side -------------------------------------------------------------


class SparkDriverService(HorovodRunDriverService):
    """Driver service that also records each task's hostname (needed for
    LOCAL/CROSS topology when several executors share a host)."""

    NAME = "horovod spark driver service"

    def __init__(self, num_tasks: int, key: bytes, nics=None):
        super().__init__(num_tasks, key, nics)
        self.hostnames: Dict[int, str] = {}

    def _handle(self, req, client_address):
        if isinstance(req, RegisterTaskHostnameRequest):
            self.hostnames[req.index] = req.hostname
            return network.AckResponse()
        return super()._handle(req, client_address)


# -- task (executor) side ----------------------------------------------------


class SparkTaskService(network.BasicService):
    """Runs inside one Spark executor; executes fn as a subprocess of the
    executor (the reference's mpirun_exec_fn role) so fn inherits the
    executor's env/cwd/container."""

    NAME_FMT = "horovod spark task service #%d"

    def __init__(self, index: int, key: bytes, nics=None):
        super().__init__(self.NAME_FMT % index, key, nics)
        self.index = index
        self._state = "idle"
        self._result: Optional[bytes] = None
        self._error = ""
        self._shutdown_ev = threading.Event()

    def _handle(self, req, client_address):
        if isinstance(req, FreePortRequest):
            return FreePortResponse(_free_port_pair())
        if isinstance(req, ExecuteRequest):
            if self._state == "running":
                return ResultResponse("running", None,
                                      "already executing")
            self._state = "running"
            threading.Thread(target=self._exec, args=(req,),
                             daemon=True).start()
            return network.AckResponse()
        if isinstance(req, ResultRequest):
            return ResultResponse(self._state, self._result, self._error)
        if isinstance(req, ShutdownRequest):
            self._shutdown_ev.set()
            return network.AckResponse()
        return super()._handle(req, client_address)

    def _exec(self, req: ExecuteRequest):
        try:
            with tempfile.NamedTemporaryFile(
                    suffix=".hvdtask", delete=False) as f:
                f.write(req.payload)
                payload_path = f.name
            env = dict(os.environ)
            env.update(req.env)
            # fn's output streams into the executor's own stdout/stderr —
            # Spark surfaces those as the executor logs, exactly where the
            # reference's in-executor fn logs land. Only a bounded stderr
            # tail is retained (for the driver's error report); capturing
            # the full output in memory would grow unbounded over a
            # multi-hour fn.
            import collections

            tail: "collections.deque" = collections.deque(maxlen=20)
            proc = subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.spark.task_exec",
                 payload_path],
                env=env, stdout=None, stderr=subprocess.PIPE, text=True)
            for line in proc.stderr:
                sys.stderr.write(line)
                tail.append(line.rstrip("\n"))
            rc = proc.wait()
            out_path = payload_path + ".out"
            if rc == 0 and os.path.exists(out_path):
                with open(out_path, "rb") as f:
                    self._result = f.read()
                self._state = "done"
            else:
                self._error = (f"task fn exited rc={rc}: " +
                               "\n".join(tail))
                self._state = "failed"
            for p in (payload_path, out_path):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        except Exception as e:
            self._error = str(e)
            self._state = "failed"

    def wait_for_shutdown(self, timeout: Optional[float] = None) -> bool:
        return self._shutdown_ev.wait(timeout)


def task_main(index: int, driver_addresses: List[Tuple[str, int]],
              key: bytes, timeout: Optional[float] = None, nics=None):
    """The body of one Spark task: start the service, register, serve
    until the driver says shutdown (or ``timeout`` — pass the driver's
    full registration+exec budget; the service MUST outlive the exec
    round or the driver's result polls hit a closed socket mid-train).
    Returns the task's final state."""
    service = SparkTaskService(index, key, nics)
    try:
        client = network.BasicClient(SparkDriverService.NAME,
                                     driver_addresses, key)
        client._request(RegisterTaskRequest(index, service.addresses()))
        client._request(
            RegisterTaskHostnameRequest(index, socket.gethostname()))
        service.wait_for_shutdown(timeout)
        return service._state
    finally:
        service.shutdown()


# -- orchestration (driver) --------------------------------------------------


def run_via_task_services(driver: SparkDriverService, fn, args, kwargs,
                          num_proc: int, key: bytes,
                          exec_timeout: Optional[float] = None,
                          env: Optional[Dict[str, str]] = None
                          ) -> List[Any]:
    """The full register -> exec -> collect round. ``driver`` must already
    have every task registered (``wait_for_initial_registration``).
    ``exec_timeout=None`` (default) lets fn run unbounded — training jobs
    routinely exceed any fixed cap; the old ssh path had none either.
    Every exit (success, failure, probe error, timeout) shuts the task
    services down so executors never idle out their full lifetime."""
    # Probe every task's advertised addresses concurrently: each dead
    # address costs a full connect timeout, and serial probing would add
    # O(num_proc x dead_addrs x timeout) to every launch.
    routable: Dict[int, List[Tuple[str, int]]] = {}
    errors: Dict[int, str] = {}

    def _probe(i):
        addrs = driver.task_addresses_for_driver(i)
        if not addrs:
            errors[i] = f"task {i} never registered"
            return
        ok = probe_routable_addresses(
            addrs, SparkTaskService.NAME_FMT % i, key)
        if not ok:
            errors[i] = (f"task {i} registered but none of its addresses "
                         f"{addrs} are routable from the driver")
            return
        routable[i] = ok

    probers = [threading.Thread(target=_probe, args=(i,), daemon=True)
               for i in range(num_proc)]
    for t in probers:
        t.start()
    for t in probers:
        t.join()
    if errors:
        _best_effort_shutdown(routable, key)
        raise RuntimeError("; ".join(errors[i] for i in sorted(errors)))

    clients = {
        i: network.BasicClient(SparkTaskService.NAME_FMT % i, routable[i],
                               key)
        for i in range(num_proc)
    }

    def _shutdown_all():
        for i in range(num_proc):
            try:
                clients[i]._request(ShutdownRequest())
            except (ConnectionError, OSError):
                pass

    try:
        return _exec_round(driver, clients, routable, fn, args, kwargs,
                           num_proc, exec_timeout, env)
    finally:
        # Idempotent: tasks treat shutdown-after-shutdown as a no-op.
        _shutdown_all()


def _best_effort_shutdown(routable, key):
    for i, addrs in routable.items():
        try:
            network.BasicClient(SparkTaskService.NAME_FMT % i, addrs,
                                key)._request(ShutdownRequest())
        except Exception:
            # Best-effort means best-effort: a task mid-teardown can
            # reply with a truncated/garbage frame (UnpicklingError,
            # EOFError — not just socket errors), and one bad reply must
            # not leak the remaining tasks or mask the caller's original
            # exception.
            pass


def shutdown_registered_tasks(driver, num_proc: int, key: bytes) -> None:
    """Best-effort ShutdownRequest to every task that has registered
    addresses with ``driver``. Driver exit paths that never reach
    ``run_via_task_services`` (e.g. a registration timeout with a partial
    world) call this so the tasks that DID register don't serve forever:
    ``task_main`` waits on ``wait_for_shutdown(None)``, and a leaked Spark
    task holds its executor slot for the application's lifetime."""
    registered = {}
    for i in range(num_proc):
        addrs = driver.task_addresses_for_driver(i)
        if addrs:
            registered[i] = addrs
    _best_effort_shutdown(registered, key)


def _exec_round(driver, clients, routable, fn, args, kwargs, num_proc,
                exec_timeout, env):
    # Topology: tasks grouped by executor hostname, ranks in task order
    # (the reference's get_host_assignments over executor hosts). The
    # hostname arrives in a second registration request, so wait for all
    # of them — fabricating placeholders would silently wreck
    # local/cross ranks for late registrants.
    deadline = time.monotonic() + 30
    while len(driver.hostnames) < num_proc:
        if time.monotonic() > deadline:
            missing = sorted(set(range(num_proc)) - set(driver.hostnames))
            raise RuntimeError(
                f"tasks {missing} registered addresses but never their "
                f"hostname")
        # hvdlint: ignore[retry-discipline] -- fixed-cadence status poll
        # against Spark's own task API (its scheduler owns the pacing);
        # backoff would only slow registration detection
        time.sleep(0.05)
    hostnames = {i: driver.hostnames[i] for i in range(num_proc)}
    by_host: Dict[str, List[int]] = {}
    for i in range(num_proc):
        by_host.setdefault(hostnames[i], []).append(i)
    cross_size = len(by_host)
    cross_of = {h: c for c, h in enumerate(sorted(by_host))}

    # Rank 0's executor picks the controller base port (it must be free
    # *there*, not on the driver).
    base_port = clients[0]._request(FreePortRequest()).base_port
    # Controller address: other EXECUTORS must reach it, so loopback (a
    # driver co-located with task 0 probes its own 127.0.0.1 as routable)
    # only qualifies when the whole world shares one host.
    non_loop = [a for a, _ in routable[0] if a != "127.0.0.1"]
    if non_loop:
        controller_addr = non_loop[0]
    elif len(set(hostnames.values())) <= 1:
        controller_addr = routable[0][0][0]
    else:
        raise RuntimeError(
            f"task 0 on {hostnames[0]} advertised no non-loopback "
            f"address reachable from the driver, but the job spans "
            f"{len(set(hostnames.values()))} hosts — other executors "
            f"cannot reach its controller")

    payload = _pickle.dumps((fn, tuple(args), dict(kwargs or {})))
    for i in range(num_proc):
        h = hostnames[i]
        slot = SlotInfo(
            hostname=h, rank=i, local_rank=by_host[h].index(i),
            cross_rank=cross_of[h], size=num_proc,
            local_size=len(by_host[h]), cross_size=cross_size)
        block = slot_env(slot, controller_addr, base_port,
                         controller_addr, base_port, base_env={})
        if env:
            block.update(env)
        clients[i]._request(ExecuteRequest(block, payload))

    deadline = (time.monotonic() + exec_timeout
                if exec_timeout is not None else None)
    results: Dict[int, Any] = {}
    failed: Dict[int, str] = {}

    while len(results) < num_proc:
        for i in range(num_proc):
            if i in results or i in failed:
                continue
            r = clients[i]._request(ResultRequest())
            if r.state == "done":
                results[i] = _pickle.loads(r.result)
            elif r.state == "failed":
                failed[i] = r.error
        if failed:
            # Fail fast: peers are likely blocked in hvd.init waiting for
            # the dead rank; waiting out any timeout would bury the root
            # cause (the caller's finally shuts every task down).
            raise RuntimeError(
                "spark tasks failed: " +
                "; ".join(f"rank {i}: {e}"
                          for i, e in sorted(failed.items())))
        if len(results) < num_proc and deadline is not None and \
                time.monotonic() > deadline:
            raise TimeoutError(
                f"spark tasks still running after {exec_timeout}s "
                f"(ranks {sorted(set(range(num_proc)) - set(results))})")
        # hvdlint: ignore[retry-discipline] -- fixed-cadence result poll
        # against Spark's own task API; the deadline above bounds it
        time.sleep(0.5)

    return [results[i] for i in range(num_proc)]


def _free_port_pair() -> int:
    """A base port such that base AND base+1 are free (gRPC coordination
    takes base, the native controller base+1 — config.py convention)."""
    for _ in range(64):
        s1 = socket.socket()
        s1.bind(("0.0.0.0", 0))
        base = s1.getsockname()[1]
        s2 = socket.socket()
        try:
            s2.bind(("0.0.0.0", base + 1))
            return base
        except OSError:
            continue
        finally:
            s2.close()
            s1.close()
    raise RuntimeError("no free port pair found")
