"""Per-worker PyTorch training function for the Torch Estimator (parity:
``horovod/spark/torch/remote.py``).

Reads this rank's Parquet shard, wraps the optimizer in
``horovod_tpu.torch.DistributedOptimizer``, broadcasts initial state from
rank 0, and checkpoints on rank 0 — the reference's remote loop minus
Petastorm (pyarrow row-group sharding plays that role).
"""

from __future__ import annotations

import io
import os
from typing import Dict


def make_remote_trainer(model_bytes: bytes, optimizer_cls, optimizer_kwargs,
                        loss_fns, batch_size: int, epochs: int, meta: Dict,
                        checkpoint_path: str, verbose: int = 0,
                        shuffle: bool = True, train_minibatch_fn=None,
                        sample_weight_col=None):
    def trainer():
        import numpy as np
        import torch

        import horovod_tpu.torch as hvd
        from ..common.reader import ShardReader

        hvd.init()
        try:
            model = torch.load(io.BytesIO(model_bytes), weights_only=False)
            optimizer = optimizer_cls(model.parameters(), **optimizer_kwargs)
            hvd.broadcast_parameters(model.state_dict(), root_rank=0)
            hvd.broadcast_optimizer_state(optimizer, root_rank=0)
            optimizer = hvd.DistributedOptimizer(
                optimizer, named_parameters=model.named_parameters())

            # Streaming shard reader (the Petastorm role in the reference's
            # remote trainer): one row-group window resident at a time.
            reader = ShardReader(
                meta["train_data_path"], meta, hvd.rank(), hvd.size(),
                batch_size=batch_size, shuffle=shuffle)
            if reader.rows == 0:
                # Fail loudly: a zero-step rank would skip the per-step
                # gradient allreduces the data-holding ranks submit and
                # deadlock the negotiation.
                raise ValueError(
                    f"rank {hvd.rank()}'s training shard is empty: the "
                    "dataset has fewer row groups than workers; increase "
                    "num_partitions (or reduce the world size)")

            history = []
            model.train()
            for epoch in range(epochs):
                total, steps = 0.0, 0
                for xs, ys in reader.batches(epoch):
                    bx = [torch.as_tensor(np.asarray(a, np.float32))
                          for a in xs]
                    by = [torch.as_tensor(np.asarray(a)) for a in ys]
                    optimizer.zero_grad()
                    if train_minibatch_fn is not None:
                        loss = train_minibatch_fn(model, optimizer, bx, by)
                    else:
                        out = model(*bx)
                        outs = out if isinstance(out, (list, tuple)) else [out]
                        losses = [fn(o, y) for fn, o, y
                                  in zip(loss_fns, outs, by)]
                        loss = sum(losses)
                        loss.backward()
                        optimizer.step()
                    total += float(loss.detach())
                    steps += 1
                avg = hvd.allreduce(
                    torch.tensor(total / max(1, steps)),
                    name=f"epoch_loss.{epoch}", op=hvd.Average)
                history.append(float(avg))
                if verbose and hvd.rank() == 0:
                    print(f"epoch {epoch}: loss={float(avg):.5f}")

            result = {"history": {"loss": history}}
            if hvd.rank() == 0:
                os.makedirs(os.path.dirname(checkpoint_path), exist_ok=True)
                torch.save(model, checkpoint_path)
                result["checkpoint"] = checkpoint_path
            return result
        finally:
            hvd.shutdown()

    return trainer
