"""Per-worker PyTorch training function for the Torch Estimator (parity:
``horovod/spark/torch/remote.py``).

Reads this rank's Parquet shard, wraps the optimizer in
``horovod_tpu.torch.DistributedOptimizer``, broadcasts initial state from
rank 0, and checkpoints on rank 0 — the reference's remote loop minus
Petastorm (pyarrow row-group sharding plays that role).
"""

from __future__ import annotations

import io
import os
from typing import Dict


def make_remote_trainer(model_bytes: bytes, optimizer_cls, optimizer_kwargs,
                        loss_fns, batch_size: int, epochs: int, meta: Dict,
                        checkpoint_path: str, verbose: int = 0,
                        shuffle: bool = True, train_minibatch_fn=None,
                        sample_weight_col=None, transformation_fn=None,
                        gradient_compression=None, input_shapes=None,
                        train_reader_num_workers=None):
    def trainer():
        import numpy as np
        import torch

        import horovod_tpu.torch as hvd
        from ..common.reader import ShardReader

        hvd.init()
        try:
            model = torch.load(io.BytesIO(model_bytes), weights_only=False)
            optimizer = optimizer_cls(model.parameters(), **optimizer_kwargs)
            hvd.broadcast_parameters(model.state_dict(), root_rank=0)
            hvd.broadcast_optimizer_state(optimizer, root_rank=0)
            optimizer = hvd.DistributedOptimizer(
                optimizer, named_parameters=model.named_parameters(),
                compression=(gradient_compression
                             or hvd.Compression.none))

            # Streaming shard reader (the Petastorm role in the reference's
            # remote trainer): one row-group window resident at a time.
            reader = ShardReader(
                meta["train_data_path"], meta, hvd.rank(), hvd.size(),
                batch_size=batch_size, shuffle=shuffle,
                transform_fn=transformation_fn,
                sample_weight_col=sample_weight_col,
                num_workers=train_reader_num_workers or 0)
            if reader.rows == 0:
                # Fail loudly: a zero-step rank would skip the per-step
                # gradient allreduces the data-holding ranks submit and
                # deadlock the negotiation.
                raise ValueError(
                    f"rank {hvd.rank()}'s training shard is empty: the "
                    "dataset has fewer row groups than workers; increase "
                    "num_partitions (or reduce the world size)")

            # Validation shard, read whole (evaluation only; reference
            # torch/remote.py evaluates the val split every epoch).
            # Participation is decided by meta['val_data_path'] — the
            # SAME on every rank — so the per-epoch val collectives
            # cannot diverge even when some ranks' shards are empty
            # (those contribute 0 rows to the weighted mean).
            has_val = bool(meta.get("val_data_path"))
            val = None
            if has_val:
                from ..common.util import read_val_arrays

                arrays = read_val_arrays(meta, hvd.rank(), hvd.size(),
                                         transformation_fn)
                if arrays is not None:
                    vx = [torch.as_tensor(np.asarray(a, np.float32))
                          for a in arrays[0]]
                    if input_shapes:
                        vx = [t.reshape(tuple(s))
                              for t, s in zip(vx, input_shapes)]
                    vy = [torch.as_tensor(np.asarray(a))
                          for a in arrays[1]]
                    val = (vx, vy)

            def evaluate_val():
                """(loss_sum_weighted, rows) for the row-weighted global
                mean; empty local shards contribute (0, 0). Evaluation
                is mini-batched so a large validation shard never needs
                whole-shard activations in memory at once."""
                if val is None:
                    return 0.0, 0.0
                model.eval()
                total, rows = 0.0, 0
                n = len(val[1][0])
                with torch.no_grad():
                    for s in range(0, n, batch_size):
                        bx = [t[s:s + batch_size] for t in val[0]]
                        by = [t[s:s + batch_size] for t in val[1]]
                        out = model(*bx)
                        outs = (out if isinstance(out, (list, tuple))
                                else [out])
                        b = len(by[0])
                        total += b * sum(float(fn(o, y)) for fn, o, y
                                         in zip(loss_fns, outs, by))
                        rows += b
                model.train()
                return total, float(rows)

            history = []
            val_history = []
            model.train()
            for epoch in range(epochs):
                total, steps = 0.0, 0
                for batch in reader.batches(epoch):
                    xs, ys = batch[0], batch[1]
                    ws = batch[2][0] if sample_weight_col else None
                    bx = [torch.as_tensor(np.asarray(a, np.float32))
                          for a in xs]
                    if input_shapes:
                        # Reference convention: shapes include the -1
                        # batch dim (e.g. [[-1, 1, 28, 28]]).
                        bx = [t.reshape(tuple(s))
                              for t, s in zip(bx, input_shapes)]
                    by = [torch.as_tensor(np.asarray(a)) for a in ys]
                    optimizer.zero_grad()
                    if train_minibatch_fn is not None:
                        loss = train_minibatch_fn(model, optimizer, bx, by)
                    else:
                        out = model(*bx)
                        outs = out if isinstance(out, (list, tuple)) else [out]
                        if ws is not None:
                            # Per-ROW weighting (reference
                            # torch/remote.py calculate_loss): the loss
                            # fn must accept reduction='none' (functional
                            # losses do); each sample's loss scales by
                            # its weight, then batch-mean.
                            wt = torch.as_tensor(np.asarray(ws, np.float32))
                            try:
                                losses = [
                                    (fn(o, y, reduction="none").flatten()
                                     * wt).mean()
                                    for fn, o, y in zip(loss_fns, outs, by)]
                            except TypeError as e:
                                raise TypeError(
                                    "sample_weight_col requires loss "
                                    "functions accepting "
                                    "reduction='none' (use functional "
                                    "losses like torch.nn.functional."
                                    "mse_loss)") from e
                        else:
                            losses = [fn(o, y) for fn, o, y
                                      in zip(loss_fns, outs, by)]
                        loss = sum(losses)
                        loss.backward()
                        optimizer.step()
                    total += float(loss.detach())
                    steps += 1
                avg = hvd.allreduce(
                    torch.tensor(total / max(1, steps)),
                    name=f"epoch_loss.{epoch}", op=hvd.Average)
                history.append(float(avg))
                if has_val:
                    lw, rows = evaluate_val()
                    sums = hvd.allreduce(
                        torch.tensor([lw, rows]),
                        name=f"epoch_val_loss.{epoch}", op=hvd.Sum)
                    val_history.append(
                        float(sums[0]) / max(1.0, float(sums[1])))
                if verbose and hvd.rank() == 0:
                    tail = (f" val_loss={val_history[-1]:.5f}"
                            if val_history else "")
                    print(f"epoch {epoch}: loss={float(avg):.5f}{tail}")

            result = {"history": {"loss": history}}
            if val_history:
                result["history"]["val_loss"] = val_history
            if hvd.rank() == 0:
                os.makedirs(os.path.dirname(checkpoint_path), exist_ok=True)
                torch.save(model, checkpoint_path)
                result["checkpoint"] = checkpoint_path
            return result
        finally:
            hvd.shutdown()

    return trainer
