"""PyTorch Spark Estimator package (parity: ``horovod/spark/torch/``)."""

from .estimator import TorchEstimator, TorchModel  # noqa: F401
