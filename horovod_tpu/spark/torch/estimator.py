"""PyTorch Spark ML Estimator (parity: ``horovod/spark/torch/estimator.py:84``
TorchEstimator / ``:301`` TorchModel)."""

from __future__ import annotations

import io
from typing import Optional

from ..common.backend import Backend
from ..common.estimator import (HorovodEstimator, HorovodModel,
                                install_accessors)
from ..common.store import Store
from ..common.util import to_arrays
from .remote import make_remote_trainer


class TorchEstimator(HorovodEstimator):
    """Train a torch ``nn.Module`` over Store-backed Parquet data.

    Param surface mirrors ``torch/estimator.py:139-187``: model, optimizer
    (class + kwargs or an instance whose defaults are recovered), loss (one
    fn per label col), loss_constructors, input_shapes, feature_cols,
    label_cols, sample_weight_col, gradient_compression, batch_size,
    epochs, validation, transformation_fn, store, num_proc,
    train_minibatch_fn — each with the Spark-ML camelCase accessor pair.
    """

    # Framework-specific params (reference torch/estimator.py:139-143).
    _EXTRA_PARAM_DEFS = {
        "input_shapes": ("InputShapes", None),
        "train_minibatch_fn": ("TrainMinibatchFn", None),
    }

    def __init__(self, model=None, optimizer=None, loss=None,
                 loss_constructors=None, feature_cols=None, label_cols=None,
                 input_shapes=None, batch_size: int = 32, epochs: int = 1,
                 validation=None, store: Optional[Store] = None,
                 num_proc: Optional[int] = None,
                 backend: Optional[Backend] = None, verbose: int = 0,
                 shuffle_buffer_size: int = 0, train_minibatch_fn=None,
                 sample_weight_col=None, run_id: Optional[str] = None,
                 **kwargs):
        super().__init__(model=model, loss=loss,
                         loss_constructors=loss_constructors,
                         feature_cols=feature_cols, label_cols=label_cols,
                         batch_size=batch_size, epochs=epochs,
                         validation=validation, store=store,
                         num_proc=num_proc, verbose=verbose,
                         optimizer=optimizer, backend=backend,
                         input_shapes=input_shapes,
                         train_minibatch_fn=train_minibatch_fn,
                         shuffle_buffer_size=shuffle_buffer_size,
                         sample_weight_col=sample_weight_col,
                         run_id=run_id, **kwargs)
        self._backend = backend

    def _optimizer_spec(self):
        """(class, kwargs) for rebuilding the optimizer against the
        deserialized model's parameters on each worker (the reference
        re-instantiates from ``optimizer.state_dict`` the same way)."""
        import torch

        opt = self.getOrDefault("optimizer")
        if isinstance(opt, torch.optim.Optimizer):
            kwargs = {k: v for k, v in opt.defaults.items()}
            return type(opt), kwargs
        if isinstance(opt, tuple) and len(opt) == 2:
            return opt
        raise ValueError(
            "optimizer must be a torch.optim.Optimizer instance or a "
            "(class, kwargs) tuple")

    _checkpoint_filename = "model.pt"

    def _make_trainer(self, meta, checkpoint_path):
        import torch

        loss = self.getOrDefault("loss")
        loss_fns = loss if isinstance(loss, (list, tuple)) else [loss]
        if self.getOrDefault("loss_constructors"):
            loss_fns = [c() for c in self.getOrDefault("loss_constructors")]

        buf = io.BytesIO()
        torch.save(self.getOrDefault("model"), buf)
        opt_cls, opt_kwargs = self._optimizer_spec()
        return make_remote_trainer(
            buf.getvalue(), opt_cls, opt_kwargs, loss_fns,
            self.getOrDefault("batch_size"), self.getOrDefault("epochs"),
            meta, checkpoint_path, verbose=self.getOrDefault("verbose"),
            train_minibatch_fn=self.getOrDefault("train_minibatch_fn"),
            sample_weight_col=self.getOrDefault("sample_weight_col"),
            transformation_fn=self.getOrDefault("transformation_fn"),
            gradient_compression=self.getOrDefault("gradient_compression"),
            input_shapes=self.getOrDefault("input_shapes"),
            train_reader_num_workers=self.getOrDefault(
                "train_reader_num_workers"))

    def _load_model(self, store, checkpoint_path):
        import torch

        return torch.load(io.BytesIO(store.read(checkpoint_path)),
                          weights_only=False)

    def _make_model(self, trained, history, run_id, meta) -> "TorchModel":
        return TorchModel(model=trained,
                          feature_cols=self.getOrDefault("feature_cols"),
                          label_cols=self.getOrDefault("label_cols"),
                          run_id=run_id, history=history, _metadata=meta,
                          input_shapes=self.getOrDefault("input_shapes"))


install_accessors(TorchEstimator)


class TorchModel(HorovodModel):
    """Trained-model wrapper (parity: ``torch/estimator.py:301``)."""

    def __init__(self, model=None, feature_cols=None, label_cols=None,
                 run_id=None, history=None, _metadata=None,
                 input_shapes=None):
        super().__init__(model, feature_cols, label_cols, run_id)
        self.history = history
        self._metadata = _metadata
        self.input_shapes = input_shapes

    def transform(self, df):
        """Append ``<label>__output`` prediction columns (pandas in/out)."""
        import numpy as np
        import torch

        from ..common.util import _to_pandas

        pdf = _to_pandas(df).copy()
        meta = self._metadata
        xs = to_arrays(pdf, self.feature_cols, meta)
        tx = [torch.as_tensor(np.asarray(a, np.float32)) for a in xs]
        if self.input_shapes:
            # Reference convention: shapes include the -1 batch dim.
            tx = [t.reshape(tuple(s))
                  for t, s in zip(tx, self.input_shapes)]
        self.model.eval()
        with torch.no_grad():
            out = self.model(*tx)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for col, p in zip(self.label_cols, outs):
            p = p.numpy()
            pdf[f"{col}__output"] = (
                list(p) if p.ndim > 1 and p.shape[-1] > 1 else p.reshape(-1))
        return pdf
