"""Streaming Parquet shard reader — the Petastorm role.

The reference's estimator remote trainers stream training data through
Petastorm readers over the store's Parquet shards
(``horovod/spark/keras/remote.py``, ``horovod/spark/torch/remote.py``)
rather than materializing a shard in memory. ``ShardReader`` plays that
role TPU-native and dependency-free: row groups are the sharding unit
(round-robin by global row-group index, disjoint per rank, full
coverage), one row group is resident at a time, and an optional
shuffle window mixes rows across nearby row groups — Petastorm's
``shuffle_row_groups`` + row buffer, bounded memory either way.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


class ShardReader:
    """Iterates this rank's shard of a Parquet dataset in batches.

    Yields ``(features, labels)`` — lists of np arrays stacked per column
    (same layout as ``util.to_arrays``) of ``batch_size`` rows (the final
    batch may be short). Re-iterable: each ``batches(epoch)`` pass
    re-reads from disk, with per-epoch shuffle order.
    """

    def __init__(self, path: str, meta: Dict, rank: int = 0, size: int = 1,
                 batch_size: int = 32, shuffle: bool = True,
                 shuffle_window_row_groups: int = 4,
                 columns: Optional[Sequence[str]] = None,
                 transform_fn=None, sample_weight_col: Optional[str] = None,
                 num_workers: int = 0):
        """``transform_fn(pdf) -> pdf`` is applied to each row group's
        pandas frame before batching — the Estimator ``transformation_fn``
        hook (the role Petastorm's TransformSpec plays in the reference's
        remote trainers). ``sample_weight_col`` adds a third per-batch
        array of per-row weights (reference ``sample_weight_col`` param).
        ``num_workers`` > 0 prefetches+decodes row groups on that many
        background threads (the ``train_reader_num_workers`` /
        ``val_reader_num_workers`` role — Petastorm's reader pool), with
        a bounded queue so memory stays at O(workers) row groups; 0 reads
        synchronously.
        """
        import pyarrow.parquet as pq

        self._pq = pq
        self._meta = meta
        self._batch = batch_size
        self._shuffle = shuffle
        self._window = max(1, shuffle_window_row_groups)
        self._transform = transform_fn
        self._weight_col = sample_weight_col
        self._num_workers = max(0, int(num_workers or 0))
        self._feature_cols = list(meta["feature_cols"])
        self._label_cols = list(meta["label_cols"])
        self._columns = (list(columns) if columns is not None
                         else self._feature_cols + self._label_cols)
        if sample_weight_col and sample_weight_col not in self._columns:
            self._columns.append(sample_weight_col)
        # This rank's (filename, row_group) list — the single sharding
        # rule lives in util.iter_shard_groups. Filenames, not handles:
        # files open lazily during iteration so descriptor count stays
        # O(1) regardless of partition count.
        from .util import iter_shard_groups

        self._groups: List[Tuple[str, int]] = []
        self._rows = 0
        for fname, rg, rows in iter_shard_groups(path, rank, size):
            self._groups.append((fname, rg))
            self._rows += rows

    @property
    def rows(self) -> int:
        """Rows in this rank's shard (known without reading data)."""
        return self._rows

    def steps_per_epoch(self) -> int:
        return max(1, int(np.ceil(self._rows / self._batch)))

    def _group_arrays(self, table) -> List[List[np.ndarray]]:
        # Decode through to_arrays (shared layout contract with the
        # whole-shard path) — pandas/pyarrow convert columns at C speed;
        # per-cell Python conversion would dominate epoch time. One
        # to_pandas per row group; the transformation_fn hook sees the
        # frame before any array extraction.
        from .util import to_arrays

        pdf = table.to_pandas()
        if self._transform is not None:
            pdf = self._transform(pdf)
        cols = [to_arrays(pdf, self._feature_cols, self._meta),
                to_arrays(pdf, self._label_cols, self._meta)]
        if self._weight_col:
            cols.append([np.asarray(pdf[self._weight_col])])
        return cols

    def _read_decode(self, group, tls):
        """Read + transform + decode one (file, row_group); returns
        (arrays, n_rows). Used from reader worker threads, so the
        transform_fn must be thread-safe when num_workers > 0. ``tls``
        is a threading.local carrying a per-worker {fname: ParquetFile}
        handle cache (one footer parse per file per worker, matching
        the synchronous path's cost profile)."""
        fname, rg = group
        cache = getattr(tls, "files", None)
        if cache is None:
            cache = tls.files = {}
        pf = cache.get(fname)
        if pf is None:
            pf = cache[fname] = self._pq.ParquetFile(fname)
        table = pf.read_row_group(rg, columns=self._columns)
        arrays = self._group_arrays(table)
        n_rows = len(arrays[1][0]) if arrays[1] else table.num_rows
        return arrays, n_rows

    def _iter_group_arrays(self, order):
        """Yield (arrays, n_rows) per row group in ``order``. With
        ``num_workers`` > 0, reads+decodes run ahead on a thread pool
        with bounded in-flight work (the Petastorm reader-pool role);
        results always arrive in order."""
        if self._num_workers <= 0:
            cache = {"name": None, "pf": None}  # one open file at a time
            for i in order:
                fname, rg = self._groups[i]
                if cache["name"] != fname:
                    cache["name"] = fname
                    cache["pf"] = self._pq.ParquetFile(fname)
                table = cache["pf"].read_row_group(
                    rg, columns=self._columns)
                arrays = self._group_arrays(table)
                n_rows = (len(arrays[1][0]) if arrays[1]
                          else table.num_rows)
                yield arrays, n_rows
            return
        import collections
        import concurrent.futures
        import threading

        tls = threading.local()
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self._num_workers)
        pending = collections.deque()
        it = iter(order)

        def submit_next():
            try:
                i = next(it)
            except StopIteration:
                return
            pending.append(
                pool.submit(self._read_decode, self._groups[i], tls))

        try:
            for _ in range(self._num_workers + 1):
                submit_next()
            while pending:
                result = pending.popleft().result()
                submit_next()
                yield result
        finally:
            # An abandoned epoch (fit pulling fewer steps than the
            # shard holds) must not block on — or waste — the
            # prefetched reads: drop queued work, don't wait.
            pool.shutdown(wait=False, cancel_futures=True)

    def batches(self, epoch: int = 0
                ) -> Iterator[Tuple[List[np.ndarray], ...]]:
        """One pass over the shard, yielding ``(features, labels)`` — or
        ``(features, labels, [weights])`` with ``sample_weight_col`` —
        per batch. Bounded memory: at most ``shuffle_window_row_groups``
        (+ prefetch depth) row groups resident."""
        rng = np.random.RandomState(epoch)
        order = (rng.permutation(len(self._groups)) if self._shuffle
                 else np.arange(len(self._groups)))

        n_streams = 3 if self._weight_col else 2
        bufs: List[List[List[np.ndarray]]] = [[] for _ in range(n_streams)]
        buffered = 0

        def drain(final=False):
            nonlocal bufs, buffered
            if buffered == 0:
                return
            streams = [
                [np.concatenate([b[c] for b in bufs[s]])
                 for c in range(len(bufs[s][0]))]
                for s in range(n_streams)
            ]
            if self._shuffle:
                perm = rng.permutation(buffered)
                streams = [[a[perm] for a in s] for s in streams]
            n = buffered
            start = 0
            while start < n:
                end = min(start + self._batch, n)
                if not final and n - start < self._batch:
                    # Carry the remainder into the next window so only the
                    # epoch's last batch can be short.
                    bufs = [[[a[start:] for a in s]] for s in streams]
                    buffered = n - start
                    return
                yield tuple([a[start:end] for a in s] for s in streams)
                start = end
            bufs, buffered = [[] for _ in range(n_streams)], 0

        for arrays, n_rows in self._iter_group_arrays(order):
            for s in range(n_streams):
                bufs[s].append(arrays[s])
            buffered += n_rows
            if len(bufs[0]) >= self._window:
                yield from drain(final=False)
        yield from drain(final=True)
