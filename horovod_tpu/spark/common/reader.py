"""Streaming Parquet shard reader — the Petastorm role.

The reference's estimator remote trainers stream training data through
Petastorm readers over the store's Parquet shards
(``horovod/spark/keras/remote.py``, ``horovod/spark/torch/remote.py``)
rather than materializing a shard in memory. ``ShardReader`` plays that
role TPU-native and dependency-free: row groups are the sharding unit
(round-robin by global row-group index, disjoint per rank, full
coverage), one row group is resident at a time, and an optional
shuffle window mixes rows across nearby row groups — Petastorm's
``shuffle_row_groups`` + row buffer, bounded memory either way.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


class ShardReader:
    """Iterates this rank's shard of a Parquet dataset in batches.

    Yields ``(features, labels)`` — lists of np arrays stacked per column
    (same layout as ``util.to_arrays``) of ``batch_size`` rows (the final
    batch may be short). Re-iterable: each ``batches(epoch)`` pass
    re-reads from disk, with per-epoch shuffle order.
    """

    def __init__(self, path: str, meta: Dict, rank: int = 0, size: int = 1,
                 batch_size: int = 32, shuffle: bool = True,
                 shuffle_window_row_groups: int = 4,
                 columns: Optional[Sequence[str]] = None):
        import pyarrow.parquet as pq

        self._pq = pq
        self._meta = meta
        self._batch = batch_size
        self._shuffle = shuffle
        self._window = max(1, shuffle_window_row_groups)
        self._feature_cols = list(meta["feature_cols"])
        self._label_cols = list(meta["label_cols"])
        self._columns = (list(columns) if columns is not None
                         else self._feature_cols + self._label_cols)
        # This rank's (filename, row_group) list — the single sharding
        # rule lives in util.iter_shard_groups. Filenames, not handles:
        # files open lazily during iteration so descriptor count stays
        # O(1) regardless of partition count.
        from .util import iter_shard_groups

        self._groups: List[Tuple[str, int]] = []
        self._rows = 0
        for fname, rg, rows in iter_shard_groups(path, rank, size):
            self._groups.append((fname, rg))
            self._rows += rows

    @property
    def rows(self) -> int:
        """Rows in this rank's shard (known without reading data)."""
        return self._rows

    def steps_per_epoch(self) -> int:
        return max(1, int(np.ceil(self._rows / self._batch)))

    def _column_arrays(self, table, cols: Sequence[str]) -> List[np.ndarray]:
        # Decode through to_arrays (shared layout contract with the
        # whole-shard path) — pandas/pyarrow convert columns at C speed;
        # per-cell Python conversion would dominate epoch time.
        from .util import to_arrays

        return to_arrays(table.to_pandas(), cols, self._meta)

    def batches(self, epoch: int = 0
                ) -> Iterator[Tuple[List[np.ndarray], List[np.ndarray]]]:
        """One pass over the shard. Bounded memory: at most
        ``shuffle_window_row_groups`` row groups resident."""
        rng = np.random.RandomState(epoch)
        order = (rng.permutation(len(self._groups)) if self._shuffle
                 else np.arange(len(self._groups)))
        cache = {"name": None, "pf": None}  # one open file at a time

        def read_group(i):
            fname, rg = self._groups[order[i]]
            if cache["name"] != fname:
                cache["name"] = fname
                cache["pf"] = self._pq.ParquetFile(fname)
            return cache["pf"].read_row_group(rg, columns=self._columns)

        feat_buf: List[np.ndarray] = []
        lab_buf: List[np.ndarray] = []
        buffered = 0

        def drain(final=False):
            nonlocal feat_buf, lab_buf, buffered
            if buffered == 0:
                return
            feats = [np.concatenate([b[c] for b in feat_buf])
                     for c in range(len(self._feature_cols))]
            labs = [np.concatenate([b[c] for b in lab_buf])
                    for c in range(len(self._label_cols))]
            if self._shuffle:
                perm = rng.permutation(buffered)
                feats = [f[perm] for f in feats]
                labs = [y[perm] for y in labs]
            n = buffered
            start = 0
            while start < n:
                end = min(start + self._batch, n)
                if not final and n - start < self._batch:
                    # Carry the remainder into the next window so only the
                    # epoch's last batch can be short.
                    feat_buf = [[f[start:] for f in feats]]
                    lab_buf = [[y[start:] for y in labs]]
                    buffered = n - start
                    return
                yield ([f[start:end] for f in feats],
                       [y[start:end] for y in labs])
                start = end
            feat_buf, lab_buf, buffered = [], [], 0

        for i in range(len(self._groups)):
            table = read_group(i)
            feat_buf.append(self._column_arrays(table, self._feature_cols))
            lab_buf.append(self._column_arrays(table, self._label_cols))
            buffered += table.num_rows
            if len(feat_buf) >= self._window:
                yield from drain(final=False)
        yield from drain(final=True)
