"""Storage abstraction for Spark Estimators (parity:
``horovod/spark/common/store.py:430``): where intermediate Parquet data,
checkpoints, and logs live. ``LocalStore`` (plain filesystem) is fully
functional; HDFS/S3 flavors are declared for API parity and gate on their
optional dependencies.
"""

from __future__ import annotations

import contextlib
import os
import shutil
from typing import Optional


class Store:
    """Interface (parity: ``store.py`` Store)."""

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write_text(self, path: str, text: str) -> None:
        raise NotImplementedError

    def is_parquet_dataset(self, path: str) -> bool:
        raise NotImplementedError

    def get_parquet_dataset(self, path: str):
        raise NotImplementedError

    def get_train_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_test_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def saving_runs(self) -> bool:
        return True

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        if prefix_path.startswith("hdfs://"):
            return HDFSStore(prefix_path, *args, **kwargs)
        if prefix_path.startswith("s3://"):
            raise NotImplementedError(
                "S3 store needs an object-store client; mount via FUSE and "
                "use LocalStore, or extend Store")
        return LocalStore(prefix_path, *args, **kwargs)


class LocalStore(Store):
    """Filesystem store (parity: ``store.py`` LocalStore)."""

    def __init__(self, prefix_path: str,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 test_path: Optional[str] = None,
                 runs_path: Optional[str] = None,
                 save_runs: bool = True):
        self.prefix_path = prefix_path
        self._train_path = train_path or os.path.join(
            prefix_path, "intermediate_train_data")
        self._val_path = val_path or os.path.join(
            prefix_path, "intermediate_val_data")
        self._test_path = test_path or os.path.join(
            prefix_path, "intermediate_test_data")
        self._runs_path = runs_path or os.path.join(prefix_path, "runs")
        self._save_runs = save_runs
        os.makedirs(prefix_path, exist_ok=True)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write_text(self, path: str, text: str) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(text)

    def is_parquet_dataset(self, path: str) -> bool:
        return os.path.isdir(path) and any(
            n.endswith(".parquet") for n in os.listdir(path))

    def get_parquet_dataset(self, path: str):
        import pyarrow.parquet as pq  # optional dependency

        return pq.ParquetDataset(path)

    def _suffixed(self, base: str, idx) -> str:
        return base if idx is None else f"{base}.{idx}"

    def get_train_data_path(self, idx=None) -> str:
        return self._suffixed(self._train_path, idx)

    def get_val_data_path(self, idx=None) -> str:
        return self._suffixed(self._val_path, idx)

    def get_test_data_path(self, idx=None) -> str:
        return self._suffixed(self._test_path, idx)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self._runs_path, run_id, "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self._runs_path, run_id, "logs")

    def saving_runs(self) -> bool:
        return self._save_runs

    def clear(self) -> None:
        with contextlib.suppress(FileNotFoundError):
            shutil.rmtree(self.prefix_path)


class HDFSStore(Store):
    """HDFS store (parity: ``store.py`` HDFSStore); gates on pyarrow's
    HDFS client."""

    def __init__(self, prefix_path: str, *args, **kwargs):
        raise NotImplementedError(
            "HDFS store requires a pyarrow HDFS connection, unavailable in "
            "the TPU image; use LocalStore on a mounted filesystem")
