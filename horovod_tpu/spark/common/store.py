"""Storage abstraction for Spark Estimators (parity:
``horovod/spark/common/store.py:430``): where intermediate Parquet data,
checkpoints, and logs live. ``LocalStore`` (plain filesystem) is fully
functional; HDFS/S3 flavors are declared for API parity and gate on their
optional dependencies.
"""

from __future__ import annotations

import contextlib
import os
import shutil
from typing import Optional


class Store:
    """Interface (parity: ``store.py`` Store)."""

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write_text(self, path: str, text: str) -> None:
        raise NotImplementedError

    def is_parquet_dataset(self, path: str) -> bool:
        raise NotImplementedError

    def get_parquet_dataset(self, path: str):
        raise NotImplementedError

    def get_train_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_test_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def saving_runs(self) -> bool:
        return True

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        if prefix_path.startswith("hdfs://"):
            return HDFSStore(prefix_path, *args, **kwargs)
        if prefix_path.startswith("s3://"):
            return S3Store(prefix_path, *args, **kwargs)
        return LocalStore(prefix_path, *args, **kwargs)


class LocalStore(Store):
    """Filesystem store (parity: ``store.py`` LocalStore)."""

    def __init__(self, prefix_path: str,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 test_path: Optional[str] = None,
                 runs_path: Optional[str] = None,
                 save_runs: bool = True):
        self.prefix_path = prefix_path
        self._train_path = train_path or os.path.join(
            prefix_path, "intermediate_train_data")
        self._val_path = val_path or os.path.join(
            prefix_path, "intermediate_val_data")
        self._test_path = test_path or os.path.join(
            prefix_path, "intermediate_test_data")
        self._runs_path = runs_path or os.path.join(prefix_path, "runs")
        self._save_runs = save_runs
        os.makedirs(prefix_path, exist_ok=True)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write_text(self, path: str, text: str) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(text)

    def is_parquet_dataset(self, path: str) -> bool:
        return os.path.isdir(path) and any(
            n.endswith(".parquet") for n in os.listdir(path))

    def get_parquet_dataset(self, path: str):
        import pyarrow.parquet as pq  # optional dependency

        return pq.ParquetDataset(path)

    def _suffixed(self, base: str, idx) -> str:
        return base if idx is None else f"{base}.{idx}"

    def get_train_data_path(self, idx=None) -> str:
        return self._suffixed(self._train_path, idx)

    def get_val_data_path(self, idx=None) -> str:
        return self._suffixed(self._val_path, idx)

    def get_test_data_path(self, idx=None) -> str:
        return self._suffixed(self._test_path, idx)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self._runs_path, run_id, "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self._runs_path, run_id, "logs")

    def saving_runs(self) -> bool:
        return self._save_runs

    def clear(self) -> None:
        with contextlib.suppress(FileNotFoundError):
            shutil.rmtree(self.prefix_path)


class _FilesystemStore(Store):
    """Shared implementation over a ``pyarrow.fs.FileSystem`` (the role of
    the reference's HDFSStore pyarrow client, ``store.py:280-430``). Path
    layout mirrors LocalStore; IO goes through the pyarrow filesystem so
    the same code serves HDFS and S3. The filesystem connects lazily —
    constructing a store (and computing its paths) needs no cluster."""

    def __init__(self, prefix_path: str,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 test_path: Optional[str] = None,
                 runs_path: Optional[str] = None,
                 save_runs: bool = True):
        self.prefix_path = prefix_path.rstrip("/")
        join = "/".join
        self._train_path = train_path or join(
            [self.prefix_path, "intermediate_train_data"])
        self._val_path = val_path or join(
            [self.prefix_path, "intermediate_val_data"])
        self._test_path = test_path or join(
            [self.prefix_path, "intermediate_test_data"])
        self._runs_path = runs_path or join([self.prefix_path, "runs"])
        self._save_runs = save_runs
        self._fs = None

    # -- subclass hook -------------------------------------------------------

    def _connect(self):
        """Return (pyarrow.fs.FileSystem, path-stripper)."""
        raise NotImplementedError

    def _fs_and_path(self, path: str):
        if self._fs is None:
            self._fs = self._connect()
        return self._fs, self._strip(path)

    # -- Store interface over pyarrow.fs -------------------------------------

    def exists(self, path: str) -> bool:
        from pyarrow.fs import FileType

        fs, p = self._fs_and_path(path)
        return fs.get_file_info(p).type != FileType.NotFound

    def read(self, path: str) -> bytes:
        fs, p = self._fs_and_path(path)
        with fs.open_input_stream(p) as f:
            return f.read()

    def write_text(self, path: str, text: str) -> None:
        fs, p = self._fs_and_path(path)
        parent = p.rsplit("/", 1)[0]
        fs.create_dir(parent, recursive=True)
        with fs.open_output_stream(p) as f:
            f.write(text.encode())

    def is_parquet_dataset(self, path: str) -> bool:
        from pyarrow.fs import FileSelector, FileType

        fs, p = self._fs_and_path(path)
        info = fs.get_file_info(p)
        if info.type != FileType.Directory:
            return False
        return any(i.path.endswith(".parquet")
                   for i in fs.get_file_info(FileSelector(p)))

    def get_parquet_dataset(self, path: str):
        import pyarrow.parquet as pq

        fs, p = self._fs_and_path(path)
        return pq.ParquetDataset(p, filesystem=fs)

    def _suffixed(self, base: str, idx) -> str:
        return base if idx is None else f"{base}.{idx}"

    def get_train_data_path(self, idx=None) -> str:
        return self._suffixed(self._train_path, idx)

    def get_val_data_path(self, idx=None) -> str:
        return self._suffixed(self._val_path, idx)

    def get_test_data_path(self, idx=None) -> str:
        return self._suffixed(self._test_path, idx)

    def get_checkpoint_path(self, run_id: str) -> str:
        return "/".join([self._runs_path, run_id, "checkpoint"])

    def get_logs_path(self, run_id: str) -> str:
        return "/".join([self._runs_path, run_id, "logs"])

    def saving_runs(self) -> bool:
        return self._save_runs


class HDFSStore(_FilesystemStore):
    """HDFS store (parity: ``store.py:280`` HDFSStore) over
    ``pyarrow.fs.HadoopFileSystem``. Fully functional where libhdfs is
    present; path construction and layout work without a cluster, and
    the first actual IO raises pyarrow's descriptive error when the
    Hadoop client libraries are missing (as on the TPU image)."""

    def __init__(self, prefix_path: str, host: Optional[str] = None,
                 port: Optional[int] = None, user: Optional[str] = None,
                 **kwargs):
        super().__init__(prefix_path, **kwargs)
        rest = prefix_path[len("hdfs://"):]
        authority = rest.split("/", 1)[0]
        if host is None and authority and ":" in authority:
            host, _, port_s = authority.partition(":")
            port = port or int(port_s)
        elif host is None and authority:
            host = authority
        self._host = host or "default"
        self._port = port or 0
        self._user = user

    def _strip(self, path: str) -> str:
        if path.startswith("hdfs://"):
            rest = path[len("hdfs://"):]
            return "/" + rest.split("/", 1)[1] if "/" in rest else "/"
        return path

    def _connect(self):
        from pyarrow.fs import HadoopFileSystem

        return HadoopFileSystem(self._host, self._port, user=self._user)


class S3Store(_FilesystemStore):
    """S3 store over ``pyarrow.fs.S3FileSystem`` (the reference gates S3
    behind fsspec the same way; here pyarrow's native client serves)."""

    def _strip(self, path: str) -> str:
        return path[len("s3://"):] if path.startswith("s3://") else path

    def _connect(self):
        from pyarrow.fs import S3FileSystem

        return S3FileSystem()
