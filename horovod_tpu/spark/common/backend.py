"""Estimator execution backends (parity: ``horovod/spark/common/backend.py``).

The reference's ``SparkBackend`` runs the remote training function on
``num_proc`` Spark executors through ``horovod.spark.run``. Here the same
interface has two implementations:

- ``LocalBackend`` — runs the training function in-process with the
  collective world initialized over the local device mesh. This is the
  TPU-native default: on a TPU VM the executors *are* the local chips, so
  in-process SPMD replaces per-executor processes.
- ``SparkBackend`` — dispatches through ``horovod_tpu.spark.run`` when
  pyspark is available (cluster mode).
"""

from __future__ import annotations

from typing import Callable, Optional


class Backend:
    """Interface (parity: ``backend.py`` Backend)."""

    def run(self, fn: Callable, args=(), kwargs=None, env=None):
        raise NotImplementedError

    def num_processes(self) -> int:
        raise NotImplementedError


class LocalBackend(Backend):
    """Run the remote function once in-process (world = local devices)."""

    def __init__(self, num_proc: Optional[int] = None, verbose: int = 0):
        self._num_proc = num_proc or 1
        self.verbose = verbose

    def num_processes(self) -> int:
        return self._num_proc

    def run(self, fn, args=(), kwargs=None, env=None):
        return [fn(*args, **(kwargs or {}))]


class SparkBackend(Backend):
    """Run on Spark executors (parity: ``backend.py`` SparkBackend)."""

    def __init__(self, num_proc: Optional[int] = None, env=None,
                 verbose: int = 0, nics=None, prefix_output_with_timestamp=False):
        self._num_proc = num_proc
        self._env = env
        self.verbose = verbose
        self._nics = nics
        self._prefix = prefix_output_with_timestamp

    def num_processes(self) -> int:
        return self._num_proc or 1

    def run(self, fn, args=(), kwargs=None, env=None):
        from .. import run as spark_run

        return spark_run(fn, args=args, kwargs=kwargs or {},
                         num_proc=self._num_proc, env=env or self._env,
                         verbose=self.verbose, nics=self._nics)
