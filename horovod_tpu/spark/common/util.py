"""Data-preparation helpers for the Spark Estimators (parity:
``horovod/spark/common/util.py`` — ``prepare_data``/``get_simple_meta_from_parquet``).

The reference materializes a Spark DataFrame to Parquet in the Store and
derives per-column metadata (shape, dtype, row counts) that the remote
training functions need. The TPU-native port does the same from either a
Spark DataFrame (when pyspark is importable) or a pandas DataFrame via
pyarrow, so the full estimator path is exercisable without a cluster.
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .store import Store


def _to_pandas(df):
    """Accept a pandas DataFrame directly or convert a Spark DataFrame."""
    import pandas as pd

    if isinstance(df, pd.DataFrame):
        return df
    # Spark DataFrame (duck-typed so pyspark stays optional).
    if hasattr(df, "toPandas"):
        return df.toPandas()
    raise TypeError(
        f"expected a pandas or Spark DataFrame, got {type(df)}")


def _col_shape(series) -> Tuple[int, ...]:
    """Per-row shape of a column: scalars → (), list/array cells → cell shape."""
    first = series.iloc[0]
    if isinstance(first, (list, tuple)):
        return (len(first),)
    if isinstance(first, np.ndarray):
        return tuple(first.shape)
    return ()


def make_metadata(pdf, feature_cols: Sequence[str],
                  label_cols: Sequence[str]) -> Dict:
    """Column metadata in the spirit of the reference's ``_get_metadata``."""
    meta = {"columns": {}, "feature_cols": list(feature_cols),
            "label_cols": list(label_cols), "rows": len(pdf)}
    avg_row_bytes = 0
    for col in list(feature_cols) + list(label_cols):
        if col not in pdf.columns:
            raise ValueError(f"column '{col}' not in DataFrame "
                             f"(have {list(pdf.columns)})")
        shape = _col_shape(pdf[col])
        arr = np.asarray(pdf[col].iloc[0])
        meta["columns"][col] = {
            "shape": list(shape),
            "dtype": str(arr.dtype),
            "size": int(np.prod(shape)) if shape else 1,
        }
        avg_row_bytes += (int(np.prod(shape)) if shape else 1) * arr.itemsize
    meta["avg_row_size"] = avg_row_bytes
    return meta


def write_parquet(pdf, path: str, num_partitions: int = 1) -> int:
    """Materialize a pandas DataFrame as a Parquet dataset directory with
    ``num_partitions`` files (the sharding unit for distributed readers)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    n = len(pdf)
    per = math.ceil(n / max(1, num_partitions)) or 1
    written = 0
    for i in range(max(1, num_partitions)):
        chunk = pdf.iloc[i * per:(i + 1) * per]
        if chunk.empty and i > 0:
            break
        table = pa.Table.from_pandas(chunk.reset_index(drop=True),
                                     preserve_index=False)
        # ~8 row groups per partition gives the round-robin shard reader
        # granularity (a world larger than the partition count still gets
        # data on every rank) without fragmenting large datasets into tiny
        # groups.
        row_group_size = max(1, math.ceil(len(chunk) / 8))
        pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"),
                       row_group_size=row_group_size)
        written += len(chunk)
    return written


def prepare_data(store: Store, df, feature_cols: Sequence[str],
                 label_cols: Sequence[str],
                 validation=None, num_partitions: int = 1,
                 dataset_idx=None) -> Dict:
    """Split ``df`` into train/val, write both to the store's intermediate
    Parquet paths, and return the metadata dict (parity:
    ``common/util.py`` ``prepare_data``).

    ``validation`` may be a float fraction (tail split, as the reference's
    random split plays that role), a column name of 0/1 flags, or a second
    DataFrame.
    """
    pdf = _to_pandas(df)
    val_pdf = None
    if validation is None:
        train_pdf = pdf
    elif isinstance(validation, float):
        n_val = int(len(pdf) * validation)
        train_pdf, val_pdf = pdf.iloc[:-n_val or None], (
            pdf.iloc[-n_val:] if n_val else None)
    elif isinstance(validation, str):
        mask = pdf[validation].astype(bool)
        train_pdf = pdf[~mask].drop(columns=[validation])
        val_pdf = pdf[mask].drop(columns=[validation])
    else:
        train_pdf, val_pdf = pdf, _to_pandas(validation)

    meta = make_metadata(train_pdf, feature_cols, label_cols)
    train_path = store.get_train_data_path(dataset_idx)
    meta["train_rows"] = write_parquet(train_pdf, train_path, num_partitions)
    meta["train_data_path"] = train_path
    if val_pdf is not None and len(val_pdf):
        val_path = store.get_val_data_path(dataset_idx)
        meta["val_rows"] = write_parquet(val_pdf, val_path, num_partitions)
        meta["val_data_path"] = val_path
    else:
        meta["val_rows"] = 0
        meta["val_data_path"] = None
    return meta


def _list_parquet_files(path: str) -> List[str]:
    """THE dataset file-listing rule (single definition for sharding and
    schema recovery)."""
    return sorted(
        os.path.join(path, f) for f in os.listdir(path)
        if f.endswith(".parquet"))


def iter_shard_groups(path: str, rank: int = 0, size: int = 1):
    """This rank's (filename, row_group_index, num_rows) triples.

    THE sharding rule (one definition; ``read_shard`` and the streaming
    ``ShardReader`` both consume it): sorted ``.parquet`` listing,
    round-robin by global row-group index — disjoint per rank, all rows
    covered, the granularity Petastorm uses in the reference's remote
    readers (``spark/keras/remote.py``). Only metadata is read here; no
    file handles outlive the call (a 4096-partition dataset must not pin
    4096 descriptors for a training run's lifetime).
    """
    import pyarrow.parquet as pq

    g = 0  # global row-group index across files
    for fname in _list_parquet_files(path):
        md = pq.read_metadata(fname)
        for rg in range(md.num_row_groups):
            if g % size == rank:
                yield fname, rg, md.row_group(rg).num_rows
            g += 1


def read_shard(path: str, rank: int = 0, size: int = 1,
               columns: Optional[List[str]] = None):
    """Read this rank's whole shard as a pandas DataFrame (see
    ``iter_shard_groups`` for the sharding rule; ``reader.ShardReader``
    streams the same shard with bounded memory)."""
    import pandas as pd
    import pyarrow.parquet as pq

    frames = []
    open_name, open_pf = None, None
    for fname, rg, _rows in iter_shard_groups(path, rank, size):
        if fname != open_name:
            open_name, open_pf = fname, pq.ParquetFile(fname)
        frames.append(open_pf.read_row_group(rg, columns=columns)
                      .to_pandas())
    if not frames:
        # Keep the dataset schema so downstream column selection works on
        # empty shards (this rank drew zero row groups).
        files = _list_parquet_files(path)
        schema_cols = (columns or
                       (pq.read_schema(files[0]).names if files else []))
        return pd.DataFrame(columns=schema_cols)
    return pd.concat(frames, ignore_index=True)


def to_arrays(pdf, cols: Sequence[str], meta: Dict) -> List[np.ndarray]:
    """Stack DataFrame columns into dense np arrays using column metadata
    (list/array cells become trailing dims)."""
    out = []
    for col in cols:
        info = meta["columns"][col]
        shape = tuple(info["shape"])
        if len(pdf) == 0:
            arr = np.zeros((0,) + shape, dtype=info["dtype"])
        elif shape:
            arr = np.stack([np.asarray(v) for v in pdf[col].to_numpy()])
            arr = arr.reshape((len(pdf),) + shape)
        else:
            arr = pdf[col].to_numpy()
        out.append(arr.astype(info["dtype"]))
    return out


def read_val_arrays(meta: Dict, rank: int, size: int,
                    transformation_fn=None):
    """This rank's validation split as ``(features, labels)`` array
    lists, or ``None`` when the split is absent or the shard empty.
    Shared by the keras/torch remote trainers (identical read →
    transform → to_arrays flow; one copy so fixes can't miss a
    framework)."""
    if not meta.get("val_data_path"):
        return None
    vdf = read_shard(meta["val_data_path"], rank, size,
                     columns=(meta["feature_cols"] + meta["label_cols"]))
    if transformation_fn is not None:
        vdf = transformation_fn(vdf)
    if not len(vdf):
        return None
    return (to_arrays(vdf, meta["feature_cols"], meta),
            to_arrays(vdf, meta["label_cols"], meta))
