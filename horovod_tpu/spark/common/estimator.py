"""Spark ML Estimator base (parity: ``horovod/spark/common/estimator.py:26``
HorovodEstimator / HorovodModel).

The reference's Estimators train a Keras/Torch model over Parquet data
materialized by a ``Store`` and return a Spark ML ``Model`` for batch
inference. The TPU-native port keeps the exact param surface; ``fit``
gates on pyspark (not in the TPU image) while parameter validation and
store plumbing work standalone so estimator configs can be built and
tested anywhere.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .store import Store

# cloudpickle handles closures/lambdas (callbacks, transformation_fn);
# pyspark bundles one; plain pickle is the module-level-only fallback
# (same chain as spark/exec.py).
try:
    import cloudpickle as _pickle
except ImportError:
    try:
        from pyspark import cloudpickle as _pickle
    except ImportError:
        import pickle as _pickle


def _save_dir(obj, payload, path: str, meta_name: str,
              blob_name: str) -> None:
    """Versioned-directory persistence shared by Estimator and Model
    (parity role: the reference's HorovodParamsWriter,
    ``keras/estimator.py:40-70``): a json sidecar naming the concrete
    class + format version, and a pickle blob of ``payload``."""
    import json
    import os

    os.makedirs(path, exist_ok=True)
    meta = {
        "class": f"{type(obj).__module__}.{type(obj).__qualname__}",
        "format_version": 1,
    }
    with open(os.path.join(path, meta_name), "w") as f:
        json.dump(meta, f)
    with open(os.path.join(path, blob_name), "wb") as f:
        _pickle.dump(payload, f)


def _load_meta_class(cls, path: str, meta_name: str, kind: str):
    """Read the meta sidecar and resolve+validate the saved class BEFORE
    any pickle bytes are touched (unpickling runs arbitrary code; the
    class gate must come first)."""
    import importlib
    import json
    import os

    with open(os.path.join(path, meta_name)) as f:
        meta = json.load(f)
    if meta.get("format_version") != 1:
        raise ValueError(
            f"unsupported {kind} format {meta.get('format_version')}")
    mod_name, _, qual = meta["class"].rpartition(".")
    klass = getattr(importlib.import_module(mod_name), qual)
    if not (klass is cls or issubclass(klass, cls)):
        raise TypeError(
            f"saved {kind} is a {meta['class']}, not a {cls.__qualname__}")
    return klass


def _to_int(name, v):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise TypeError(f"estimator param '{name}' must be an int, "
                        f"got {type(v).__name__}")
    if int(v) != v:
        raise TypeError(f"estimator param '{name}' must be integral, "
                        f"got {v}")
    return int(v)


def _to_str(name, v):
    if not isinstance(v, str):
        raise TypeError(f"estimator param '{name}' must be a str, "
                        f"got {type(v).__name__}")
    return v


def _to_str_list(name, v):
    if isinstance(v, str):
        return [v]
    if not all(isinstance(s, str) for s in v):
        raise TypeError(f"estimator param '{name}' must be a list of str")
    return list(v)


class EstimatorParams:
    """Declared parameters (parity: the Param list + camelCase accessor
    surface of ``common/params.py:25-350`` — each param gets
    ``set<Name>``/``get<Name>`` methods generated below, the reference's
    Spark-ML ``Params`` idiom without the pyspark dependency).

    Values may be supplied via the constructor, ``setParams(**kwargs)``,
    or the per-param setters; typed params validate on set (the role of
    Spark's ``TypeConverters``)."""

    # name -> (camel accessor suffix, converter or None)
    _PARAM_DEFS = {
        "num_proc": ("NumProc", _to_int),
        "model": ("Model", None),
        "backend": ("Backend", None),
        "store": ("Store", None),
        "optimizer": ("Optimizer", None),
        "loss": ("Loss", None),
        "loss_constructors": ("LossConstructors", None),
        "metrics": ("Metrics", None),
        "loss_weights": ("LossWeights", None),
        "sample_weight_col": ("SampleWeightCol", _to_str),
        "gradient_compression": ("GradientCompression", None),
        "feature_cols": ("FeatureCols", _to_str_list),
        "label_cols": ("LabelCols", _to_str_list),
        "validation": ("Validation", None),
        "callbacks": ("Callbacks", None),
        "batch_size": ("BatchSize", _to_int),
        "epochs": ("Epochs", _to_int),
        "verbose": ("Verbose", _to_int),
        "shuffle_buffer_size": ("ShuffleBufferSize", _to_int),
        "partitions_per_process": ("PartitionsPerProcess", _to_int),
        "run_id": ("RunId", _to_str),
        "train_steps_per_epoch": ("TrainStepsPerEpoch", _to_int),
        "validation_steps_per_epoch": ("ValidationStepsPerEpoch", _to_int),
        "transformation_fn": ("TransformationFn", None),
        "train_reader_num_workers": ("TrainReaderNumWorkers", _to_int),
        # Accepted for reference-API compatibility: validation here is a
        # one-shot whole-shard read (fit holds it in memory), not the
        # reference's streaming Petastorm reader, so a val reader pool
        # has nothing to parallelize.
        "val_reader_num_workers": ("ValReaderNumWorkers", _to_int),
        "label_shapes": ("LabelShapes", None),
    }
    # Subclasses contribute framework-specific params (the reference's
    # class-level Param declarations on KerasEstimator/TorchEstimator)
    # via _EXTRA_PARAM_DEFS, merged down the MRO.
    _EXTRA_PARAM_DEFS: Dict[str, tuple] = {}

    @classmethod
    def _param_defs(cls) -> Dict[str, tuple]:
        defs = dict(EstimatorParams._PARAM_DEFS)
        for klass in reversed(cls.__mro__):
            defs.update(getattr(klass, "_EXTRA_PARAM_DEFS", {}))
        return defs

    def __init__(self, **kwargs):
        self._params: Dict[str, Any] = {
            k: None for k in type(self)._param_defs()}
        self.setParams(**kwargs)

    def _set_one(self, name: str, value):
        if name not in self._params:
            raise ValueError(
                f"unknown estimator param '{name}'; valid: "
                f"{sorted(self._params)}")
        conv = type(self)._param_defs().get(name, (None, None))[1]
        if value is not None and conv is not None:
            value = conv(name, value)
        self._params[name] = value

    def getOrDefault(self, name: str):
        return self._params.get(name)

    def setParams(self, **kwargs) -> "EstimatorParams":
        for k, v in kwargs.items():
            self._set_one(k, v)
        return self


def install_accessors(cls):
    """Generate ``set<Name>``/``get<Name>`` pairs for every declared param
    (parity: the explicit accessor list in ``common/params.py:145-350``).
    Apply to every concrete estimator class that adds _EXTRA_PARAM_DEFS."""
    def make(name):
        def setter(self, value):
            self._set_one(name, value)
            return self

        def getter(self):
            return self.getOrDefault(name)

        return setter, getter

    for name, (camel, _) in cls._param_defs().items():
        setter, getter = make(name)
        setter.__name__, getter.__name__ = f"set{camel}", f"get{camel}"
        setter.__doc__ = f"Set estimator param ``{name}``; returns self."
        getter.__doc__ = f"Get estimator param ``{name}``."
        if not hasattr(cls, f"set{camel}"):
            setattr(cls, setter.__name__, setter)
        if not hasattr(cls, f"get{camel}"):
            setattr(cls, getter.__name__, getter)
    return cls


install_accessors(EstimatorParams)


class HorovodEstimator(EstimatorParams):
    """Base estimator (parity: ``common/estimator.py:26``)."""

    # -- persistence (parity: the Spark-ML read/write surface the
    # reference provides through HorovodParamsWriter/Reader with custom
    # param serializers, keras/estimator.py:40-101; pyspark-free here:
    # params ride cloudpickle, the directory format is versioned) -------

    _PERSIST_META = "estimator.json"
    _PERSIST_PARAMS = "params.pkl"

    def save(self, path: str) -> "HorovodEstimator":
        """Persist this estimator (all params, including the model and
        any callbacks/functions) to a directory; reload with
        ``load(path)`` — the reference's ``est.write().save(path)``."""
        _save_dir(self, self._params, path, self._PERSIST_META,
                  self._PERSIST_PARAMS)
        return self

    @classmethod
    def load(cls, path: str) -> "HorovodEstimator":
        """Reload an estimator saved with ``save`` (reference
        ``Estimator.read().load(path)``). Returns an instance of the
        originally-saved class (which must be ``cls`` or a subclass)."""
        import os
        import pickle

        klass = _load_meta_class(cls, path, cls._PERSIST_META, "estimator")
        with open(os.path.join(path, cls._PERSIST_PARAMS), "rb") as f:
            params = pickle.load(f)
        est = klass()
        est._params.update(params)
        return est

    def _validate(self) -> None:
        if self.getOrDefault("model") is None:
            raise ValueError("model is required")
        store = self.getOrDefault("store")
        if store is not None and not isinstance(store, Store):
            raise ValueError(f"store must be a Store, got {type(store)}")
        if not self.getOrDefault("feature_cols"):
            raise ValueError("feature_cols is required")
        if not self.getOrDefault("label_cols"):
            raise ValueError("label_cols is required")

    # -- template method: shared fit orchestration ---------------------------
    # Subclasses implement the three hooks below; the flow (validate →
    # materialize Parquet → run the remote trainer on the backend → load
    # the rank-0 checkpoint) is identical across frameworks (parity:
    # the reference's HorovodEstimator._fit, ``common/estimator.py``).

    _checkpoint_filename = "model.bin"

    def _make_trainer(self, meta, checkpoint_path):
        """Return the zero-arg function executed on every worker."""
        raise NotImplementedError

    def _load_model(self, store, checkpoint_path):
        """Deserialize the trained model from the store checkpoint."""
        raise NotImplementedError

    def _make_model(self, trained, history, run_id, meta):
        """Wrap the trained model in the framework's HorovodModel."""
        raise NotImplementedError

    def fit(self, df):
        """Train on a (pandas or Spark) DataFrame; returns a HorovodModel."""
        import os
        import uuid

        from .backend import LocalBackend
        from .util import prepare_data

        self._validate()
        store = self.getOrDefault("store")
        if store is None:
            raise ValueError("store is required to fit")
        run_id = self.getOrDefault("run_id") or f"run_{uuid.uuid4().hex[:8]}"
        backend = (self.getOrDefault("backend")
                   or getattr(self, "_backend", None)
                   or LocalBackend(self.getOrDefault("num_proc") or 1))

        # partitions_per_process scales the Parquet partition count so
        # each worker shards over several row groups (reference
        # params.py:77-80; default 10 there, 1 here keeps tiny test
        # datasets intact — pass explicitly for production layouts).
        ppp = self.getOrDefault("partitions_per_process") or 1
        meta = prepare_data(
            store, df,
            self.getOrDefault("feature_cols"),
            self.getOrDefault("label_cols"),
            validation=self.getOrDefault("validation"),
            num_partitions=backend.num_processes() * ppp)

        checkpoint = os.path.join(store.get_checkpoint_path(run_id),
                                  self._checkpoint_filename)
        results = backend.run(self._make_trainer(meta, checkpoint))
        history = results[0]["history"]
        trained = self._load_model(store, checkpoint)
        return self._make_model(trained, history, run_id, meta)


class HorovodModel:
    """Trained-model wrapper for batch inference (parity:
    ``common/estimator.py`` HorovodModel)."""

    def __init__(self, model, feature_cols: Optional[List[str]] = None,
                 label_cols: Optional[List[str]] = None,
                 run_id: Optional[str] = None):
        self.model = model
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.run_id = run_id

    # -- persistence (the Spark-ML Model read/write role) --------------------

    _PERSIST_META = "model.json"
    _PERSIST_BLOB = "model.pkl"

    def save(self, path: str) -> "HorovodModel":
        """Persist the trained-model wrapper (framework model + columns
        + history/metadata) to a directory; reload with ``load(path)``.
        Keras 3 and torch models both round-trip through cloudpickle."""
        _save_dir(self, self, path, self._PERSIST_META, self._PERSIST_BLOB)
        return self

    @classmethod
    def load(cls, path: str) -> "HorovodModel":
        import os
        import pickle

        # Class gate runs on the json sidecar BEFORE any pickle bytes
        # are touched (unpickling executes arbitrary code).
        _load_meta_class(cls, path, cls._PERSIST_META, "model")
        with open(os.path.join(path, cls._PERSIST_BLOB), "rb") as f:
            obj = pickle.load(f)
        if not isinstance(obj, cls):
            raise TypeError(
                f"saved model is a {type(obj).__qualname__}, not a "
                f"{cls.__qualname__}")
        return obj

    def transform(self, df):
        from .. import _require_pyspark

        _require_pyspark()
        raise NotImplementedError(
            "batch inference requires pyspark; call model directly for "
            "local inference")
