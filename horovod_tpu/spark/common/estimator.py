"""Spark ML Estimator base (parity: ``horovod/spark/common/estimator.py:26``
HorovodEstimator / HorovodModel).

The reference's Estimators train a Keras/Torch model over Parquet data
materialized by a ``Store`` and return a Spark ML ``Model`` for batch
inference. The TPU-native port keeps the exact param surface; ``fit``
gates on pyspark (not in the TPU image) while parameter validation and
store plumbing work standalone so estimator configs can be built and
tested anywhere.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .store import Store


class EstimatorParams:
    """Declared parameters (parity: the Param list in
    ``common/estimator.py`` + ``params.py``)."""

    _PARAMS = [
        "num_proc", "model", "backend", "store", "loss", "loss_constructors",
        "metrics", "loss_weights", "sample_weight_col", "feature_cols",
        "label_cols", "validation", "callbacks", "batch_size", "epochs",
        "verbose", "shuffle_buffer_size", "partitions_per_process",
        "run_id", "train_steps_per_epoch", "validation_steps_per_epoch",
        "transformation_fn", "train_reader_num_workers",
        "val_reader_num_workers", "label_shapes",
    ]

    def __init__(self, **kwargs):
        self._params: Dict[str, Any] = {k: None for k in self._PARAMS}
        for k, v in kwargs.items():
            if k not in self._params:
                raise ValueError(
                    f"unknown estimator param '{k}'; valid: "
                    f"{sorted(self._params)}")
            self._params[k] = v

    def getOrDefault(self, name: str):
        return self._params.get(name)

    def setParams(self, **kwargs) -> "EstimatorParams":
        for k, v in kwargs.items():
            if k not in self._params:
                raise ValueError(f"unknown estimator param '{k}'")
            self._params[k] = v
        return self


class HorovodEstimator(EstimatorParams):
    """Base estimator (parity: ``common/estimator.py:26``)."""

    def _validate(self) -> None:
        if self.getOrDefault("model") is None:
            raise ValueError("model is required")
        store = self.getOrDefault("store")
        if store is not None and not isinstance(store, Store):
            raise ValueError(f"store must be a Store, got {type(store)}")
        if not self.getOrDefault("feature_cols"):
            raise ValueError("feature_cols is required")
        if not self.getOrDefault("label_cols"):
            raise ValueError("label_cols is required")

    # -- template method: shared fit orchestration ---------------------------
    # Subclasses implement the three hooks below; the flow (validate →
    # materialize Parquet → run the remote trainer on the backend → load
    # the rank-0 checkpoint) is identical across frameworks (parity:
    # the reference's HorovodEstimator._fit, ``common/estimator.py``).

    _checkpoint_filename = "model.bin"

    def _make_trainer(self, meta, checkpoint_path):
        """Return the zero-arg function executed on every worker."""
        raise NotImplementedError

    def _load_model(self, store, checkpoint_path):
        """Deserialize the trained model from the store checkpoint."""
        raise NotImplementedError

    def _make_model(self, trained, history, run_id, meta):
        """Wrap the trained model in the framework's HorovodModel."""
        raise NotImplementedError

    def fit(self, df):
        """Train on a (pandas or Spark) DataFrame; returns a HorovodModel."""
        import os
        import uuid

        from .backend import LocalBackend
        from .util import prepare_data

        self._validate()
        store = self.getOrDefault("store")
        if store is None:
            raise ValueError("store is required to fit")
        run_id = self.getOrDefault("run_id") or f"run_{uuid.uuid4().hex[:8]}"
        backend = getattr(self, "_backend", None) or LocalBackend(
            self.getOrDefault("num_proc") or 1)

        meta = prepare_data(
            store, df,
            self.getOrDefault("feature_cols"),
            self.getOrDefault("label_cols"),
            validation=self.getOrDefault("validation"),
            num_partitions=backend.num_processes())

        checkpoint = os.path.join(store.get_checkpoint_path(run_id),
                                  self._checkpoint_filename)
        results = backend.run(self._make_trainer(meta, checkpoint))
        history = results[0]["history"]
        trained = self._load_model(store, checkpoint)
        return self._make_model(trained, history, run_id, meta)


class HorovodModel:
    """Trained-model wrapper for batch inference (parity:
    ``common/estimator.py`` HorovodModel)."""

    def __init__(self, model, feature_cols: Optional[List[str]] = None,
                 label_cols: Optional[List[str]] = None,
                 run_id: Optional[str] = None):
        self.model = model
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.run_id = run_id

    def transform(self, df):
        from .. import _require_pyspark

        _require_pyspark()
        raise NotImplementedError(
            "batch inference requires pyspark; call model directly for "
            "local inference")
