"""Spark ML Estimator base (parity: ``horovod/spark/common/estimator.py:26``
HorovodEstimator / HorovodModel).

The reference's Estimators train a Keras/Torch model over Parquet data
materialized by a ``Store`` and return a Spark ML ``Model`` for batch
inference. The TPU-native port keeps the exact param surface; ``fit``
gates on pyspark (not in the TPU image) while parameter validation and
store plumbing work standalone so estimator configs can be built and
tested anywhere.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .store import Store


def _to_int(name, v):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise TypeError(f"estimator param '{name}' must be an int, "
                        f"got {type(v).__name__}")
    if int(v) != v:
        raise TypeError(f"estimator param '{name}' must be integral, "
                        f"got {v}")
    return int(v)


def _to_str(name, v):
    if not isinstance(v, str):
        raise TypeError(f"estimator param '{name}' must be a str, "
                        f"got {type(v).__name__}")
    return v


def _to_str_list(name, v):
    if isinstance(v, str):
        return [v]
    if not all(isinstance(s, str) for s in v):
        raise TypeError(f"estimator param '{name}' must be a list of str")
    return list(v)


class EstimatorParams:
    """Declared parameters (parity: the Param list + camelCase accessor
    surface of ``common/params.py:25-350`` — each param gets
    ``set<Name>``/``get<Name>`` methods generated below, the reference's
    Spark-ML ``Params`` idiom without the pyspark dependency).

    Values may be supplied via the constructor, ``setParams(**kwargs)``,
    or the per-param setters; typed params validate on set (the role of
    Spark's ``TypeConverters``)."""

    # name -> (camel accessor suffix, converter or None)
    _PARAM_DEFS = {
        "num_proc": ("NumProc", _to_int),
        "model": ("Model", None),
        "backend": ("Backend", None),
        "store": ("Store", None),
        "optimizer": ("Optimizer", None),
        "loss": ("Loss", None),
        "loss_constructors": ("LossConstructors", None),
        "metrics": ("Metrics", None),
        "loss_weights": ("LossWeights", None),
        "sample_weight_col": ("SampleWeightCol", _to_str),
        "gradient_compression": ("GradientCompression", None),
        "feature_cols": ("FeatureCols", _to_str_list),
        "label_cols": ("LabelCols", _to_str_list),
        "validation": ("Validation", None),
        "callbacks": ("Callbacks", None),
        "batch_size": ("BatchSize", _to_int),
        "epochs": ("Epochs", _to_int),
        "verbose": ("Verbose", _to_int),
        "shuffle_buffer_size": ("ShuffleBufferSize", _to_int),
        "partitions_per_process": ("PartitionsPerProcess", _to_int),
        "run_id": ("RunId", _to_str),
        "train_steps_per_epoch": ("TrainStepsPerEpoch", _to_int),
        "validation_steps_per_epoch": ("ValidationStepsPerEpoch", _to_int),
        "transformation_fn": ("TransformationFn", None),
        "train_reader_num_workers": ("TrainReaderNumWorkers", _to_int),
        "val_reader_num_workers": ("ValReaderNumWorkers", _to_int),
        "label_shapes": ("LabelShapes", None),
    }
    # Subclasses contribute framework-specific params (the reference's
    # class-level Param declarations on KerasEstimator/TorchEstimator)
    # via _EXTRA_PARAM_DEFS, merged down the MRO.
    _EXTRA_PARAM_DEFS: Dict[str, tuple] = {}

    @classmethod
    def _param_defs(cls) -> Dict[str, tuple]:
        defs = dict(EstimatorParams._PARAM_DEFS)
        for klass in reversed(cls.__mro__):
            defs.update(getattr(klass, "_EXTRA_PARAM_DEFS", {}))
        return defs

    def __init__(self, **kwargs):
        self._params: Dict[str, Any] = {
            k: None for k in type(self)._param_defs()}
        self.setParams(**kwargs)

    def _set_one(self, name: str, value):
        if name not in self._params:
            raise ValueError(
                f"unknown estimator param '{name}'; valid: "
                f"{sorted(self._params)}")
        conv = type(self)._param_defs().get(name, (None, None))[1]
        if value is not None and conv is not None:
            value = conv(name, value)
        self._params[name] = value

    def getOrDefault(self, name: str):
        return self._params.get(name)

    def setParams(self, **kwargs) -> "EstimatorParams":
        for k, v in kwargs.items():
            self._set_one(k, v)
        return self


def install_accessors(cls):
    """Generate ``set<Name>``/``get<Name>`` pairs for every declared param
    (parity: the explicit accessor list in ``common/params.py:145-350``).
    Apply to every concrete estimator class that adds _EXTRA_PARAM_DEFS."""
    def make(name):
        def setter(self, value):
            self._set_one(name, value)
            return self

        def getter(self):
            return self.getOrDefault(name)

        return setter, getter

    for name, (camel, _) in cls._param_defs().items():
        setter, getter = make(name)
        setter.__name__, getter.__name__ = f"set{camel}", f"get{camel}"
        setter.__doc__ = f"Set estimator param ``{name}``; returns self."
        getter.__doc__ = f"Get estimator param ``{name}``."
        if not hasattr(cls, f"set{camel}"):
            setattr(cls, setter.__name__, setter)
        if not hasattr(cls, f"get{camel}"):
            setattr(cls, getter.__name__, getter)
    return cls


install_accessors(EstimatorParams)


class HorovodEstimator(EstimatorParams):
    """Base estimator (parity: ``common/estimator.py:26``)."""

    def _validate(self) -> None:
        if self.getOrDefault("model") is None:
            raise ValueError("model is required")
        store = self.getOrDefault("store")
        if store is not None and not isinstance(store, Store):
            raise ValueError(f"store must be a Store, got {type(store)}")
        if not self.getOrDefault("feature_cols"):
            raise ValueError("feature_cols is required")
        if not self.getOrDefault("label_cols"):
            raise ValueError("label_cols is required")

    # -- template method: shared fit orchestration ---------------------------
    # Subclasses implement the three hooks below; the flow (validate →
    # materialize Parquet → run the remote trainer on the backend → load
    # the rank-0 checkpoint) is identical across frameworks (parity:
    # the reference's HorovodEstimator._fit, ``common/estimator.py``).

    _checkpoint_filename = "model.bin"

    def _make_trainer(self, meta, checkpoint_path):
        """Return the zero-arg function executed on every worker."""
        raise NotImplementedError

    def _load_model(self, store, checkpoint_path):
        """Deserialize the trained model from the store checkpoint."""
        raise NotImplementedError

    def _make_model(self, trained, history, run_id, meta):
        """Wrap the trained model in the framework's HorovodModel."""
        raise NotImplementedError

    def fit(self, df):
        """Train on a (pandas or Spark) DataFrame; returns a HorovodModel."""
        import os
        import uuid

        from .backend import LocalBackend
        from .util import prepare_data

        self._validate()
        store = self.getOrDefault("store")
        if store is None:
            raise ValueError("store is required to fit")
        run_id = self.getOrDefault("run_id") or f"run_{uuid.uuid4().hex[:8]}"
        backend = (self.getOrDefault("backend")
                   or getattr(self, "_backend", None)
                   or LocalBackend(self.getOrDefault("num_proc") or 1))

        # partitions_per_process scales the Parquet partition count so
        # each worker shards over several row groups (reference
        # params.py:77-80; default 10 there, 1 here keeps tiny test
        # datasets intact — pass explicitly for production layouts).
        ppp = self.getOrDefault("partitions_per_process") or 1
        meta = prepare_data(
            store, df,
            self.getOrDefault("feature_cols"),
            self.getOrDefault("label_cols"),
            validation=self.getOrDefault("validation"),
            num_partitions=backend.num_processes() * ppp)

        checkpoint = os.path.join(store.get_checkpoint_path(run_id),
                                  self._checkpoint_filename)
        results = backend.run(self._make_trainer(meta, checkpoint))
        history = results[0]["history"]
        trained = self._load_model(store, checkpoint)
        return self._make_model(trained, history, run_id, meta)


class HorovodModel:
    """Trained-model wrapper for batch inference (parity:
    ``common/estimator.py`` HorovodModel)."""

    def __init__(self, model, feature_cols: Optional[List[str]] = None,
                 label_cols: Optional[List[str]] = None,
                 run_id: Optional[str] = None):
        self.model = model
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.run_id = run_id

    def transform(self, df):
        from .. import _require_pyspark

        _require_pyspark()
        raise NotImplementedError(
            "batch inference requires pyspark; call model directly for "
            "local inference")
