"""Spark ML Estimator base (parity: ``horovod/spark/common/estimator.py:26``
HorovodEstimator / HorovodModel).

The reference's Estimators train a Keras/Torch model over Parquet data
materialized by a ``Store`` and return a Spark ML ``Model`` for batch
inference. The TPU-native port keeps the exact param surface; ``fit``
gates on pyspark (not in the TPU image) while parameter validation and
store plumbing work standalone so estimator configs can be built and
tested anywhere.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .store import Store


class EstimatorParams:
    """Declared parameters (parity: the Param list in
    ``common/estimator.py`` + ``params.py``)."""

    _PARAMS = [
        "num_proc", "model", "backend", "store", "loss", "loss_constructors",
        "metrics", "loss_weights", "sample_weight_col", "feature_cols",
        "label_cols", "validation", "callbacks", "batch_size", "epochs",
        "verbose", "shuffle_buffer_size", "partitions_per_process",
        "run_id", "train_steps_per_epoch", "validation_steps_per_epoch",
        "transformation_fn", "train_reader_num_workers",
        "val_reader_num_workers", "label_shapes",
    ]

    def __init__(self, **kwargs):
        self._params: Dict[str, Any] = {k: None for k in self._PARAMS}
        for k, v in kwargs.items():
            if k not in self._params:
                raise ValueError(
                    f"unknown estimator param '{k}'; valid: "
                    f"{sorted(self._params)}")
            self._params[k] = v

    def getOrDefault(self, name: str):
        return self._params.get(name)

    def setParams(self, **kwargs) -> "EstimatorParams":
        for k, v in kwargs.items():
            if k not in self._params:
                raise ValueError(f"unknown estimator param '{k}'")
            self._params[k] = v
        return self


class HorovodEstimator(EstimatorParams):
    """Base estimator (parity: ``common/estimator.py:26``)."""

    def _validate(self) -> None:
        if self.getOrDefault("model") is None:
            raise ValueError("model is required")
        store = self.getOrDefault("store")
        if store is not None and not isinstance(store, Store):
            raise ValueError(f"store must be a Store, got {type(store)}")
        if not self.getOrDefault("feature_cols"):
            raise ValueError("feature_cols is required")
        if not self.getOrDefault("label_cols"):
            raise ValueError("label_cols is required")

    def fit(self, df):
        """Train on a Spark DataFrame; returns a HorovodModel."""
        self._validate()
        from .. import _require_pyspark

        _require_pyspark()
        raise NotImplementedError(
            "Estimator.fit requires a Spark session with Petastorm-style "
            "data materialization; train through horovod_tpu.spark.run or "
            "the launcher instead")


class HorovodModel:
    """Trained-model wrapper for batch inference (parity:
    ``common/estimator.py`` HorovodModel)."""

    def __init__(self, model, feature_cols: Optional[List[str]] = None,
                 label_cols: Optional[List[str]] = None,
                 run_id: Optional[str] = None):
        self.model = model
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.run_id = run_id

    def transform(self, df):
        from .. import _require_pyspark

        _require_pyspark()
        raise NotImplementedError(
            "batch inference requires pyspark; call model directly for "
            "local inference")
