"""Subprocess bootstrap for in-executor fn execution (parity:
``horovod/spark/task/mpirun_exec_fn.py:1-30``).

Launched by ``SparkTaskService`` inside a Spark executor with the
HOROVOD_* topology block already in the environment: loads the pickled
(fn, args, kwargs) payload, runs fn, and writes the pickled result next
to the payload. hvd.init() inside fn joins the world exactly as an
ssh-launched worker would — the transport to get *here* was Spark's own
(task service over TCP), not ssh.
"""

import sys

try:
    import cloudpickle as _pickle
except ImportError:
    try:
        from pyspark import cloudpickle as _pickle
    except ImportError:
        import pickle as _pickle


def main(payload_path: str) -> int:
    with open(payload_path, "rb") as f:
        fn, args, kwargs = _pickle.loads(f.read())
    result = fn(*args, **kwargs)
    with open(payload_path + ".out", "wb") as f:
        f.write(_pickle.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
