"""``import horovod_tpu.mxnet as hvd`` — MXNet binding (parity:
``horovod/mxnet/__init__.py:36-150``).

The reference pushes collectives onto the MXNet dependency engine via a C
API (``mxnet/mpi_ops.cc:217-283``); the TPU-native equivalent rides the
same host ring plane as the torch/TF bindings, converting NDArrays through
their CPU buffers. MXNet is not part of the TPU image, so this module
gates on import: the API surface is defined for parity and raises a clear
error when MXNet itself is unavailable.
"""

from __future__ import annotations

try:
    import mxnet  # noqa: F401

    _MXNET_AVAILABLE = True
except ImportError:
    _MXNET_AVAILABLE = False

from ..common.host_world import world as _world
from ..ops.xla import Adasum, Average, Max, Min, ReduceOp, Sum  # noqa: F401

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "allreduce", "allreduce_",
    "allgather", "broadcast", "broadcast_", "broadcast_parameters",
    "broadcast_object", "DistributedOptimizer", "DistributedTrainer",
    "Average", "Sum", "Adasum", "Min", "Max", "ReduceOp",
]


def _require_mxnet():
    if not _MXNET_AVAILABLE:
        raise ImportError(
            "horovod_tpu.mxnet requires the mxnet package, which is not "
            "installed in this environment. The torch/tensorflow/keras "
            "bindings and the JAX-native API cover the same collective "
            "surface.")


def init(comm=None):
    _world().init(comm=comm)


def shutdown():
    _world().shutdown()


def is_initialized() -> bool:
    return _world().initialized


def rank() -> int:
    _world().require_init()
    return _world().rank


def size() -> int:
    _world().require_init()
    return _world().size


def local_rank() -> int:
    _world().require_init()
    return _world().local_rank


def local_size() -> int:
    _world().require_init()
    return _world().local_size


def cross_rank() -> int:
    _world().require_init()
    return _world().cross_rank


def cross_size() -> int:
    _world().require_init()
    return _world().cross_size


def _nd_collective(kind, tensor, **kw):
    """Route an NDArray through the numpy host-plane collectives."""
    _require_mxnet()
    import numpy as np

    from ..tensorflow.mpi_ops import (
        _np_allgather, _np_allreduce, _np_broadcast)

    arr = tensor.asnumpy()
    if kind == "allreduce":
        # _np_allreduce already applies the 1/size scaling for Average
        # (ring AVERAGE op natively; identity at size 1).
        out = _np_allreduce(arr, kw["name"], kw["op"], 1.0, 1.0)
    elif kind == "allgather":
        out = _np_allgather(arr, kw["name"])
    else:
        out = _np_broadcast(arr, kw["root_rank"], kw["name"])
    return mxnet.nd.array(out, dtype=arr.dtype.name)


_name_counter = 0


def _auto_name(prefix):
    global _name_counter
    _name_counter += 1
    return f"mx.{prefix}.{_name_counter}"


def allreduce(tensor, average=True, name=None, priority=0):
    """(parity: ``mxnet/mpi_ops.py:48-120``; ``priority`` accepted for API
    compatibility — XLA/ring scheduling replaces engine priorities)."""
    return _nd_collective("allreduce", tensor,
                          name=name or _auto_name("allreduce"),
                          op=Average if average else Sum)


def allreduce_(tensor, average=True, name=None, priority=0):
    out = allreduce(tensor, average, name, priority)
    tensor[:] = out
    return tensor


def allgather(tensor, name=None, priority=0):
    return _nd_collective("allgather", tensor,
                          name=name or _auto_name("allgather"))


def broadcast(tensor, root_rank, name=None, priority=0):
    return _nd_collective("broadcast", tensor, root_rank=root_rank,
                          name=name or _auto_name("broadcast"))


def broadcast_(tensor, root_rank, name=None, priority=0):
    out = broadcast(tensor, root_rank, name, priority)
    tensor[:] = out
    return tensor


def broadcast_parameters(params, root_rank=0):
    """Broadcast a gluon ParameterDict / dict of NDArrays (parity:
    ``mxnet/__init__.py:116-150``)."""
    _require_mxnet()
    for i, (name, p) in enumerate(sorted(params.items())):
        try:
            tensor = p.data() if hasattr(p, "data") else p
        except Exception:
            continue
        broadcast_(tensor, root_rank, name=f"mx.bcast.{i}.{name}")


def broadcast_object(obj, root_rank=0, name=None):
    """Broadcast an arbitrary picklable object (parity with the other
    bindings; the reference's mxnet module gained this in later versions).
    Pure host-plane — usable without mxnet installed."""
    from ..torch import functions as _torch_functions  # shared host impl

    return _torch_functions.broadcast_object(obj, root_rank, name=name)


class DistributedOptimizer:
    """Wrap an mxnet Optimizer: allreduce gradients in update() (parity:
    ``mxnet/__init__.py:36-77``)."""

    def __init__(self, optimizer):
        _require_mxnet()
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def _do_allreduce(self, index, grad):
        if size() == 1:
            return grad
        if isinstance(index, (tuple, list)):
            return [allreduce(g, average=True,
                              name=f"mx.grad.{i}")
                    for i, g in zip(index, grad)]
        return allreduce(grad, average=True, name=f"mx.grad.{index}")

    def update(self, index, weight, grad, state):
        grad = self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        grad = self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)


def DistributedTrainer(params, optimizer, optimizer_params=None, **kwargs):
    """gluon Trainer whose step() averages gradients (parity:
    ``mxnet/__init__.py:79-114``)."""
    _require_mxnet()
    import mxnet.gluon as gluon

    class _Trainer(gluon.Trainer):
        def __init__(self):
            super().__init__(params, optimizer,
                             optimizer_params=optimizer_params, **kwargs)
            self._scale /= size()

        def _allreduce_grads(self):
            if size() == 1:
                return
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    for g in param.list_grad():
                        allreduce_(g, average=False,
                                   name=f"mx.trainer.grad.{i}")

    return _Trainer()
