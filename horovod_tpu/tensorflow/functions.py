"""High-level TF helpers (parity: ``horovod/tensorflow/functions.py:47-133``).

``broadcast_variables`` / ``broadcast_object`` are the resume-consistency
primitives (SURVEY §5 checkpoint/resume): after restoring on rank 0, these
make all ranks bit-identical before training resumes.
"""

from __future__ import annotations

import io
import pickle
from typing import Iterable, Optional

import numpy as np
import tensorflow as tf

from .mpi_ops import _np_broadcast, _world, broadcast, size


def broadcast_variables(variables: Iterable[tf.Variable],
                        root_rank: int = 0) -> None:
    """Assign every variable its ``root_rank`` value (parity:
    ``tensorflow/functions.py:47``)."""
    for i, var in enumerate(variables):
        var.assign(broadcast(var, root_rank,
                             name=f"tf.bcast.var.{i}.{var.name}"))


def broadcast_object(obj, root_rank: int = 0,
                     name: Optional[str] = None):
    """Broadcast an arbitrary picklable object (parity:
    ``tensorflow/functions.py:83-133``)."""
    w = _world()
    w.require_init()
    if size() == 1:
        return obj
    name = name or "tf.bcast.obj"
    if w.rank == root_rank:
        payload = pickle.dumps(obj)
        n = np.asarray([len(payload)], np.int64)
    else:
        payload = b""
        n = np.zeros(1, np.int64)
    n = _np_broadcast(n, root_rank, name + ".len")
    buf = np.zeros(int(n[0]), np.uint8)
    if w.rank == root_rank:
        buf[:] = np.frombuffer(payload, np.uint8)
    buf = _np_broadcast(buf, root_rank, name + ".data")
    return pickle.loads(buf.tobytes())


def broadcast_object_fn(root_rank: int = 0, name: Optional[str] = None):
    def _fn(obj):
        return broadcast_object(obj, root_rank=root_rank, name=name)

    return _fn


def allgather_object(obj, name: Optional[str] = None):
    """Gather one picklable object per rank into a list (capability
    extension mirroring later-reference ``allgather_object``)."""
    from .mpi_ops import _np_allgather

    w = _world()
    w.require_init()
    if size() == 1:
        return [obj]
    name = name or "tf.allgather.obj"
    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    gathered = _np_allgather(payload, name)
    sizes = _np_allgather(np.asarray([len(payload)], np.int64),
                          name + ".sizes")
    out, off = [], 0
    for s in sizes.reshape(-1):
        out.append(pickle.loads(gathered[off: off + int(s)].tobytes()))
        off += int(s)
    return out
