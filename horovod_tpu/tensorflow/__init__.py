"""``import horovod_tpu.tensorflow as hvd`` — TensorFlow binding.

Capability parity with the reference's ``horovod/tensorflow/__init__.py``:
``allreduce`` with IndexedSlices and Adasum scaling rules (``:42-121``),
``DistributedOptimizer`` (``:383-444``), ``DistributedGradientTape``
(``:447-504``), ``broadcast_global_variables`` / ``BroadcastGlobalVariables
Hook`` (``:139-200``). The collective transport is the TPU-native host ring
plane (see ``mpi_ops.py``); dense reductions of device-resident JAX arrays
belong on the XLA plane (``horovod_tpu.ops.xla``) instead.
"""

from __future__ import annotations

from typing import Optional

import tensorflow as tf

from ..common.exceptions import HorovodInternalError  # noqa: F401
from .compression import Compression
from .functions import (  # noqa: F401
    allgather_object, broadcast_object, broadcast_object_fn,
    broadcast_variables)
from .mpi_ops import (  # noqa: F401
    Adasum, Average, Max, Min, ReduceOp, Sum, _allreduce, _np_allreduce,
    allgather, barrier, broadcast, ccl_built, cross_rank, cross_size,
    ddl_built, gloo_built, gloo_enabled, init, is_initialized, join,
    local_rank, local_size, mpi_built, mpi_enabled, mpi_threads_supported,
    nccl_built, rank, shutdown, size)


def allreduce(tensor, average=None, device_dense="", device_sparse="",
              compression=Compression.none, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              name: Optional[str] = None):
    """Averaging allreduce with the reference's op semantics
    (``tensorflow/__init__.py:42-121``): IndexedSlices take the
    allgather path; Average divides the summed result by world size;
    Adasum applies the scaling-insensitive combination."""
    op = _handle_average(average, op)
    if isinstance(tensor, tf.IndexedSlices):
        if op == Adasum:
            raise NotImplementedError(
                "Adasum is not supported for IndexedSlices")
        # Parity: sparse gradients are combined by gathering values and
        # indices from all ranks (tensorflow/__init__.py:74-88).
        values = allgather(tensor.values)
        indices = allgather(tensor.indices)
        if op == Average:
            values = values / size()
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    compressed, ctx = compression.compress(tensor)
    if op == Average:
        summed = _allreduce(compressed, name=name, op=Sum,
                            prescale_factor=prescale_factor,
                            postscale_factor=postscale_factor)
        out = summed / size()
    else:
        out = _allreduce(compressed, name=name, op=op,
                         prescale_factor=prescale_factor,
                         postscale_factor=postscale_factor)
    return compression.decompress(out, ctx)


def _handle_average(average, op):
    """Back-compat shim for the deprecated ``average=`` argument (parity:
    ``common/util.py`` handle_average_backwards_compatibility)."""
    if average is not None:
        if op is not None:
            raise ValueError("specify either op or average, not both")
        return Average if average else Sum
    return Average if op is None else op


def broadcast_global_variables(root_rank: int = 0):
    """Broadcast all TF global variables from ``root_rank`` (parity:
    ``tensorflow/__init__.py:139``). In TF2 eager there is no global
    collection; pass explicit variables to ``broadcast_variables``."""
    if tf.executing_eagerly():
        raise RuntimeError(
            "broadcast_global_variables() requires graph mode; use "
            "hvd.broadcast_variables(model.variables) in TF2")
    return tf.group(
        *[tf.compat.v1.assign(v, broadcast(v, root_rank))
          for v in tf.compat.v1.global_variables()])


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """SessionRunHook broadcasting global variables once after session
    creation (parity: ``tensorflow/__init__.py:167-200``)."""

    def __init__(self, root_rank: int, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        self.bcast_op = None
        self.device = device

    def begin(self):
        self.bcast_op = broadcast_global_variables(self.root_rank)

    def after_create_session(self, session, coord):
        session.run(self.bcast_op)


class _DistributedOptimizer(tf.compat.v1.train.Optimizer):
    """v1-optimizer wrapper: allreduce gradients in ``compute_gradients``
    (parity: ``tensorflow/__init__.py:383-444``)."""

    def __init__(self, optimizer, name=None, use_locking=False,
                 device_dense="", device_sparse="",
                 compression=Compression.none, sparse_as_dense=False,
                 op=Average):
        self._optimizer = optimizer
        self._device_dense = device_dense
        self._device_sparse = device_sparse
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._op = op
        super().__init__(name=name or "Distributed{}".format(
            type(optimizer).__name__), use_locking=use_locking)

    def compute_gradients(self, *args, **kwargs):
        gradients = self._optimizer.compute_gradients(*args, **kwargs)
        if size() == 1:
            return gradients
        grads, variables = zip(*gradients)
        averaged = [
            self._maybe_allreduce(g, i) for i, g in enumerate(grads)]
        return list(zip(averaged, variables))

    def _maybe_allreduce(self, grad, idx):
        if grad is None:
            return None
        if self._sparse_as_dense and isinstance(grad, tf.IndexedSlices):
            grad = tf.convert_to_tensor(grad)
        return allreduce(grad, op=self._op, compression=self._compression)

    def apply_gradients(self, *args, **kwargs):
        return self._optimizer.apply_gradients(*args, **kwargs)

    def get_slot(self, *args, **kwargs):
        return self._optimizer.get_slot(*args, **kwargs)

    def get_slot_names(self, *args, **kwargs):
        return self._optimizer.get_slot_names(*args, **kwargs)

    def variables(self, *args, **kwargs):
        return self._optimizer.variables(*args, **kwargs)


def DistributedOptimizer(optimizer, name=None, use_locking=False,
                         device_dense="", device_sparse="",
                         compression=Compression.none,
                         sparse_as_dense=False, op=Average):
    """Wrap a v1 or Keras optimizer so gradients are allreduced before
    applying (parity: ``tensorflow/__init__.py:383-444``)."""
    if isinstance(optimizer, tf.compat.v1.train.Optimizer):
        return _DistributedOptimizer(
            optimizer, name, use_locking, device_dense, device_sparse,
            compression, sparse_as_dense, op)
    try:
        is_keras = isinstance(optimizer, tf.keras.optimizers.Optimizer)
    except AttributeError:
        is_keras = False
    if is_keras:
        from . import keras as _keras_mod

        return _keras_mod.DistributedOptimizer(
            optimizer, compression=compression, sparse_as_dense=sparse_as_dense)
    raise ValueError(
        "DistributedOptimizer expects a tf.compat.v1.train.Optimizer or a "
        "Keras optimizer, got {}".format(type(optimizer)))


class DistributedGradientTape(tf.GradientTape):
    """GradientTape whose ``gradient()`` allreduces the results (parity:
    ``tensorflow/__init__.py:447-504``)."""

    def __new__(cls, tape=None, *args, **kwargs):
        return super().__new__(cls)

    def __init__(self, tape: Optional[tf.GradientTape] = None,
                 device_dense="", device_sparse="",
                 compression=Compression.none, sparse_as_dense=False,
                 op=Average, persistent=False,
                 watch_accessed_variables=True):
        if tape is not None:
            # Adopt the wrapped tape's recording state.
            self.__dict__.update(tape.__dict__)
            self._wrapped = tape
        else:
            super().__init__(persistent=persistent,
                             watch_accessed_variables=watch_accessed_variables)
            self._wrapped = None
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._op = op

    def gradient(self, target, sources, output_gradients=None):
        if self._wrapped is not None:
            gradients = self._wrapped.gradient(target, sources,
                                               output_gradients)
        else:
            gradients = super().gradient(target, sources, output_gradients)
        if size() == 1:
            return gradients
        out = []
        for g in gradients:
            if g is None:
                out.append(None)
                continue
            if self._sparse_as_dense and isinstance(g, tf.IndexedSlices):
                g = tf.convert_to_tensor(g)
            out.append(allreduce(g, op=self._op,
                                 compression=self._compression))
        return out


from . import elastic  # noqa: E402,F401  (exposes hvd.elastic.run / states)
