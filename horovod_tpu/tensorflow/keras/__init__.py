"""``import horovod_tpu.tensorflow.keras as hvd`` (parity:
``horovod/tensorflow/keras/__init__.py``).

Under Keras 3, ``tf.keras`` is ``keras``; this module shares the
``horovod_tpu.keras`` implementation, as the reference shares
``horovod/_keras/``.
"""

from ...keras import (  # noqa: F401
    Adasum, Average, Compression, DistributedOptimizer, Max, Min, ReduceOp,
    Sum, allgather, allgather_object, allreduce, barrier, broadcast,
    broadcast_object, broadcast_object_fn, broadcast_variables, ccl_built,
    cross_rank, cross_size, ddl_built, gloo_built, gloo_enabled, init,
    is_initialized, join, load_model, local_rank, local_size, mpi_built,
    mpi_enabled, mpi_threads_supported, nccl_built, rank, shutdown, size)
from ...keras import callbacks  # noqa: F401
from . import elastic  # noqa: F401
