"""``horovod_tpu.tensorflow.keras.elastic`` (parity:
``horovod/tensorflow/keras/elastic.py``) — shares the Keras-3-unified
implementation in ``horovod_tpu.keras.elastic``."""

from ...keras.elastic import (  # noqa: F401
    CommitStateCallback, KerasState, UpdateBatchStateCallback,
    UpdateEpochStateCallback, run)
