"""Elastic state for TensorFlow / Keras (parity:
``horovod/tensorflow/elastic.py:91-209`` TensorFlowState /
TensorFlowKerasState).
"""

from __future__ import annotations


import numpy as np
import tensorflow as tf

from ..elastic.state import ObjectState, State
from . import mpi_ops as _ops
from .functions import broadcast_object, broadcast_variables


class TensorFlowState(ObjectState):
    """Elastic state over explicit TF2 variables (parity:
    ``tensorflow/elastic.py:91-141``): snapshots variable values in memory
    on ``commit``, broadcasts from the coordinator on ``sync``."""

    def __init__(self, variables=None, **kwargs):
        self.variables = list(variables) if variables is not None else []
        self._saved_values = None
        super().__init__(bcast_object=broadcast_object, **kwargs)

    def _public_attrs(self):
        return {
            k: v
            for k, v in self.__dict__.items()
            if not k.startswith("_") and k != "variables"
        }

    def save(self):
        self._saved_values = [np.array(v.numpy()) for v in self.variables]
        super().save()

    def restore(self):
        if self._saved_values is not None:
            for var, val in zip(self.variables, self._saved_values):
                var.assign(val)
        super().restore()

    def sync(self):
        if self.variables:
            broadcast_variables(self.variables, root_rank=0)
        super().sync()


class TensorFlowKerasState(TensorFlowState):
    """Elastic state for a Keras model + optimizer (parity:
    ``tensorflow/elastic.py:143-209``)."""

    def __init__(self, model, optimizer=None, **kwargs):
        self.model = model
        self.optimizer = optimizer or getattr(model, "optimizer", None)
        variables = list(model.variables)
        if self.optimizer is not None:
            variables += [v for v in self.optimizer.variables
                          if all(v is not mv for mv in model.variables)]
        super().__init__(variables=variables, **kwargs)

    def _public_attrs(self):
        return {
            k: v
            for k, v in self.__dict__.items()
            if not k.startswith("_")
            and k not in ("variables", "model", "optimizer")
        }


def _reinitialize():
    _ops.shutdown()
    _ops.init()


def run(func):
    """Elastic retry loop for TF training functions (parity:
    ``tensorflow/elastic.py:23-60`` + ``common/elastic.py:147-168``). The
    shared guarded loop lives in ``elastic.state.retry_loop``."""
    from ..elastic.state import retry_loop

    return retry_loop(func, _reinitialize)
