"""Gradient compression for TF tensors (parity:
``horovod/tensorflow/compression.py``).

bfloat16 is added as the TPU-native wire format (fp32 exponent range, no
loss-scaling needed); fp16 is kept for reference-script compatibility.
"""

import tensorflow as tf


class Compressor:
    """Interface: ``compress(tensor) -> (tensor, ctx)``,
    ``decompress(tensor, ctx) -> tensor``."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating:
            return tf.cast(tensor, tf.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class BF16Compressor(Compressor):
    """TPU-native extension: bfloat16 wire format."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating:
            return tf.cast(tensor, tf.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class Compression:
    """Option enum (parity: ``Compression.none`` / ``Compression.fp16``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
