"""Gradient compression for TF tensors (parity:
``horovod/tensorflow/compression.py``).

Thin binding over the tree-wide compressor implementation
(``horovod_tpu/common/compression.py``): this module only supplies the
TF cast primitives; the compress/decompress logic — and the wire format
policy (fp16 for reference-script compatibility, bfloat16 as the
TPU-native extension with fp32's exponent range) — lives in one place.
"""

import tensorflow as tf

from ..common.compression import make_framework_compression

_WIRE = {"float16": tf.float16, "bfloat16": tf.bfloat16}

Compression = make_framework_compression(
    cast=lambda tensor, dtype: tf.cast(tensor, _WIRE.get(dtype, dtype)),
    is_floating=lambda tensor: tensor.dtype.is_floating,
)

# Reference-compatible module-level names.
Compressor = Compression.Compressor
NoneCompressor = Compression.none
FP16Compressor = Compression.fp16
BF16Compressor = Compression.bf16
