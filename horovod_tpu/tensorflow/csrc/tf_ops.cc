// Native TensorFlow op kernels over the horovod_tpu runtime.
//
// Parity: the reference's HorovodAllreduceOp / HorovodAllgatherOp /
// HorovodBroadcastOp AsyncOpKernels (tensorflow/mpi_ops.cc:287-466). The
// TF executor drives these kernels directly — no tf.py_function Python hop
// in the data path — and each kernel enqueues into the shared native
// runtime (csrc/hvd), completing the async kernel from the entry's status
// callback exactly as the reference completes its kernels from the
// background thread's StatusCallback.
//
// The runtime library (libhvdtpu.so) is dlopen'ed by path (HVDTPU_LIB env,
// exported by the Python loader) so this extension shares the ctypes-loaded
// copy and its process-global state instead of linking a second instance.

#include <dlfcn.h>

#include <cstdlib>
#include <mutex>
#include <string>
#include <vector>

#include "tensorflow/core/framework/op.h"
#include "tensorflow/core/framework/op_kernel.h"
#include "tensorflow/core/framework/shape_inference.h"

namespace hvdtf {

using tensorflow::AsyncOpKernel;
using tensorflow::DataType;
using tensorflow::OpKernelConstruction;
using tensorflow::OpKernelContext;
using tensorflow::Tensor;
using tensorflow::TensorShape;
using tensorflow::errors::Internal;
using tensorflow::errors::InvalidArgument;

// ---- runtime C API, resolved from the shared libhvdtpu.so ------------------

typedef long long (*EnqueueCbFn)(const char*, int, int, int,
                                 const long long*, int, void*, void*, int,
                                 double, double, int,
                                 void (*)(void*, long long, int,
                                          const char*),
                                 void*);
typedef long long (*ResultBytesFn)(long long);
typedef int (*ResultDimsFn)(long long, long long*, int);
typedef int (*ResultFetchFn)(long long, void*, long long);
typedef int (*IntFn)();

struct Api {
  EnqueueCbFn enqueue_cb = nullptr;
  ResultBytesFn result_bytes = nullptr;
  ResultDimsFn result_dims = nullptr;
  ResultFetchFn result_fetch = nullptr;
  IntFn initialized = nullptr;
  bool ok = false;
};

static Api* api() {
  static Api a;
  static std::once_flag once;
  std::call_once(once, []() {
    const char* path = std::getenv("HVDTPU_LIB");
    if (path == nullptr) return;
    void* h = ::dlopen(path, RTLD_NOW | RTLD_GLOBAL);
    if (h == nullptr) return;
    a.enqueue_cb =
        reinterpret_cast<EnqueueCbFn>(::dlsym(h, "hvd_enqueue_cb"));
    a.result_bytes =
        reinterpret_cast<ResultBytesFn>(::dlsym(h, "hvd_result_bytes"));
    a.result_dims =
        reinterpret_cast<ResultDimsFn>(::dlsym(h, "hvd_result_dims"));
    a.result_fetch =
        reinterpret_cast<ResultFetchFn>(::dlsym(h, "hvd_result_fetch"));
    a.initialized = reinterpret_cast<IntFn>(::dlsym(h, "hvd_initialized"));
    a.ok = a.enqueue_cb && a.result_bytes && a.result_dims &&
           a.result_fetch && a.initialized;
  });
  return &a;
}

// Native op/dtype codes (mirror of common/native.py).
constexpr int kOpAllreduce = 0;
constexpr int kOpAllgather = 1;
constexpr int kOpBroadcast = 2;
constexpr int kPlaneHost = 1;

static int DtypeCode(DataType dt) {
  switch (dt) {
    case tensorflow::DT_UINT8: return 0;
    case tensorflow::DT_INT8: return 1;
    case tensorflow::DT_UINT16: return 2;
    case tensorflow::DT_INT16: return 3;
    case tensorflow::DT_INT32: return 4;
    case tensorflow::DT_INT64: return 5;
    case tensorflow::DT_HALF: return 6;
    case tensorflow::DT_FLOAT: return 7;
    case tensorflow::DT_DOUBLE: return 8;
    case tensorflow::DT_BOOL: return 9;
    case tensorflow::DT_BFLOAT16: return 10;
    default: return -1;
  }
}

// Heap-allocated completion context. The completion callback owns it:
// once hvd_enqueue_cb returns >= 0 the callback fires exactly once (maybe
// before the enqueue returns), so ComputeAsync never touches it after a
// successful enqueue. The collective's handle arrives as a callback
// argument — never read back from this struct — so there is no ordering
// race with the background thread.
struct Completion {
  OpKernelContext* ctx;
  AsyncOpKernel::DoneCallback done;
  bool allgather = false;
  std::vector<long long> tail_dims;  // allgather: dims 1.. of the input
};

static void OnDone(void* arg, long long handle, int ok, const char* err) {
  Completion* c = static_cast<Completion*>(arg);
  if (!ok) {
    c->ctx->SetStatus(Internal(
        "horovod_tpu collective failed: ", err ? err : "unknown error"));
    c->done();
    delete c;
    return;
  }
  if (c->allgather) {
    // Ragged output: size/first-dims arrive with the response (reference
    // MPI_Allgatherv displacement flow); allocate now and copy out.
    Api* a = api();
    long long nbytes = a->result_bytes(handle);
    std::vector<long long> dims(512);
    int nranks = a->result_dims(handle, dims.data(),
                                static_cast<int>(dims.size()));
    if (nranks > static_cast<int>(dims.size())) {
      dims.resize(nranks);
      nranks = a->result_dims(handle, dims.data(),
                              static_cast<int>(dims.size()));
    }
    if (nbytes < 0 || nranks <= 0) {
      c->ctx->SetStatus(Internal("allgather result missing"));
      c->done();
      delete c;
      return;
    }
    long long dim0 = 0;
    for (int i = 0; i < nranks; ++i) dim0 += dims[i];
    TensorShape shape;
    shape.AddDim(dim0);
    for (auto d : c->tail_dims) shape.AddDim(d);
    Tensor* out = nullptr;
    auto st = c->ctx->allocate_output(0, shape, &out);
    if (!st.ok()) {
      c->ctx->SetStatus(st);
      c->done();
      delete c;
      return;
    }
    if (nbytes > 0) {
      a->result_fetch(handle, const_cast<char*>(out->tensor_data().data()),
                      nbytes);
    }
  }
  c->done();
  delete c;
}

static bool Ready(OpKernelContext* ctx, AsyncOpKernel::DoneCallback& done) {
  Api* a = api();
  if (!a->ok) {
    ctx->SetStatus(Internal(
        "horovod_tpu native runtime unavailable (HVDTPU_LIB not set or "
        "symbols missing)"));
    done();
    return false;
  }
  if (!a->initialized()) {
    ctx->SetStatus(Internal(
        "horovod_tpu is not initialized; call hvd.init() first"));
    done();
    return false;
  }
  return true;
}

// ---- HorovodTpuAllreduce ---------------------------------------------------

class AllreduceOp : public AsyncOpKernel {
 public:
  explicit AllreduceOp(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale_factor", &postscale_));
    if (name_.empty()) name_ = name();
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    if (!Ready(ctx, done)) return;
    const Tensor& input = ctx->input(0);
    int code = DtypeCode(input.dtype());
    OP_REQUIRES_ASYNC(ctx, code >= 0,
                      InvalidArgument("unsupported dtype for allreduce"),
                      done);
    Tensor* output = nullptr;
    OP_REQUIRES_OK_ASYNC(
        ctx, ctx->allocate_output(0, input.shape(), &output), done);
    std::vector<long long> dims;
    for (int i = 0; i < input.dims(); ++i) dims.push_back(input.dim_size(i));
    auto* c = new Completion{ctx, done};
    long long h = api()->enqueue_cb(
        name_.c_str(), kOpAllreduce, reduce_op_, code, dims.data(),
        static_cast<int>(dims.size()),
        const_cast<char*>(input.tensor_data().data()),
        const_cast<char*>(output->tensor_data().data()), -1, prescale_,
        postscale_, kPlaneHost, &OnDone, c);
    if (h < 0) {
      // done never fired (enqueue contract): complete + free here.
      ctx->SetStatus(Internal("horovod_tpu runtime is not initialized"));
      done();
      delete c;
    }
  }

 private:
  std::string name_;
  int reduce_op_ = 1;
  float prescale_ = 1.0f;
  float postscale_ = 1.0f;
};

REGISTER_OP("HorovodTpuAllreduce")
    .Attr(
        "T: {uint8, int8, uint16, int16, int32, int64, half, float32, "
        "float64, bool, "
        "bfloat16}")
    .Attr("tensor_name: string = ''")
    .Attr("reduce_op: int = 1")
    .Attr("prescale_factor: float = 1.0")
    .Attr("postscale_factor: float = 1.0")
    .Input("tensor: T")
    .Output("sum: T")
    .SetShapeFn([](tensorflow::shape_inference::InferenceContext* c) {
      c->set_output(0, c->input(0));
      return tensorflow::OkStatus();
    });

REGISTER_KERNEL_BUILDER(
    Name("HorovodTpuAllreduce").Device(tensorflow::DEVICE_CPU), AllreduceOp);

// ---- HorovodTpuAllgather ---------------------------------------------------

class AllgatherOp : public AsyncOpKernel {
 public:
  explicit AllgatherOp(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    if (name_.empty()) name_ = name();
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    if (!Ready(ctx, done)) return;
    const Tensor& input = ctx->input(0);
    int code = DtypeCode(input.dtype());
    OP_REQUIRES_ASYNC(ctx, code >= 0,
                      InvalidArgument("unsupported dtype for allgather"),
                      done);
    OP_REQUIRES_ASYNC(
        ctx, input.dims() >= 1,
        InvalidArgument("allgather requires rank >= 1 tensors"), done);
    std::vector<long long> dims;
    for (int i = 0; i < input.dims(); ++i) dims.push_back(input.dim_size(i));
    auto* c = new Completion{ctx, done};
    c->allgather = true;
    c->tail_dims.assign(dims.begin() + 1, dims.end());
    long long h = api()->enqueue_cb(
        name_.c_str(), kOpAllgather, 1, code, dims.data(),
        static_cast<int>(dims.size()),
        const_cast<char*>(input.tensor_data().data()), nullptr, -1, 1.0,
        1.0, kPlaneHost, &OnDone, c);
    if (h < 0) {
      // done never fired (enqueue contract): complete + free here.
      ctx->SetStatus(Internal("horovod_tpu runtime is not initialized"));
      done();
      delete c;
    }
  }

 private:
  std::string name_;
};

REGISTER_OP("HorovodTpuAllgather")
    .Attr(
        "T: {uint8, int8, uint16, int16, int32, int64, half, float32, "
        "float64, bool, "
        "bfloat16}")
    .Attr("tensor_name: string = ''")
    .Input("tensor: T")
    .Output("gathered: T")
    .SetShapeFn([](tensorflow::shape_inference::InferenceContext* c) {
      tensorflow::shape_inference::ShapeHandle out;
      TF_RETURN_IF_ERROR(c->ReplaceDim(
          c->input(0), 0, c->UnknownDim(), &out));
      c->set_output(0, out);
      return tensorflow::OkStatus();
    });

REGISTER_KERNEL_BUILDER(
    Name("HorovodTpuAllgather").Device(tensorflow::DEVICE_CPU), AllgatherOp);

// ---- HorovodTpuBroadcast ---------------------------------------------------

class BroadcastOp : public AsyncOpKernel {
 public:
  explicit BroadcastOp(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("root_rank", &root_rank_));
    if (name_.empty()) name_ = name();
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    if (!Ready(ctx, done)) return;
    const Tensor& input = ctx->input(0);
    int code = DtypeCode(input.dtype());
    OP_REQUIRES_ASYNC(ctx, code >= 0,
                      InvalidArgument("unsupported dtype for broadcast"),
                      done);
    Tensor* output = nullptr;
    OP_REQUIRES_OK_ASYNC(
        ctx, ctx->allocate_output(0, input.shape(), &output), done);
    std::vector<long long> dims;
    for (int i = 0; i < input.dims(); ++i) dims.push_back(input.dim_size(i));
    // The ring broadcast operates in place on the root's buffer; give every
    // rank its own output copy seeded from the input.
    if (output->tensor_data().data() != input.tensor_data().data()) {
      memcpy(const_cast<char*>(output->tensor_data().data()),
             input.tensor_data().data(), input.TotalBytes());
    }
    auto* c = new Completion{ctx, done};
    long long h = api()->enqueue_cb(
        name_.c_str(), kOpBroadcast, 1, code, dims.data(),
        static_cast<int>(dims.size()),
        const_cast<char*>(output->tensor_data().data()),
        const_cast<char*>(output->tensor_data().data()), root_rank_, 1.0,
        1.0, kPlaneHost, &OnDone, c);
    if (h < 0) {
      // done never fired (enqueue contract): complete + free here.
      ctx->SetStatus(Internal("horovod_tpu runtime is not initialized"));
      done();
      delete c;
    }
  }

 private:
  std::string name_;
  int root_rank_ = 0;
};

REGISTER_OP("HorovodTpuBroadcast")
    .Attr(
        "T: {uint8, int8, uint16, int16, int32, int64, half, float32, "
        "float64, bool, "
        "bfloat16}")
    .Attr("tensor_name: string = ''")
    .Attr("root_rank: int = 0")
    .Input("tensor: T")
    .Output("output: T")
    .SetShapeFn([](tensorflow::shape_inference::InferenceContext* c) {
      c->set_output(0, c->input(0));
      return tensorflow::OkStatus();
    });

REGISTER_KERNEL_BUILDER(
    Name("HorovodTpuBroadcast").Device(tensorflow::DEVICE_CPU), BroadcastOp);

}  // namespace hvdtf
