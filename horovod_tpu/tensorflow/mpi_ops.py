"""TensorFlow binding: collective ops with gradient registration.

Capability parity with the reference's ``horovod/tensorflow/mpi_ops.py:89-197``
(op wrappers + gradients) and the custom-kernel layer
``tensorflow/mpi_ops.cc:287-466``. Architecture:

- **Native kernels (primary)**: real TF AsyncOpKernels
  (``csrc/tf_ops.cc``, built on demand against the installed TF) enqueue
  host-resident TF tensors into the shared native runtime — the controller
  cycle loop, fusion planner, and C++ TCP ring data plane
  (``csrc/hvd/ring_ops.cc``) the PyTorch binding also rides. The TF
  executor drives the kernels directly and completion fires from the
  entry's status callback: no ``tf.py_function`` Python hop in the data
  path, matching the reference's async-kernel design.
- **py_function (fallback)**: when the extension can't build/load (no
  compiler, ``HOROVOD_NATIVE=0``) or the world is single-process, the same
  collectives run through numpy shims under ``tf.py_function`` with
  identical semantics.

Gradients follow the reference's table (allreduce' = allreduce,
allgather' = allreduce + local slice, broadcast' = allreduce with non-root
zeroing), registered both on the raw kernels (``native_ops.py``) and the
``tf.custom_gradient`` wrappers.

Ranks are processes, one per ``horovod_tpu.run``-launched worker, exactly as
in the reference. For TPU-compiled training the idiomatic path remains the
JAX plane (``horovod_tpu.make_train_step`` / ``ops.xla``); the TF binding's
plane is the host ring, as the reference's CPU ops are.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import tensorflow as tf

from ..common import native as _native
from ..common.exceptions import HorovodInternalError
from ..common.host_world import NUMPY_DTYPE_CODES, world as _world
from ..ops.xla import Adasum, Average, Max, Min, ReduceOp, Sum  # noqa: F401
from . import native_ops as _native_ops

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "mpi_threads_supported",
    "mpi_built", "mpi_enabled", "gloo_built", "gloo_enabled", "nccl_built",
    "ddl_built", "ccl_built", "_allreduce", "allgather", "broadcast", "join",
    "barrier", "Average", "Sum", "Adasum", "Min", "Max", "ReduceOp",
]

_name_counter = 0
_name_lock = threading.Lock()


def _auto_name(prefix: str) -> str:
    global _name_counter
    with _name_lock:
        _name_counter += 1
        return f"tf.{prefix}.noname.{_name_counter}"


def init(comm=None):
    """Initialize the process-rank world (parity: ``hvd.init()``)."""
    _world().init(comm=comm)


def shutdown():
    _world().shutdown()


def is_initialized() -> bool:
    return _world().initialized


def rank() -> int:
    _world().require_init()
    return _world().rank


def size() -> int:
    _world().require_init()
    return _world().size


def local_rank() -> int:
    _world().require_init()
    return _world().local_rank


def local_size() -> int:
    _world().require_init()
    return _world().local_size


def cross_rank() -> int:
    _world().require_init()
    return _world().cross_rank


def cross_size() -> int:
    _world().require_init()
    return _world().cross_size


def mpi_threads_supported() -> bool:
    return False


def mpi_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


# ---- numpy-level collectives on the host plane ------------------------------


def _np_code(arr: np.ndarray) -> int:
    code = NUMPY_DTYPE_CODES.get(str(arr.dtype))
    if code is None:
        raise ValueError(f"unsupported dtype {arr.dtype}")
    return code


def _np_allreduce(arr: np.ndarray, name: str, op: int, prescale: float,
                  postscale: float) -> np.ndarray:
    w = _world()
    w.require_init()
    arr = np.asarray(arr, order="C")
    if w.size == 1 or not w.native:
        scale = prescale * (postscale if op not in (Min, Max) else 1.0)
        if scale == 1.0:
            # Exact identity — never round-trip integers through float64.
            return arr.copy()
        return (arr.astype(np.float64) * scale).astype(arr.dtype)
    out = np.empty_like(arr)
    h = w.enqueue(name, _native.OP_ALLREDUCE, op, _np_code(arr), arr.shape,
                  arr.ctypes.data, out.ctypes.data, prescale=prescale,
                  postscale=postscale)
    r, err = w.wait(h)
    if r < 0:
        raise HorovodInternalError(err)
    return out


def _np_allgather(arr: np.ndarray, name: str) -> np.ndarray:
    """Ragged-dim-0 allgather (parity: MPI_Allgatherv semantics,
    ``mpi_operations.cc:140-175``): per-rank sizes ride the response and
    the native ring gathers with displacement math — no size pre-exchange,
    no padding."""
    out, _sizes = _world().allgatherv_np(np.asarray(arr), name)
    return out


def _np_broadcast(arr: np.ndarray, root_rank: int, name: str) -> np.ndarray:
    w = _world()
    w.require_init()
    arr = np.asarray(arr, order="C")
    if w.size == 1 or not w.native:
        return arr.copy()
    return w.broadcast_np(arr, root_rank, name)


# ---- TF op wrappers with gradients ------------------------------------------


def _kernels():
    """The native kernel library when the in-graph path is usable (multi-
    process native world + built extension), else None. Native kernels are
    real TF AsyncOpKernels driven by the TF executor — no py_function
    Python hop in the data path (reference tensorflow/mpi_ops.cc:287-466);
    py_function remains the fallback."""
    w = _world()
    if not (w.initialized and w.native):
        return None
    return _native_ops.load()


def _to_numpy(tensor: tf.Tensor) -> np.ndarray:
    return tensor.numpy() if hasattr(tensor, "numpy") else np.asarray(tensor)


def _wrap(np_fn, tensor: tf.Tensor, same_shape: bool = True) -> tf.Tensor:
    """Run a numpy-collective on a TF tensor, graph-safe. ``same_shape``
    marks shape-preserving collectives (allreduce/broadcast), whose output
    gets the input's runtime shape forced back — py_function materializes
    0-d results as shape [1] otherwise."""
    if tf.executing_eagerly() and not isinstance(tensor, tf.Variable) \
            and not tf.is_symbolic_tensor(tensor):
        return tf.constant(np_fn(_to_numpy(tensor)))
    out = tf.py_function(lambda t: np_fn(t.numpy()), [tensor], tensor.dtype)
    if same_shape:
        out = tf.reshape(out, tf.shape(tensor))
        out.set_shape(tensor.shape)
    return out


def _allreduce(tensor: tf.Tensor, name: Optional[str] = None, op: int = Sum,
               prescale_factor: float = 1.0,
               postscale_factor: float = 1.0) -> tf.Tensor:
    """Raw summing allreduce, no gradient (parity:
    ``tensorflow/mpi_ops.py:89-110`` ``_allreduce``)."""
    name = name or _auto_name("allreduce")
    k = _kernels()
    if k is not None:
        return k.horovod_tpu_allreduce(
            tensor, tensor_name=name, reduce_op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
    return _wrap(
        lambda a: _np_allreduce(a, name, op, prescale_factor,
                                postscale_factor), tensor)


def allgather(tensor: tf.Tensor, name: Optional[str] = None) -> tf.Tensor:
    """Differentiable concat-on-dim-0 allgather (parity:
    ``tensorflow/mpi_ops.py:114-147``). Gradient: allreduce the upstream
    gradient, then take this rank's dim-0 segment."""
    name = name or _auto_name("allgather")
    tensor = tf.convert_to_tensor(tensor)
    if tensor.shape.rank == 0:
        tensor = tf.reshape(tensor, [1])
    dim0 = tf.shape(tensor)[0]

    @tf.custom_gradient
    def _fn(t):
        k = _kernels()
        if k is not None:
            out = k.horovod_tpu_allgather(t, tensor_name=name)
        else:
            out = _wrap(lambda a: _np_allgather(a, name), t,
                        same_shape=False)
        if t.shape.rank is not None and t.shape.rank > 0:
            out.set_shape(tf.TensorShape([None]).concatenate(t.shape[1:]))

        def grad(dy):
            summed = _allreduce(dy, name=name + ".grad", op=Sum)
            dim0v = tf.reshape(tf.cast(dim0, tf.int64), [1])
            if k is not None:
                sizes = k.horovod_tpu_allgather(
                    dim0v, tensor_name=name + ".grad.dim0")
            else:
                sizes = _wrap(
                    lambda a: _np_allgather(a, name + ".grad.dim0"),
                    dim0v, same_shape=False)
            offset = tf.reduce_sum(sizes[: rank()])
            return tf.slice(
                summed, tf.concat(
                    [[tf.cast(offset, tf.int32)],
                     tf.zeros([tf.rank(dy) - 1], tf.int32)], axis=0),
                tf.concat([[tf.cast(dim0, tf.int32)],
                           tf.fill([tf.rank(dy) - 1], -1)], axis=0))

        return out, grad

    return _fn(tensor)


def broadcast(tensor: tf.Tensor, root_rank: int,
              name: Optional[str] = None) -> tf.Tensor:
    """Differentiable broadcast from ``root_rank`` (parity:
    ``tensorflow/mpi_ops.py:150-197``). Gradient: allreduce to root; zero
    elsewhere."""
    name = name or _auto_name("broadcast")
    tensor = tf.convert_to_tensor(tensor)

    @tf.custom_gradient
    def _fn(t):
        k = _kernels()
        if k is not None:
            out = k.horovod_tpu_broadcast(t, tensor_name=name,
                                          root_rank=root_rank)
        else:
            out = _wrap(lambda a: _np_broadcast(a, root_rank, name), t)
        out.set_shape(t.shape)

        def grad(dy):
            summed = _allreduce(dy, name=name + ".grad", op=Sum)
            if rank() == root_rank:
                return summed
            return tf.zeros_like(summed)

        return out, grad

    return _fn(tensor)


def join() -> int:
    """Graceful departure barrier (parity: ``hvd.join()``)."""
    w = _world()
    w.require_init()
    w.barrier("tf.join")
    return w.size - 1


def barrier():
    _world().barrier("tf.barrier")
