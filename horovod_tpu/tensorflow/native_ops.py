"""Loader for the native TF op library (libhvdtf.so).

Parity: the reference's TF binding loads its compiled kernel extension via
``load_library`` (``tensorflow/mpi_ops.py:89``); here the extension is
built on demand against the installed TF (see ``csrc/Makefile``) and
dlopens the shared native runtime so kernels enqueue into the same
controller world the Python API uses. When the build or load fails the TF
binding falls back to the ``tf.py_function`` path transparently.
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional

from ..common import config as _config
from ..common import logging as _log
from ..common import native as _native

_CSRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "lib",
    "libhvdtf.so")

_ops = None
_tried = False


def _build() -> bool:
    """Build the extension under an exclusive file lock with an atomic
    rename, so concurrent ranks on one host never dlopen a half-written
    shared object (the loser of the lock race finds the finished .so)."""
    try:
        import fcntl

        import tensorflow as tf

        env = dict(os.environ)
        env["TF_CFLAGS"] = " ".join(tf.sysconfig.get_compile_flags())
        env["TF_LFLAGS"] = " ".join(tf.sysconfig.get_link_flags())
        os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
        with open(_LIB_PATH + ".lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            if os.path.exists(_LIB_PATH):
                return True
            tmp = _LIB_PATH + f".build.{os.getpid()}"
            subprocess.run(["make", "-C", _CSRC_DIR, f"OUT={tmp}"],
                           check=True, env=env, capture_output=True,
                           timeout=600)
            os.rename(tmp, _LIB_PATH)
        return os.path.exists(_LIB_PATH)
    except Exception as e:
        _log.warning(f"native TF op build failed: {e}")
        return False


def load():
    """Returns the op module (with HorovodTpuAllreduce/Allgather/Broadcast)
    or None when the native path is unavailable."""
    global _ops, _tried
    if _ops is not None or _tried:
        return _ops
    _tried = True
    if not _config.native_enabled():
        return None
    # The kernels resolve the runtime's C API from the ctypes-loaded
    # libhvdtpu.so; export its path so the extension dlopens the same copy.
    if _native.load_library() is None:
        return None
    os.environ.setdefault("HVDTPU_LIB", _native._lib_path())
    if not os.path.exists(_LIB_PATH) and not _build():
        return None
    try:
        import tensorflow as tf

        _ops = tf.load_op_library(_LIB_PATH)
        _register_gradients(_ops)
        _log.debug("native TF op library loaded")
    except Exception as e:
        _log.warning(f"native TF op load failed: {e}")
        _ops = None
    return _ops


def _register_gradients(k) -> None:
    """Gradient table for the raw kernels (parity: the reference's
    RegisterGradient entries, ``tensorflow/mpi_ops.py:89-197``): allreduce'
    = allreduce; allgather' = allreduce + this rank's dim-0 slice;
    broadcast' = allreduce, zeroed off-root. Backward collectives derive
    their names from the forward tensor_name so they stay deterministic
    across ranks."""
    import tensorflow as tf
    from tensorflow.python.framework import ops as tf_framework_ops

    @tf_framework_ops.RegisterGradient("HorovodTpuAllreduce")
    def _allreduce_grad(op, grad):  # noqa: ANN001
        name = op.get_attr("tensor_name").decode() + ".bwd"
        return k.horovod_tpu_allreduce(grad, tensor_name=name, reduce_op=1)

    @tf_framework_ops.RegisterGradient("HorovodTpuAllgather")
    def _allgather_grad(op, grad):  # noqa: ANN001
        name = op.get_attr("tensor_name").decode()
        from .mpi_ops import rank

        summed = k.horovod_tpu_allreduce(grad, tensor_name=name + ".bwd",
                                         reduce_op=1)
        dim0 = tf.shape(op.inputs[0], out_type=tf.int64)[0]
        sizes = k.horovod_tpu_allgather(tf.reshape(dim0, [1]),
                                        tensor_name=name + ".bwd.dim0")
        offset = tf.cast(tf.reduce_sum(sizes[: rank()]), tf.int32)
        n = tf.cast(dim0, tf.int32)
        begin = tf.concat(
            [[offset], tf.zeros([tf.rank(grad) - 1], tf.int32)], axis=0)
        size = tf.concat([[n], tf.fill([tf.rank(grad) - 1], -1)], axis=0)
        return tf.slice(summed, begin, size)

    @tf_framework_ops.RegisterGradient("HorovodTpuBroadcast")
    def _broadcast_grad(op, grad):  # noqa: ANN001
        name = op.get_attr("tensor_name").decode() + ".bwd"
        root = op.get_attr("root_rank")
        from .mpi_ops import rank

        summed = k.horovod_tpu_allreduce(grad, tensor_name=name,
                                         reduce_op=1)
        if rank() == root:
            return summed
        return tf.zeros_like(summed)
