"""DistributedOptimizer for the JAX-native API.

Parity target: ``hvd.DistributedOptimizer`` (reference
``torch/optimizer.py:31-195``, ``tensorflow/__init__.py:383-444``), rebuilt
for the JAX/optax idiom: instead of hooking per-parameter gradient
accumulators, we wrap the optax ``GradientTransformation`` so that
``update()`` allreduces the gradient pytree across the mesh axis before the
inner optimizer sees it. Inside ``jit``/``shard_map`` the allreduce compiles
to a single fused XLA AllReduce per dtype over ICI — tensor fusion falls out
of compilation rather than a background fusion buffer.

``backward_passes_per_step`` (gradient accumulation before communication,
reference ``torch/optimizer.py:46``) is supported via
``optax.MultiSteps``-style accumulation handled by the caller or the
``accumulate`` knob here.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import optax

from .common.state import AXIS_GLOBAL
from .ops import xla as _xla


class DistributedState(NamedTuple):
    inner_state: Any
    accum: Any
    step: Any


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    op: int = _xla.ReduceOp.AVERAGE,
    axis_name: str = AXIS_GLOBAL,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    backward_passes_per_step: int = 1,
    compression=None,
    bucket_cap_bytes="auto",
) -> optax.GradientTransformation:
    """Wrap ``optimizer`` so updates are computed from mesh-reduced grads.

    Must be used inside a program where ``axis_name`` is bound (shard_map /
    pjit over ``hvd.mesh()``); single-device programs may simply not bind
    the axis and pass ``axis_name=None`` to skip communication.

    ``bucket_cap_bytes`` selects tensor-fusion v2 (backward-order bucketed
    AllReduces that overlap backprop, ``common/fusion.py``): an int caps
    each bucket at that many bytes; ``"auto"`` (default) follows
    ``HOROVOD_FUSION_THRESHOLD`` — the same knob that paces the host
    plane's cycle fusion, including its autotuned value — and stays
    monolithic (v1, one AllReduce per dtype) when the knob was never set;
    ``None`` forces monolithic.
    """
    import jax.numpy as jnp

    from .common.fusion import resolve_bucket_cap

    cap = resolve_bucket_cap(bucket_cap_bytes)

    def reduce_grads(grads):
        if axis_name is None:
            return grads
        if compression is not None:
            grads = jax.tree_util.tree_map(compression.compress, grads)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        reduced = _xla.grouped_allreduce(
            leaves, axis_name=axis_name, op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            bucket_cap_bytes=cap,
        )
        out = jax.tree_util.tree_unflatten(treedef, reduced)
        if compression is not None:
            out = jax.tree_util.tree_map(compression.decompress, out)
        return out

    if backward_passes_per_step <= 1:

        def init_fn(params):
            return DistributedState(optimizer.init(params), None, None)

        def update_fn(grads, state, params=None, **extra):
            grads = reduce_grads(grads)
            updates, inner = optimizer.update(grads, state.inner_state, params,
                                              **extra)
            return updates, DistributedState(inner, None, None)

        return optax.GradientTransformation(init_fn, update_fn)

    # Gradient accumulation: communicate only every k-th step (parity:
    # backward_passes_per_step, reference torch/optimizer.py:46,119-135).
    k = backward_passes_per_step

    def init_fn(params):
        accum = jax.tree_util.tree_map(jnp.zeros_like, params)
        return DistributedState(optimizer.init(params), accum,
                                jnp.zeros((), dtype=jnp.int32))

    def update_fn(grads, state, params=None, **extra):
        accum = jax.tree_util.tree_map(lambda a, g: a + g, state.accum, grads)
        step = state.step + 1
        do_comm = step >= k

        def comm_branch(operand):
            accum, inner_state = operand
            mean = jax.tree_util.tree_map(lambda a: a / k, accum)
            reduced = reduce_grads(mean)
            updates, inner = optimizer.update(reduced, inner_state, params,
                                              **extra)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, accum)
            return updates, inner, zeros, jnp.zeros((), dtype=jnp.int32)

        def skip_branch(operand):
            accum, inner_state = operand
            updates = jax.tree_util.tree_map(jnp.zeros_like, accum)
            return updates, inner_state, accum, step

        updates, inner, accum, step = jax.lax.cond(
            do_comm, comm_branch, skip_branch, (accum, state.inner_state))
        return updates, DistributedState(inner, accum, step)

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedGradientTransformation(*args, **kwargs):
    """Alias matching JAX naming conventions."""
    return DistributedOptimizer(*args, **kwargs)
