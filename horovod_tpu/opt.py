"""DistributedOptimizer for the JAX-native API.

Parity target: ``hvd.DistributedOptimizer`` (reference
``torch/optimizer.py:31-195``, ``tensorflow/__init__.py:383-444``), rebuilt
for the JAX/optax idiom: instead of hooking per-parameter gradient
accumulators, we wrap the optax ``GradientTransformation`` so that
``update()`` allreduces the gradient pytree across the mesh axis before the
inner optimizer sees it. Inside ``jit``/``shard_map`` the allreduce compiles
to a single fused XLA AllReduce per dtype over ICI — tensor fusion falls out
of compilation rather than a background fusion buffer.

``backward_passes_per_step`` (gradient accumulation before communication,
reference ``torch/optimizer.py:46``) is supported via
``optax.MultiSteps``-style accumulation handled by the caller or the
``accumulate`` knob here.

This wrapper keeps params, grads, and optimizer state fully replicated —
the right trade when memory is not the constraint. When it is, the ZeRO
plane (``zero.py``, ``HOROVOD_ZERO_STAGE={1,2,3}``) shards state, then
gradients, then parameters 1/d across the mesh while keeping this
module's compression and fusion semantics (docs/zero.md).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import optax

from .common.state import AXIS_GLOBAL
from .ops import xla as _xla


class DistributedState(NamedTuple):
    inner_state: Any
    accum: Any
    step: Any
    # Error-feedback residuals (fp32, one per parameter element) when the
    # compression mode carries error feedback ("ef16"); None otherwise —
    # a None child adds no leaves, so uncompressed states and compiled
    # programs are unchanged by the field's existence.
    residual: Any = None


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    op: int = _xla.ReduceOp.AVERAGE,
    axis_name: str = AXIS_GLOBAL,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    backward_passes_per_step: int = 1,
    compression="auto",
    bucket_cap_bytes="auto",
) -> optax.GradientTransformation:
    """Wrap ``optimizer`` so updates are computed from mesh-reduced grads.

    Must be used inside a program where ``axis_name`` is bound (shard_map /
    pjit over ``hvd.mesh()``); single-device programs may simply not bind
    the axis and pass ``axis_name=None`` to skip communication.

    ``bucket_cap_bytes`` selects tensor-fusion v2 (backward-order bucketed
    AllReduces that overlap backprop, ``common/fusion.py``): an int caps
    each bucket at that many bytes; ``"auto"`` (default) follows
    ``HOROVOD_FUSION_THRESHOLD`` — the same knob that paces the host
    plane's cycle fusion, including its autotuned value — and stays
    monolithic (v1, one AllReduce per dtype) when the knob was never set;
    ``None`` forces monolithic.

    ``compression`` selects the on-wire gradient format
    (``common/compression.py``; docs/compression.md):
    ``hvd.Compression.{none,fp16,bf16,ef16}``, the mode name as a
    string, or ``"auto"`` (default) to follow ``HOROVOD_COMPRESSION`` —
    unset keeps programs byte-identical to the uncompressed path. With
    fp16/bf16 the bucketed AllReduces reduce in the 16-bit wire dtype
    (≈2x fewer wire bytes for fp32 grads) with fp32 post-reduction
    arithmetic; ``ef16`` additionally keeps fp32 residuals in this
    transformation's state (``DistributedState.residual``) so
    quantization error is re-injected next step (error feedback) instead
    of biasing the trajectory. The residual makes the state pytree
    differ from the uncompressed one — init and update must agree on the
    mode (``init_train_state`` / ``make_train_step`` plumb it through).
    """
    import jax.numpy as jnp

    from .common.compression import (apply_error_feedback, init_residual,
                                     resolve_compression)
    from .common.fusion import resolve_bucket_cap

    cap = resolve_bucket_cap(bucket_cap_bytes)
    comp = resolve_compression(compression)
    ef = comp is not None and comp.error_feedback
    wire_comp = comp.inner if ef else comp

    def reduce_grads(grads):
        if axis_name is None:
            return grads
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        reduced = _xla.grouped_allreduce(
            leaves, axis_name=axis_name, op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            bucket_cap_bytes=cap,
            compression=wire_comp,
        )
        return jax.tree_util.tree_unflatten(treedef, reduced)

    def check_residual(state):
        """Fail loudly on an init/update compression mismatch (the ZeRO
        plane's state-owns-the-mode contract, applied here): a residual
        structure mismatch would otherwise surface as an opaque pytree
        error (ef step, plain state) or silently drop the error
        feedback (plain step, ef state)."""
        residual = getattr(state, "residual", None)
        if ef and residual is None:
            raise ValueError(
                "compression mismatch: this DistributedOptimizer was "
                "built with error feedback (ef16) but the optimizer "
                "state carries no residuals. Initialize the state with "
                "the same compression mode (init_train_state(..., "
                "compression='ef16') / DistributedOptimizer(..., "
                "compression='ef16').init).")
        if not ef and residual is not None:
            raise ValueError(
                "compression mismatch: the optimizer state carries "
                "error-feedback residuals but this DistributedOptimizer "
                "was built without error feedback. Build init and "
                "update with the same compression mode.")

    def reduce_grads_ef(grads, residual):
        """(reduced, new_residual): correct with the residual, quantize,
        reduce in the wire dtype, store back the quantization error."""
        if axis_name is None:
            return grads, residual
        wire, new_res = apply_error_feedback(comp, grads, residual)
        reduced = reduce_grads(wire)
        # grouped_allreduce returns each leaf at its (wire) input dtype;
        # hand the inner optimizer gradients at the original dtype.
        reduced = jax.tree_util.tree_map(
            lambda r, g: r.astype(g.dtype), reduced, grads)
        return reduced, new_res

    if backward_passes_per_step <= 1:

        def init_fn(params):
            return DistributedState(optimizer.init(params), None, None,
                                    init_residual(params) if ef else None)

        def update_fn(grads, state, params=None, **extra):
            check_residual(state)
            if ef:
                grads, new_res = reduce_grads_ef(grads, state.residual)
            else:
                grads, new_res = reduce_grads(grads), None
            updates, inner = optimizer.update(grads, state.inner_state, params,
                                              **extra)
            return updates, DistributedState(inner, None, None, new_res)

        return optax.GradientTransformation(init_fn, update_fn)

    # Gradient accumulation: communicate only every k-th step (parity:
    # backward_passes_per_step, reference torch/optimizer.py:46,119-135).
    k = backward_passes_per_step

    def init_fn(params):
        accum = jax.tree_util.tree_map(jnp.zeros_like, params)
        return DistributedState(optimizer.init(params), accum,
                                jnp.zeros((), dtype=jnp.int32),
                                init_residual(params) if ef else None)

    def update_fn(grads, state, params=None, **extra):
        check_residual(state)
        accum = jax.tree_util.tree_map(lambda a, g: a + g, state.accum, grads)
        step = state.step + 1
        do_comm = step >= k

        def comm_branch(operand):
            accum, inner_state, residual = operand
            mean = jax.tree_util.tree_map(lambda a: a / k, accum)
            if ef:
                # Error feedback at communication time: the residual
                # corrects what actually travels the wire (the k-step
                # mean), untouched on skipped micro-steps.
                reduced, new_res = reduce_grads_ef(mean, residual)
            else:
                reduced, new_res = reduce_grads(mean), residual
            updates, inner = optimizer.update(reduced, inner_state, params,
                                              **extra)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, accum)
            return (updates, inner, zeros, jnp.zeros((), dtype=jnp.int32),
                    new_res)

        def skip_branch(operand):
            accum, inner_state, residual = operand
            updates = jax.tree_util.tree_map(jnp.zeros_like, accum)
            return updates, inner_state, accum, step, residual

        updates, inner, accum, step, resid = jax.lax.cond(
            do_comm, comm_branch, skip_branch,
            (accum, state.inner_state, state.residual))
        return updates, DistributedState(inner, accum, step, resid)

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedGradientTransformation(*args, **kwargs):
    """Alias matching JAX naming conventions."""
    return DistributedOptimizer(*args, **kwargs)
