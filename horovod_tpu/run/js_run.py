"""``jsrun`` launcher for LSF clusters (parity: ``horovod/run/js_run.py``).

On an LSF/CSM machine the scheduler owns process placement: instead of
ssh-spawning per slot, the launcher emits one ``jsrun`` invocation with an
explicit resource file (ERF) binding each rank to its host, and jsrun
starts the workers. Workers still rendezvous through the standard
``HOROVOD_*`` env + HTTP rendezvous, so below L5 nothing changes.
"""

from __future__ import annotations

import os
import shlex
import shutil
import tempfile
from typing import Dict, List, Optional

from .common.util import safe_shell_exec
from .util.lsf import LSFUtils


def is_jsrun_installed() -> bool:
    return shutil.which("jsrun") is not None


def generate_jsrun_rankfile(hosts: Dict[str, int],
                            path: Optional[str] = None,
                            num_proc: Optional[int] = None) -> str:
    """Write an explicit resource file mapping each rank to its host
    (parity: ``js_run.py`` ``generate_jsrun_rankfile``; format documented
    by IBM Spectrum LSF ERF).

    One resource set per rank, capped at ``num_proc`` ranks — cpu indices
    are assigned sequentially per host, the reference's layout for one
    process per slot.
    """
    if path is None:
        fd, path = tempfile.mkstemp(suffix=".rankfile", text=True)
        os.close(fd)
    limit = num_proc if num_proc is not None else sum(hosts.values())
    lines = ["overlapping_rs: allow", "cpu_index_using: logical", ""]
    rank = 0
    for host, slots in hosts.items():
        for local in range(slots):
            if rank >= limit:
                break
            lines.append(f"rank: {rank}: {{ hostname: {host}; "
                         f"cpu: {{{local}}} }}")
            rank += 1
    if rank < limit:
        raise ValueError(
            f"hosts provide only {rank} slots, need num_proc={limit}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def build_jsrun_command(num_proc: int, hosts: Dict[str, int],
                        command: List[str], rankfile: Optional[str] = None,
                        output_filename: Optional[str] = None) -> str:
    """The single jsrun invocation string (parity: ``js_run.py:72-90``)."""
    rankfile = rankfile or generate_jsrun_rankfile(hosts, num_proc=num_proc)
    parts = ["jsrun", "--erf_input", rankfile]
    if output_filename:
        parts += ["--stdio_stderr", output_filename,
                  "--stdio_stdout", output_filename]
    parts += command
    return " ".join(shlex.quote(p) for p in parts)


def js_run(num_proc: int, command: List[str],
           hosts: Optional[Dict[str, int]] = None,
           env: Optional[dict] = None,
           output_filename: Optional[str] = None,
           verbose: int = 0) -> int:
    """Launch via jsrun inside an LSF allocation. ``hosts`` is the
    launcher's slot plan (host → slots, rank order); it defaults to the
    full allocation but the runner passes its own plan so the launched
    world always matches HOROVOD_SIZE and the rendezvous plan."""
    if not LSFUtils.using_lsf():
        raise RuntimeError("js_run requires an LSF allocation "
                           "(LSB_JOBID not set)")
    if not is_jsrun_installed():
        raise RuntimeError(
            "jsrun not found; run on an LSF/CSM cluster or use the default "
            "launcher")
    hosts = hosts or LSFUtils.get_compute_hosts()
    cmd = build_jsrun_command(num_proc, hosts, command,
                              output_filename=output_filename)
    if verbose:
        print(cmd)
    return safe_shell_exec.execute(cmd, env=env)
