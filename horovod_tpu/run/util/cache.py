"""On-disk result cache for launch-time discovery (parity:
``horovod/run/util/cache.py`` Cache): NIC probing and host checks are slow
over ssh, so their results are cached under ``~/.horovod`` with a TTL.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Optional


class Cache:
    def __init__(self, cache_folder: str, cache_staleness_threshold_minutes:
                 float, parameters_hash: str = ""):
        os.makedirs(cache_folder, exist_ok=True)
        self._path = os.path.join(cache_folder, "cache.json")
        self._ttl = cache_staleness_threshold_minutes * 60.0
        self._hash = parameters_hash
        self._lock = threading.Lock()
        self._content = {}
        if os.path.isfile(self._path):
            try:
                with open(self._path) as f:
                    stored = json.load(f)
                if stored.get("_hash") == self._hash:
                    self._content = stored.get("entries", {})
            except (ValueError, OSError):
                pass

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            entry = self._content.get(key)
            if entry is None:
                return None
            value, ts = entry
            if time.time() - ts > self._ttl:
                return None
            return value

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            self._content[key] = (value, time.time())
            tmp = self._path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"_hash": self._hash, "entries": self._content}, f)
            os.replace(tmp, self._path)
