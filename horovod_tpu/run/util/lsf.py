"""LSF cluster detection (parity: ``horovod/run/util/lsf.py`` LSFUtils).

The reference queries IBM CSM for the allocation's node list and GPU/core
counts; the portable signal set is the LSF batch environment itself
(``LSB_JOBID``, ``LSB_MCPU_HOSTS``/``LSB_HOSTS``), which this port reads
directly — CSM tooling is absent on TPU pods, and the slot count per host
comes from the allocation string rather than GPU discovery.
"""

from __future__ import annotations

import os
from typing import Dict, List


class LSFUtils:
    """LSF utilities (parity: ``lsf.py`` LSFUtils)."""

    @staticmethod
    def using_lsf() -> bool:
        """True when running inside an LSF allocation
        (parity: ``lsf.py`` ``using_lsf``)."""
        return "LSB_JOBID" in os.environ

    @staticmethod
    def get_compute_hosts() -> Dict[str, int]:
        """Ordered host → slot-count map from the allocation.

        ``LSB_MCPU_HOSTS`` is ``"host1 n1 host2 n2 ..."``; ``LSB_HOSTS``
        repeats each host once per slot. The batch (launch) host keeps its
        allocation entry, matching the reference's rankfile behavior.
        """
        mcpu = os.environ.get("LSB_MCPU_HOSTS", "").split()
        hosts: Dict[str, int] = {}
        if mcpu:
            for i in range(0, len(mcpu) - 1, 2):
                hosts[mcpu[i]] = hosts.get(mcpu[i], 0) + int(mcpu[i + 1])
            return hosts
        for h in os.environ.get("LSB_HOSTS", "").split():
            hosts[h] = hosts.get(h, 0) + 1
        return hosts

    @staticmethod
    def get_num_processes() -> int:
        return sum(LSFUtils.get_compute_hosts().values())

    @staticmethod
    def get_num_hosts() -> int:
        return len(LSFUtils.get_compute_hosts())

    @staticmethod
    def get_hosts_string() -> str:
        """``-H``-style ``host:slots,...`` string for the runner."""
        return ",".join(f"{h}:{n}"
                        for h, n in LSFUtils.get_compute_hosts().items())
