"""Launcher utilities (parity: ``horovod/run/util/``)."""
