"""Thread helpers (parity: ``horovod/run/util/threads.py``)."""

from __future__ import annotations

import threading
from typing import Callable, Optional


def in_thread(target: Callable, args=(), name: Optional[str] = None,
              daemon: bool = True) -> threading.Thread:
    """Run ``target`` on a fresh daemon thread (parity: ``in_thread``)."""
    t = threading.Thread(target=target, args=args, name=name, daemon=daemon)
    t.start()
    return t


def on_event(event: threading.Event, target: Callable, args=(),
             stop: Optional[threading.Event] = None,
             daemon: bool = True) -> threading.Thread:
    """Invoke ``target`` once ``event`` fires, unless ``stop`` fires first
    (parity: ``on_event``)."""

    def waiter():
        while not event.wait(0.1):
            if stop is not None and stop.is_set():
                return
        target(*args)

    return in_thread(waiter, daemon=daemon)
