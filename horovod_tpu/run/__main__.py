"""``python -m horovod_tpu.run`` — the horovodrun-equivalent CLI."""

from .runner import main

if __name__ == "__main__":
    main()
