"""YAML config file → args → env (parity:
``horovod/run/common/util/config_parser.py:55-130`` set_args_from_config
and ``:158+`` set_env_from_args).

Three config layers converge on env vars exactly as in the reference
(SURVEY §5): CLI flags and the YAML file populate the same args namespace;
``set_env_from_args`` exports the HOROVOD_* runtime knobs the background
loop reads at ``hvd.init()``.
"""

from __future__ import annotations

import os
from typing import Optional

from ....common import config as _config

# YAML section/key → args attribute (reference config_parser.py:29-53).
_PARAM_KEYS = {
    "fusion_threshold_mb": "fusion_threshold_mb",
    "cycle_time_ms": "cycle_time_ms",
    "cache_capacity": "cache_capacity",
    "hierarchical_allreduce": "hierarchical_allreduce",
    "hierarchical_allgather": "hierarchical_allgather",
}

_AUTOTUNE_KEYS = {
    "enabled": "autotune",
    "log_file": "autotune_log_file",
    "warmup_samples": "autotune_warmup_samples",
    "steps_per_sample": "autotune_steps_per_sample",
    "bayes_opt_max_samples": "autotune_bayes_opt_max_samples",
    "gaussian_process_noise": "autotune_gaussian_process_noise",
}

_TIMELINE_KEYS = {
    "filename": "timeline_filename",
    "mark_cycles": "timeline_mark_cycles",
}

_STALL_KEYS = {
    "disable": "no_stall_check",
    "warning_time_seconds": "stall_check_warning_time_seconds",
    "shutdown_time_seconds": "stall_check_shutdown_time_seconds",
}

_LOG_KEYS = {
    "level": "log_level",
    "hide_timestamp": "log_hide_timestamp",
}


def set_args_from_config(args, config: dict, override_args: set) -> None:
    """Populate ``args`` from a parsed YAML dict without clobbering flags
    the user passed explicitly (parity: ``config_parser.py:55-130``)."""

    def apply(section: dict, keys: dict):
        for yaml_key, attr in keys.items():
            if yaml_key in section and attr not in override_args:
                setattr(args, attr, section[yaml_key])

    apply(config.get("params", {}), _PARAM_KEYS)
    apply(config.get("autotune", {}), _AUTOTUNE_KEYS)
    apply(config.get("timeline", {}), _TIMELINE_KEYS)
    apply(config.get("stall_check", {}), _STALL_KEYS)
    apply(config.get("logging", {}), _LOG_KEYS)


def _set(env: dict, name: str, value) -> None:
    # Tri-state booleans: None = unset (leave ambient env alone),
    # True/False = user-forced — an explicit False (the --no-* negations)
    # must export "0" so it overrides an ambient HOROVOD_*=1.
    if value is None:
        return
    if isinstance(value, bool):
        env[name] = "1" if value else "0"
        return
    env[name] = str(value)


def set_env_from_args(env: dict, args) -> dict:
    """Export runtime knobs from args to env (parity:
    ``config_parser.py:158+``)."""
    if getattr(args, "fusion_threshold_mb", None) is not None:
        env[_config.HOROVOD_FUSION_THRESHOLD] = str(
            int(args.fusion_threshold_mb) * 1024 * 1024)
    _set(env, _config.HOROVOD_CYCLE_TIME,
         getattr(args, "cycle_time_ms", None))
    _set(env, _config.HOROVOD_CACHE_CAPACITY,
         getattr(args, "cache_capacity", None))
    _set(env, _config.HOROVOD_HIERARCHICAL_ALLREDUCE,
         getattr(args, "hierarchical_allreduce", None))
    _set(env, _config.HOROVOD_HIERARCHICAL_ALLGATHER,
         getattr(args, "hierarchical_allgather", None))
    _set(env, _config.HOROVOD_AUTOTUNE, getattr(args, "autotune", None))
    _set(env, _config.HOROVOD_AUTOTUNE_LOG,
         getattr(args, "autotune_log_file", None))
    _set(env, _config.HOROVOD_AUTOTUNE_WARMUP_SAMPLES,
         getattr(args, "autotune_warmup_samples", None))
    _set(env, _config.HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE,
         getattr(args, "autotune_steps_per_sample", None))
    _set(env, _config.HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES,
         getattr(args, "autotune_bayes_opt_max_samples", None))
    _set(env, _config.HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE,
         getattr(args, "autotune_gaussian_process_noise", None))
    _set(env, _config.HOROVOD_TIMELINE,
         getattr(args, "timeline_filename", None))
    _set(env, _config.HOROVOD_TIMELINE_MARK_CYCLES,
         getattr(args, "timeline_mark_cycles", None))
    _set(env, _config.HOROVOD_STALL_CHECK_DISABLE,
         getattr(args, "no_stall_check", None))
    _set(env, _config.HOROVOD_STALL_CHECK_TIME_SECONDS,
         getattr(args, "stall_check_warning_time_seconds", None))
    _set(env, _config.HOROVOD_STALL_SHUTDOWN_TIME_SECONDS,
         getattr(args, "stall_check_shutdown_time_seconds", None))
    _set(env, _config.HOROVOD_LOG_LEVEL, getattr(args, "log_level", None))
    _set(env, _config.HOROVOD_LOG_HIDE_TIME,
         getattr(args, "log_hide_timestamp", None))
    return env


def load_config_file(args, override_args: set) -> None:
    """Read ``args.config_file`` (YAML) into args (parity:
    ``runner.py`` config-file handling)."""
    path: Optional[str] = getattr(args, "config_file", None)
    if not path:
        return
    if not os.path.exists(path):
        raise FileNotFoundError(f"config file not found: {path}")
    import yaml

    with open(path) as f:
        config = yaml.safe_load(f) or {}
    set_args_from_config(args, config, override_args)
