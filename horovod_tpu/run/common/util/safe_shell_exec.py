"""Subprocess execution with whole-process-group cleanup (parity:
``horovod/run/common/util/safe_shell_exec.py:160``).

Workers are launched in their own process group (session) so that killing a
worker also kills anything it spawned; stdout/stderr are pumped to the
caller's streams (or files) by daemon threads; an optional ``events`` list
of ``threading.Event``s triggers termination (the elastic driver uses this
to tear down workers on host changes).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import List, Optional

GRACEFUL_TERMINATION_TIME_S = 5

# After the worker exits, how long to keep draining its output pipes.
# EOF arrives as soon as the (dead) worker's buffered output is consumed;
# the bound only matters when a surviving grandchild inherited the pipe,
# where waiting forever would hang the launcher. Long enough that a
# final burst (a traceback after MBs of logs) is never truncated.
PUMP_DRAIN_TIME_S = 10


def terminate_executor_shell_and_children(pid: int) -> None:
    """SIGTERM the process group, then SIGKILL stragglers (parity:
    ``safe_shell_exec.py:47-72``)."""
    try:
        pgid = os.getpgid(pid)
    except OSError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except OSError:
        pass
    deadline = time.time() + GRACEFUL_TERMINATION_TIME_S
    while time.time() < deadline:
        try:
            os.killpg(pgid, 0)
        except OSError:
            return  # group is gone
        # hvdlint: ignore[retry-discipline] -- SIGTERM->SIGKILL grace
        # poll on a process group, not a retry: fixed cadence against a
        # hard deadline, nothing to back off from
        time.sleep(0.1)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except OSError:
        pass


def _pump(src, dst, prefix: Optional[str] = None) -> threading.Thread:
    # After the drain deadline the caller may close ``dst`` (e.g. the
    # per-rank log files in launch.execute_redirected) while a grandchild
    # still holds the pipe open. ``stop`` tells the pump to discard any
    # late lines instead of writing into a closed sink.
    stop = threading.Event()

    def run():
        try:
            for line in iter(src.readline, b""):
                if stop.is_set():
                    continue  # keep reading so the grandchild never blocks
                text = line.decode("utf-8", errors="replace")
                if prefix:
                    text = f"[{prefix}]{text}" if text.strip() else text
                try:
                    dst.write(text)
                    dst.flush()
                except ValueError:
                    stop.set()  # sink closed under us: drop the tail
        except ValueError:
            pass  # source pipe closed

    t = threading.Thread(target=run, daemon=True)
    t.stop = stop
    t.start()
    return t


def execute(command, env: Optional[dict] = None,
            stdout=None, stderr=None,
            events: Optional[List[threading.Event]] = None,
            prefix: Optional[str] = None) -> int:
    """Run ``command`` (shell string or argv list) in its own process
    group; return its exit code. Any event in ``events`` firing terminates
    the whole group (parity: ``safe_shell_exec.py:160``)."""
    shell = isinstance(command, str)
    proc = subprocess.Popen(
        command, shell=shell, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)

    pumps = [
        _pump(proc.stdout, stdout or sys.stdout, prefix),
        _pump(proc.stderr, stderr or sys.stderr, prefix),
    ]

    stop_watch = threading.Event()
    watchers = []
    for ev in events or []:
        def watch(e=ev):
            while not stop_watch.is_set():
                if e.wait(0.1):
                    terminate_executor_shell_and_children(proc.pid)
                    return
        t = threading.Thread(target=watch, daemon=True)
        t.start()
        watchers.append(t)

    try:
        exit_code = proc.wait()
    except BaseException:
        # Parent interrupt (KeyboardInterrupt in the launcher, SystemExit,
        # a test runner's timeout): the worker's whole process group must
        # die with us — the launcher-side analog of worker death. Without
        # this, Ctrl-C on the launcher orphans every worker (and its
        # grandchildren) into init's lap, still holding ports and TPU
        # devices.
        terminate_executor_shell_and_children(proc.pid)
        raise
    finally:
        stop_watch.set()
        # Drain fully before the caller closes its streams: a short join
        # here would let redirected log files close mid-pump and silently
        # truncate the tail (often the crash traceback itself). One shared
        # deadline bounds the TOTAL stall when a surviving grandchild
        # holds both pipes open.
        deadline = time.time() + PUMP_DRAIN_TIME_S
        for t in pumps:
            t.join(timeout=max(0.0, deadline - time.time()))
        for t in pumps:
            # Pumps that out-lived the drain deadline must not write into
            # streams the caller is about to close.
            t.stop.set()
    return exit_code
