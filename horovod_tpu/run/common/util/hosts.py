"""Host list parsing and slot assignment (parity:
``horovod/run/common/util/hosts.py``).

``parse_hosts("a:4,b:2")`` → HostInfo list; ``get_host_assignments`` packs
``np`` ranks onto hosts in order, computing rank / local_rank / cross_rank
exactly as the reference (``hosts.py:72``): ranks fill hosts sequentially,
local_rank counts within a host, cross_rank is the index of the host among
hosts that have a slot at that local_rank.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List


@dataclass
class HostInfo:
    hostname: str
    slots: int

    @staticmethod
    def from_string(host_string: str) -> "HostInfo":
        if ":" in host_string:
            hostname, slots = host_string.rsplit(":", 1)
            return HostInfo(hostname, int(slots))
        return HostInfo(host_string, 1)


@dataclass
class SlotInfo:
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int

    def to_response_string(self) -> str:
        return ",".join(
            str(v) for v in (self.rank, self.size, self.local_rank,
                             self.local_size, self.cross_rank,
                             self.cross_size))


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """``"a:4,b:2"`` → ``[HostInfo(a,4), HostInfo(b,2)]`` (parity:
    ``hosts.py:62``)."""
    return [HostInfo.from_string(s) for s in hosts_string.split(",") if s]


def parse_host_files(filename: str) -> str:
    """Hostfile (``host slots=N`` per line, mpirun-style) → hosts string
    (parity: ``runner.py`` hostfile handling)."""
    hosts = []
    with open(filename) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=", 1)[1])
            hosts.append(f"{parts[0]}:{slots}")
    return ",".join(hosts)


def get_host_assignments(hosts: List[HostInfo], min_np: int,
                         max_np: int = None) -> List[SlotInfo]:
    """Pack ranks onto hosts in order (parity: ``hosts.py:72``).

    Raises ValueError when fewer than ``min_np`` slots are available; caps
    at ``max_np`` when given.
    """
    total_slots = sum(h.slots for h in hosts)
    if total_slots < min_np:
        raise ValueError(
            f"requested {min_np} processes but only {total_slots} slots "
            f"available on {len(hosts)} hosts")
    np_ = min(total_slots, max_np) if max_np else min_np
    assignments: List[SlotInfo] = []
    rank = 0
    for cross0, host in enumerate(hosts):
        for local_rank in range(host.slots):
            if rank >= np_:
                break
            assignments.append(SlotInfo(
                hostname=host.hostname, rank=rank, local_rank=local_rank,
                cross_rank=0, size=np_, local_size=0, cross_size=0))
            rank += 1
    # Fill in local_size / cross_rank / cross_size from the final packing.
    by_host = {}
    for a in assignments:
        by_host.setdefault(a.hostname, []).append(a)
    host_order = [h.hostname for h in hosts if h.hostname in by_host]
    for a in assignments:
        a.local_size = len(by_host[a.hostname])
        peers = [h for h in host_order
                 if len(by_host[h]) > a.local_rank]
        a.cross_rank = peers.index(a.hostname)
        a.cross_size = len(peers)
    return assignments
