"""Shared-secret generation + HMAC signing (parity:
``horovod/run/common/util/secret.py``): every launcher service message is
authenticated with a per-job random key so a stray connection can't inject
commands into the control plane.
"""

import hashlib
import hmac
import os

DIGEST_LENGTH_BYTES = 32


def make_secret_key() -> bytes:
    return os.urandom(32)


def compute_digest(secret_key: bytes, message_bytes: bytes) -> bytes:
    return hmac.new(secret_key, message_bytes, hashlib.sha256).digest()


def check_digest(secret_key: bytes, message_bytes: bytes,
                 digest: bytes) -> bool:
    expected = compute_digest(secret_key, message_bytes)
    return hmac.compare_digest(expected, digest)
