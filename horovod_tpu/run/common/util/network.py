"""Pickle-over-TCP request/response services with HMAC authentication
(parity: ``horovod/run/common/util/network.py``).

``BasicService`` accepts length-prefixed, HMAC-signed pickled requests and
dispatches them to ``_handle``; ``BasicClient`` connects, sends one request,
reads one response. The launcher's driver/task services, the worker
notification plane, and the elastic rendezvous all ride this protocol.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, List, Optional, Tuple

from . import secret

_LEN = struct.Struct("!I")


class PingRequest:
    pass


class PingResponse:
    def __init__(self, service_name: str, source_address: str):
        self.service_name = service_name
        self.source_address = source_address


class AckResponse:
    pass


def _send_frame(sock: socket.socket, obj: Any, key: bytes) -> None:
    payload = pickle.dumps(obj)
    digest = secret.compute_digest(key, payload)
    sock.sendall(_LEN.pack(len(payload)) + digest + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed mid-frame")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket, key: bytes) -> Any:
    n = _LEN.unpack(_recv_exact(sock, _LEN.size))[0]
    digest = _recv_exact(sock, secret.DIGEST_LENGTH_BYTES)
    payload = _recv_exact(sock, n)
    if not secret.check_digest(key, payload, digest):
        raise PermissionError("HMAC digest mismatch — unauthenticated peer")
    return pickle.loads(payload)


def get_local_addresses() -> List[Tuple[str, str]]:
    """(interface_name, ipv4) for every up interface with an address —
    ioctl(SIOCGIFADDR) per kernel interface, no third-party deps (the role
    psutil's net_if_addrs plays in the reference)."""
    import array
    import fcntl

    out: List[Tuple[str, str]] = []
    try:
        names = socket.if_nameindex()
    except OSError:
        return out
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        for _, name in names:
            ifreq = array.array(
                "B", name.encode()[:15] + b"\0" * (32 - min(len(name), 15)))
            try:
                fcntl.ioctl(s.fileno(), 0x8915, ifreq)  # SIOCGIFADDR
            except OSError:
                continue
            ip = socket.inet_ntoa(bytes(ifreq[20:24]))
            out.append((name, ip))
    finally:
        s.close()
    return out


class BasicService:
    """Threaded TCP service dispatching authenticated pickled requests."""

    def __init__(self, service_name: str, key: bytes, nics=None):
        self._service_name = service_name
        self._key = key
        self._nics = nics
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    req = _recv_frame(self.request, outer._key)
                except (PermissionError, ConnectionError, EOFError):
                    return
                peer = self.request.getpeername()[0]
                resp = outer._handle(req, peer)
                try:
                    _send_frame(self.request, resp, outer._key)
                except OSError:
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server(("0.0.0.0", 0), _Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"svc-{service_name}")
        self._thread.start()

    def _handle(self, req: Any, client_address: str) -> Any:
        if isinstance(req, PingRequest):
            return PingResponse(self._service_name, client_address)
        raise NotImplementedError(
            f"{self._service_name}: unknown request {type(req)}")

    @property
    def port(self) -> int:
        return self._port

    def addresses(self) -> List[Tuple[str, int]]:
        """All (ip, port) pairs this service is reachable at — one per
        local interface (the reference advertises per-NIC addresses so the
        driver's routability probe can intersect them,
        ``run/common/service/driver_service.py:43``). Restricted to
        ``nics`` when the caller passed an allowlist."""
        addrs = [("127.0.0.1", self._port)]
        for name, ip in get_local_addresses():
            if self._nics and name not in self._nics:
                continue
            if all(ip != a for a, _ in addrs):
                addrs.append((ip, self._port))
        if len(addrs) == 1 and not self._nics:
            # Hostname fallback only without an allowlist: appending the
            # resolver's pick under nics={...} would advertise exactly the
            # interface the operator excluded.
            try:
                hostname_ip = socket.gethostbyname(socket.gethostname())
                if hostname_ip != "127.0.0.1":
                    addrs.append((hostname_ip, self._port))
            except OSError:
                pass
        return addrs

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)


class BasicClient:
    """One-shot request client for a BasicService."""

    def __init__(self, service_name: str,
                 addresses: List[Tuple[str, int]], key: bytes,
                 match_intf: bool = False,
                 probe_timeout: float = 5.0, attempts: int = 3):
        self._service_name = service_name
        self._key = key
        self._timeout = probe_timeout
        self._attempts = attempts
        self._address: Optional[Tuple[str, int]] = None
        last_err: Optional[Exception] = None
        for addr in addresses:
            try:
                resp = self._request_to(addr, PingRequest())
                if isinstance(resp, PingResponse) and \
                        resp.service_name == service_name:
                    self._address = addr
                    break
            except (OSError, PermissionError, ConnectionError) as e:
                last_err = e
        if self._address is None:
            raise ConnectionError(
                f"could not reach service '{service_name}' at any of "
                f"{addresses}: {last_err}")

    def _request_to(self, addr: Tuple[str, int], req: Any) -> Any:
        with socket.create_connection(addr, timeout=self._timeout) as sock:
            _send_frame(sock, req, self._key)
            return _recv_frame(sock, self._key)

    def _request(self, req: Any) -> Any:
        last_err: Optional[Exception] = None
        for _ in range(self._attempts):
            try:
                return self._request_to(self._address, req)
            except (OSError, ConnectionError) as e:
                last_err = e
        raise ConnectionError(
            f"service '{self._service_name}' at {self._address} "
            f"unreachable: {last_err}")

    def ping(self) -> bool:
        return isinstance(self._request(PingRequest()), PingResponse)
