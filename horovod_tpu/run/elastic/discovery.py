"""Host discovery for elastic training (parity:
``horovod/run/elastic/discovery.py``).

``HostDiscoveryScript`` shells out to the user's discovery script (printing
``hostname`` or ``hostname:slots`` per line); ``HostManager`` tracks the
available host set with **age ordering** — hosts keep their discovery order
across updates, so rank assignment stays stable and rank 0 lives on the
oldest host (``discovery.py:113-121``) — plus strike-counted blacklisting
with cooldown + parole (docs/fault-injection.md):

- each failure is a **strike**; below ``HOROVOD_ELASTIC_BLACKLIST_STRIKES``
  strikes (and given a ``cooldown_range``) the host sits out a randomized
  cooldown, then returns **on parole**;
- a host that runs clean through ``HOROVOD_ELASTIC_PAROLE_WINDOW`` seconds
  of parole has its strikes reset (transient faults don't accumulate into
  a death sentence);
- at ``N`` strikes — or when no cooldown range was configured — the
  blacklist is permanent and the host is never re-invited.
"""

from __future__ import annotations

import random
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ...common import config as _config
from ...common import logging as _log


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """Return {hostname: slots} currently available."""
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Run the user script; each output line is ``host`` or ``host:slots``
    (parity: ``discovery.py:42-60``)."""

    def __init__(self, discovery_script: str, slots: Optional[int] = None):
        self._script = discovery_script
        self._default_slots = slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(
            self._script, shell=True, text=True,
            stderr=subprocess.DEVNULL)
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                host, slots = line.rsplit(":", 1)
                hosts[host] = int(slots)
            else:
                if self._default_slots is None:
                    raise ValueError(
                        f"discovery script printed '{line}' without slots; "
                        "pass --slots-per-host")
                hosts[line] = self._default_slots
        return hosts


class FixedHosts(HostDiscovery):
    """Static host set (used when elastic mode runs with -H)."""

    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def set(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Tracks available hosts in age order + strike-counted blacklist
    with cooldown/parole (parity: ``discovery.py:62-121``, extended per
    the module docstring). ``clock`` is injectable so strike/parole logic
    is testable with zero real sleeping."""

    def __init__(self, discovery: HostDiscovery,
                 cooldown_range: Optional[Tuple[int, int]] = None,
                 max_strikes: Optional[int] = None,
                 parole_window: Optional[float] = None,
                 clock: Callable[[], float] = time.time):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._order: List[str] = []  # discovery age order, oldest first
        self._slots: Dict[str, int] = {}
        self._blacklist: Dict[str, float] = {}  # host -> retry-after ts
        self._cooldown_range = cooldown_range
        self._max_strikes = (max_strikes if max_strikes is not None
                             else _config.blacklist_strikes())
        self._parole_window = (parole_window if parole_window is not None
                               else _config.parole_window_seconds())
        self._clock = clock
        self._strikes: Dict[str, int] = {}
        self._parole_until: Dict[str, float] = {}
        self._events: List[dict] = []  # blacklist history, queryable
        self._on_blacklist: Optional[Callable[[str, dict], None]] = None

    def set_on_blacklist(self, cb: Optional[Callable[[str, dict], None]]
                         ) -> None:
        """Observer for blacklist decisions (the driver wires timeline +
        log recording here)."""
        self._on_blacklist = cb

    def update_available_hosts(self) -> bool:
        """Poll discovery; True when the usable host set changed (parity:
        ``HostManager.update_available_hosts``)."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            now = self._clock()
            # Cooldown expiry → parole: the host may rejoin, but its
            # strikes stand until it runs clean through the parole window.
            for h in list(self._blacklist):
                if self._blacklist[h] <= now and h in found:
                    del self._blacklist[h]
                    if self._parole_window > 0:
                        self._parole_until[h] = now + self._parole_window
                    _log.info(
                        f"elastic: host {h} returns from blacklist "
                        f"cooldown on parole "
                        f"(strikes {self._strikes.get(h, 0)}/"
                        f"{self._max_strikes})")
            # Clean parole served → strikes forgiven.
            for h in list(self._parole_until):
                if self._parole_until[h] <= now:
                    del self._parole_until[h]
                    if self._strikes.pop(h, 0):
                        _log.info(f"elastic: host {h} served its parole "
                                  f"cleanly; strikes reset")
            usable = {
                h: s for h, s in found.items()
                if self._blacklist.get(h, 0.0) <= now
            }
            prev = {h: self._slots[h] for h in self._order}
            # Age order: keep existing hosts' positions, append new ones.
            self._order = [h for h in self._order if h in usable] + \
                [h for h in found if h in usable and h not in self._order]
            self._slots = usable
            current = {h: self._slots[h] for h in self._order}
            return current != prev

    @property
    def current_hosts(self) -> List[Tuple[str, int]]:
        with self._lock:
            return [(h, self._slots[h]) for h in self._order]

    def available_slots(self) -> int:
        with self._lock:
            return sum(self._slots[h] for h in self._order)

    def blacklist(self, host: str) -> None:
        """Record a strike against ``host`` and exclude it. Below the
        strike limit (and given a cooldown range) the exclusion is a
        randomized cooldown; at the limit — or with no cooldown range —
        it is permanent (parity: ``discovery.py:102-108``, extended with
        strike counting). One *incident* is one strike: a host running N
        workers fans N ``record_failure`` calls into here when it dies,
        and calls arriving while the host is already excluded are that
        same incident, not N separate offenses — without the dedupe a
        3-slot host would go permanent on its first crash."""
        with self._lock:
            if self._blacklist.get(host, 0.0) > self._clock():
                return  # already excluded: same incident's fan-in
            strikes = self._strikes.get(host, 0) + 1
            self._strikes[host] = strikes
            # A failure during parole ends the parole; the host must
            # re-earn a clean window after its next return.
            self._parole_until.pop(host, None)
            permanent = (not self._cooldown_range
                         or strikes >= self._max_strikes)
            if permanent:
                until = float("inf")
            else:
                lo, hi = self._cooldown_range
                until = self._clock() + random.uniform(lo, hi)
            self._blacklist[host] = until
            self._order = [h for h in self._order if h != host]
            self._slots.pop(host, None)
            info = {
                "host": host, "strikes": strikes,
                "max_strikes": self._max_strikes, "permanent": permanent,
                "until": until, "ts": self._clock(),
            }
            self._events.append(info)
            cb = self._on_blacklist
        cooldown = ("permanent" if permanent
                    else f"cooldown until t={until:.1f}")
        _log.warning(f"elastic: host {host} blacklisted "
                     f"(strike {strikes}/{self._max_strikes}, {cooldown})")
        if cb is not None:
            cb(host, dict(info))

    # Exclusion window for a drained (preempted) host: long enough that
    # the driver never respawns onto a VM mid-teardown, short enough that
    # a reborn host under the same name (autoscaler replacement) gets
    # re-invited without operator action.
    DRAIN_QUARANTINE_SECONDS = 300.0

    def quarantine(self, host: str,
                   seconds: Optional[float] = None) -> None:
        """Exclude ``host`` WITHOUT a strike (docs/liveness.md): a
        graceful preemption drain is the platform reclaiming the VM, not
        the host misbehaving — it must not march toward a permanent
        blacklist, and parole state is untouched. The exclusion shares
        the cooldown bookkeeping so rank assignment and slot counting
        treat it exactly like any other excluded host."""
        if seconds is None:
            seconds = self.DRAIN_QUARANTINE_SECONDS
        with self._lock:
            if self._blacklist.get(host, 0.0) > self._clock():
                return  # already excluded
            until = self._clock() + seconds
            self._blacklist[host] = until
            self._order = [h for h in self._order if h != host]
            self._slots.pop(host, None)
            info = {
                "host": host, "strikes": self._strikes.get(host, 0),
                "max_strikes": self._max_strikes, "permanent": False,
                "until": until, "ts": self._clock(), "drained": True,
            }
            self._events.append(info)
        _log.info(f"elastic: host {host} drained; quarantined for "
                  f"{seconds:.0f}s with zero strikes")

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            return self._blacklist.get(host, 0.0) > self._clock()

    def blacklist_info(self) -> Dict[str, dict]:
        """Queryable blacklist state: ``{host: {strikes, until, permanent,
        on_parole}}`` for every host with strikes or an active exclusion."""
        with self._lock:
            now = self._clock()
            hosts = set(self._strikes) | set(self._blacklist) | \
                set(self._parole_until)
            return {
                h: {
                    "strikes": self._strikes.get(h, 0),
                    "until": self._blacklist.get(h, 0.0),
                    "permanent": self._blacklist.get(h, 0.0) == float("inf"),
                    "blacklisted": self._blacklist.get(h, 0.0) > now,
                    "on_parole": self._parole_until.get(h, 0.0) > now,
                }
                for h in sorted(hosts)
            }

    def blacklist_events(self) -> List[dict]:
        """The append-only history of blacklist decisions."""
        with self._lock:
            return [dict(e) for e in self._events]

    def has_recoverable_hosts(self) -> bool:
        """True when some excluded host can still return (finite
        cooldown) — i.e. waiting for slots is not provably futile."""
        with self._lock:
            return any(t != float("inf") for t in self._blacklist.values())
