"""Host discovery for elastic training (parity:
``horovod/run/elastic/discovery.py``).

``HostDiscoveryScript`` shells out to the user's discovery script (printing
``hostname`` or ``hostname:slots`` per line); ``HostManager`` tracks the
available host set with **age ordering** — hosts keep their discovery order
across updates, so rank assignment stays stable and rank 0 lives on the
oldest host (``discovery.py:113-121``) — plus blacklisting with cooldown.
"""

from __future__ import annotations

import random
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """Return {hostname: slots} currently available."""
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Run the user script; each output line is ``host`` or ``host:slots``
    (parity: ``discovery.py:42-60``)."""

    def __init__(self, discovery_script: str, slots: Optional[int] = None):
        self._script = discovery_script
        self._default_slots = slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(
            self._script, shell=True, text=True,
            stderr=subprocess.DEVNULL)
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                host, slots = line.rsplit(":", 1)
                hosts[host] = int(slots)
            else:
                if self._default_slots is None:
                    raise ValueError(
                        f"discovery script printed '{line}' without slots; "
                        "pass --slots-per-host")
                hosts[line] = self._default_slots
        return hosts


class FixedHosts(HostDiscovery):
    """Static host set (used when elastic mode runs with -H)."""

    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def set(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Tracks available hosts in age order + blacklist (parity:
    ``discovery.py:62-121``)."""

    def __init__(self, discovery: HostDiscovery,
                 cooldown_range: Optional[Tuple[int, int]] = None):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._order: List[str] = []  # discovery age order, oldest first
        self._slots: Dict[str, int] = {}
        self._blacklist: Dict[str, float] = {}  # host -> retry-after ts
        self._cooldown_range = cooldown_range

    def update_available_hosts(self) -> bool:
        """Poll discovery; True when the usable host set changed (parity:
        ``HostManager.update_available_hosts``)."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            now = time.time()
            usable = {
                h: s for h, s in found.items()
                if self._blacklist.get(h, 0.0) <= now
            }
            prev = {h: self._slots[h] for h in self._order}
            # Age order: keep existing hosts' positions, append new ones.
            self._order = [h for h in self._order if h in usable] + \
                [h for h in found if h in usable and h not in self._order]
            self._slots = usable
            current = {h: self._slots[h] for h in self._order}
            return current != prev

    @property
    def current_hosts(self) -> List[Tuple[str, int]]:
        with self._lock:
            return [(h, self._slots[h]) for h in self._order]

    def available_slots(self) -> int:
        with self._lock:
            return sum(self._slots[h] for h in self._order)

    def blacklist(self, host: str) -> None:
        """Exclude a failing host; with a cooldown range it may return
        after a randomized backoff (parity: ``discovery.py:102-108``)."""
        with self._lock:
            if self._cooldown_range:
                lo, hi = self._cooldown_range
                self._blacklist[host] = time.time() + random.uniform(lo, hi)
            else:
                self._blacklist[host] = float("inf")
            self._order = [h for h in self._order if h != host]
            self._slots.pop(host, None)

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            return self._blacklist.get(host, 0.0) > time.time()
