"""Elastic rendezvous helpers (parity: ``horovod/run/elastic/rendezvous.py``).

The driver writes each round's slot plan into the rendezvous KV
(``RendezvousServer.init``); workers fetch their (possibly new) rank layout
by ``/rank/<hostname>:<local_rank>`` at every (re-)init — the mechanism the
reference implements as a KV-serving handler (``rendezvous.py:22-45``).
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..http.http_client import read_data_from_kvstore

RANK_SCOPE = "rank"


def fetch_slot_info(addr: str, port: int, hostname: str, local_rank: int
                    ) -> Optional[Tuple[int, int, int, int, int, int]]:
    """Return (rank, size, local_rank, local_size, cross_rank, cross_size)
    for this worker, or None when the round's plan excludes it."""
    blob = read_data_from_kvstore(addr, port, RANK_SCOPE,
                                  f"{hostname}:{local_rank}")
    if blob is None:
        return None
    parts = blob.decode().split(",")
    return tuple(int(p) for p in parts)  # type: ignore[return-value]
