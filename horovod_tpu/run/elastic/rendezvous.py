"""Elastic rendezvous helpers (parity: ``horovod/run/elastic/rendezvous.py``).

The driver writes each round's slot plan into the rendezvous KV
(``RendezvousServer.init``); workers fetch their (possibly new) rank layout
by ``/rank/<hostname>:<local_rank>`` at every (re-)init — the mechanism the
reference implements as a KV-serving handler (``rendezvous.py:22-45``).

Every slot record carries the driver's rendezvous round, and the
controller endpoint is keyed by that round: a worker that fetched round
N's layout can only ever pair it with round N's coordinator, so a
late-publishing old rank 0 (or an early-polling old worker) can never
cross rounds.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

from ...common import config as _config
from ...common import faults as _faults
from ..http.http_client import put_data_into_kvstore, read_data_from_kvstore

RANK_SCOPE = "rank"
CONTROLLER_SCOPE = "controller"
# Liveness plane (docs/liveness.md): workers push heartbeats under
# /heartbeat/<hostname>:<local_rank>; a draining worker writes its
# protocol phase under /drain/<hostname>:<local_rank>.
HEARTBEAT_SCOPE = "heartbeat"
DRAIN_SCOPE = "drain"

SlotLayout = Tuple[int, int, int, int, int, int]


def put_heartbeat(addr: str, port: int, hostname: str, local_rank: int,
                  seq: int) -> None:
    """One worker heartbeat into the rendezvous KV. Short timeout: a
    beat that cannot land within a fraction of the liveness window is
    better dropped than queued — stale heartbeats defend nobody."""
    put_data_into_kvstore(addr, port, HEARTBEAT_SCOPE,
                          f"{hostname}:{local_rank}",
                          str(seq).encode(), timeout=2.0)


def announce_drain(addr: str, port: int, hostname: str, local_rank: int,
                   phase: str) -> None:
    """Publish this worker's drain-protocol phase ("begin" then
    "commit"). Phase-keyed (``<host>:<slot>.<phase>``), not one mutable
    value: the two phases land milliseconds apart and the driver polls
    at ~1 s, so a single key would usually show only "commit" and the
    DRAIN_BEGIN instant would be lost. The driver's liveness monitor
    turns each into a timeline instant, and the commit marker at exit
    reclassifies the departure as DRAINED — zero blacklist strikes
    (docs/liveness.md)."""
    put_data_into_kvstore(addr, port, DRAIN_SCOPE,
                          f"{hostname}:{local_rank}.{phase}", b"1",
                          timeout=2.0)


def fetch_slot_info(addr: str, port: int, hostname: str, local_rank: int,
                    rank: Optional[int] = None
                    ) -> Optional[Tuple[SlotLayout, int]]:
    """Return ((rank, size, local_rank, local_size, cross_rank,
    cross_size), rendezvous_round) for this worker, or None when the
    round's plan excludes it. ``rank`` is the caller's CURRENT rank for
    fault targeting (the env copy goes stale once the driver moves
    ranks)."""
    _faults.point("rendezvous.poll", rank=rank)
    blob = read_data_from_kvstore(addr, port, RANK_SCOPE,
                                  f"{hostname}:{local_rank}")
    if blob is None:
        return None
    parts = [int(p) for p in blob.decode().split(",")]
    return tuple(parts[:6]), parts[6]  # type: ignore[return-value]


def publish_controller_endpoint(addr: str, port: int, controller_host: str,
                                controller_port: int,
                                rendezvous_round: int) -> None:
    """Rank 0 announces where its native controller listens this round.

    The static launcher can hand every worker a fixed
    ``HOROVOD_CONTROLLER_ADDR`` because rank 0's host never moves; under
    elasticity rank 0 migrates when its host is blacklisted, so the live
    endpoint must travel through the rendezvous KV — the role the
    reference's Gloo rendezvous store plays for its full-mesh connect
    (``gloo_context.cc:70-90``). The key is scoped by the round the
    publisher fetched its slot from, so a rank 0 deposed between its slot
    fetch and this publish writes a key no current-round worker reads."""
    put_data_into_kvstore(addr, port, CONTROLLER_SCOPE,
                          f"endpoint.{rendezvous_round}",
                          f"{controller_host}:{controller_port}".encode())


def fetch_controller_endpoint(addr: str, port: int, rendezvous_round: int,
                              timeout: float = 120.0,
                              rank: Optional[int] = None
                              ) -> Optional[Tuple[str, int]]:
    """Poll the KV until the given round's controller endpoint appears.

    Returns (host, port), or None on timeout. The poll schedule comes
    from the shared Retrier under the ``RENDEZVOUS`` scope (monotonic
    deadline: NTP steps on freshly provisioned TPU VMs must not stretch
    or collapse the wait). Each KV read uses a short per-request timeout
    and a single attempt so short overall deadlines (the stale-round poll
    passes 2 s) hold — the default client settings could block ~31 s in
    one read."""
    # The caller's ``timeout`` is a contract (the stale-round poll in
    # host_world passes 2 s and depends on it): deadline and attempts are
    # pinned against env override; only the poll cadence is tunable.
    retrier = _faults.Retrier(
        _config.retry_policy_from_env(
            "RENDEZVOUS", pinned=("max_attempts", "deadline"),
            max_attempts=0, base_delay=0.25, max_delay=2.0,
            deadline=timeout),
        f"rendezvous.endpoint.{rendezvous_round}")
    overall_deadline = time.monotonic() + timeout

    def fetch() -> Optional[Tuple[str, int]]:
        # Its own point name (not rendezvous.poll): sharing a hit
        # counter with the slot-info fetches would make step= targeting
        # depend on how many endpoint polls interleave with them.
        _faults.point("rendezvous.endpoint", rank=rank)
        # Clamp each request to the REMAINING overall budget: a read
        # started at deadline-ε must not block its full 2 s and stretch
        # a short caller deadline to ~2x.
        remaining = overall_deadline - time.monotonic()
        per_req = max(0.2, min(2.0, remaining))
        try:
            blob = read_data_from_kvstore(addr, port, CONTROLLER_SCOPE,
                                          f"endpoint.{rendezvous_round}",
                                          timeout=per_req, retries=1)
        except OSError:
            return None  # transient KV hiccup: keep polling to deadline
        if not blob:
            return None
        host, _, p = blob.decode().rpartition(":")
        return host, int(p)

    try:
        return retrier.poll(fetch)
    except _faults.RetryExhausted:
        return None
