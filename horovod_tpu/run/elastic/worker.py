"""Worker-side notification plane (parity:
``horovod/run/elastic/worker.py``).

Each worker process runs a ``WorkerNotificationService`` (authenticated
pickle-over-TCP) and registers its address in the rendezvous KV under
``/workers/<rank>``. When the driver observes a host-set change it connects
to every registered worker and sends ``HostsUpdatedRequest``; the service
posts into the process-local elastic mailbox, which surfaces as
``HostsUpdatedInterrupt`` at the next ``state.commit()`` —
(``driver.py:185-213``, ``worker.py:101-110``).
"""

from __future__ import annotations

import pickle
import threading
from typing import List, Optional, Tuple

from ...common import config as _config
from ...common import faults as _faults
from ...common import logging as _log
from ..common.util import network, secret
from ..http.http_client import put_data_into_kvstore, read_data_from_kvstore


class _HeartbeatSender(threading.Thread):
    """Worker-side liveness heartbeat (docs/liveness.md): one KV put per
    ``HOROVOD_HEARTBEAT_MS`` under ``/heartbeat/<hostname>:<local_rank>``.
    The driver's liveness monitor watches the value change and escalates
    silence miss → SUSPECT → EVICT, so a dead or partitioned worker is
    detected without waiting for a collective to wedge.

    A failed beat is skipped, never fatal: heartbeats defend the world
    against THIS process dying, so this thread dying on a transient KV
    hiccup would be the tail wagging the dog. The ``control.heartbeat``
    fault seam supports ``kind=drop_conn`` (a dropped beat) and
    ``kind=delay_ms`` (a late beat) for the chaos tests.
    """

    def __init__(self, addr: str, port: int, hostname: str,
                 local_rank: int, interval_ms: int):
        super().__init__(daemon=True, name="hvd-heartbeat")
        self._addr = addr
        self._port = port
        self._hostname = hostname
        self._local_rank = local_rank
        self._interval_s = max(interval_ms, 1) / 1000.0
        self._stop_beating = threading.Event()

    def run(self):
        from .rendezvous import put_heartbeat

        seq = 0
        while not self._stop_beating.wait(self._interval_s):
            seq += 1
            try:
                _faults.point("control.heartbeat")
                put_heartbeat(self._addr, self._port, self._hostname,
                              self._local_rank, seq)
            except OSError:
                # Includes the drop_conn fault's ConnectionResetError and
                # real KV hiccups: drop the beat, keep beating. Persistent
                # failure IS the signal — the driver sees the silence.
                continue

    def stop(self):
        self._stop_beating.set()


class HostsUpdatedRequest:
    def __init__(self, timestamp: float):
        self.timestamp = timestamp


class WorkerNotificationService(network.BasicService):
    NAME = "worker notification service"

    def __init__(self, key: bytes):
        super().__init__(self.NAME, key)

    def _handle(self, req, client_address):
        if isinstance(req, HostsUpdatedRequest):
            from ...elastic.state import notification_mailbox

            notification_mailbox.post(req.timestamp)
            return network.AckResponse()
        return super()._handle(req, client_address)


class WorkerNotificationClient(network.BasicClient):
    def __init__(self, addresses: List[Tuple[str, int]], key: bytes):
        super().__init__(WorkerNotificationService.NAME, addresses, key)

    def notify_hosts_updated(self, timestamp: float) -> None:
        self._request(HostsUpdatedRequest(timestamp))


class WorkerNotificationManager:
    """Worker-side singleton: starts the service and registers it in the
    rendezvous KV (parity: ``worker.py:30-70``)."""

    def __init__(self):
        self._service: Optional[WorkerNotificationService] = None
        self._heartbeat: Optional[_HeartbeatSender] = None

    def init(self) -> None:
        if self._service is not None:
            return
        key_b64 = _config.secret_key_b64()
        if not key_b64:
            return  # not launched by the elastic driver
        import base64

        key = base64.b64decode(key_b64)
        self._service = WorkerNotificationService(key)
        if _config.preempt_signal_spec():
            # Opt-in: convert TPU-VM preemption signals into the graceful
            # drain protocol at the next commit (see
            # elastic.state.register_preemption_signal). Signal handlers
            # can only be installed on the main thread; degrade to a
            # warning when init runs elsewhere rather than failing init.
            from ...elastic.state import register_preemption_signal

            try:
                register_preemption_signal()
            except (ValueError, AttributeError, OSError) as e:
                # ValueError: non-main thread; AttributeError: unknown
                # signal name; OSError: uncatchable signal (e.g. SIGKILL).
                _log.warning(
                    f"preemption-signal handler not installed: {e}")
        addr = _config.rendezvous_addr()
        port = _config.rendezvous_port()
        # Keyed by (hostname, local_rank) — stable for the process's whole
        # lifetime, unlike the rank, which the driver reassigns on
        # membership changes.
        hostname = _config.hostname("localhost")
        local_rank = _config.local_rank()
        if addr and port:
            put_data_into_kvstore(
                addr, port, "workers", f"{hostname}:{local_rank}",
                pickle.dumps(self._service.addresses()))
            hb_ms = _config.heartbeat_ms()
            if hb_ms > 0 and self._heartbeat is None:
                # Liveness plane armed (HOROVOD_HEARTBEAT_MS > 0; default
                # off — no thread, no KV traffic, byte-identical to the
                # pre-liveness worker).
                self._heartbeat = _HeartbeatSender(
                    addr, port, hostname, local_rank, hb_ms)
                self._heartbeat.start()

    def shutdown(self) -> None:
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        if self._service is not None:
            self._service.shutdown()
            self._service = None


notification_manager = WorkerNotificationManager()


def get_worker_client(rendezvous_addr: str, rendezvous_port: int,
                      hostname: str, local_rank: int, key: bytes
                      ) -> Optional[WorkerNotificationClient]:
    """Driver side: look up a worker's notification address (keyed by its
    stable hostname:local_rank identity) and connect."""
    blob = read_data_from_kvstore(rendezvous_addr, rendezvous_port,
                                  "workers", f"{hostname}:{local_rank}")
    if blob is None:
        return None
    return WorkerNotificationClient(pickle.loads(blob), key)
