"""Elastic launch entry (parity: ``horovod/run/gloo_run.py:275``
gloo_run_elastic): start the rendezvous + elastic driver, spawn workers
via ssh/local exec, return the job's exit status.
"""

from __future__ import annotations

import base64
import os
import sys
from typing import List, Optional

from ...common import config as _config
from .. import launch as _launch
from ..common.util import config_parser, secret
from ..common.util import safe_shell_exec
from ..http.http_server import RendezvousServer
from .discovery import FixedHosts, HostDiscoveryScript
from .driver import ElasticDriver
from .worker import get_worker_client


def run_elastic(args, command: List[str],
                base_env: Optional[dict] = None) -> int:
    if getattr(args, "host_discovery_script", None):
        discovery = HostDiscoveryScript(args.host_discovery_script,
                                        slots=getattr(args, "slots", None))
    elif getattr(args, "hosts", None):
        hosts = {}
        for part in args.hosts.split(","):
            name, slots = part.rsplit(":", 1)
            hosts[name] = int(slots)
        discovery = FixedHosts(hosts)
    else:
        raise ValueError(
            "elastic mode needs --host-discovery-script or -H")

    min_np = args.min_np or args.np or 1
    max_np = args.max_np or 0

    rendezvous = RendezvousServer(verbose=1 if args.verbose else 0)
    rendezvous_port = rendezvous.start_server()
    controller_port = _launch.free_port()
    key = secret.make_secret_key()

    env = dict(base_env if base_env is not None else os.environ)
    config_parser.set_env_from_args(env, args)
    env[_config.HOROVOD_ELASTIC] = "1"
    env["HOROVOD_SECRET_KEY"] = base64.b64encode(key).decode()
    # Controller-level job isolation (see launch.launch_workers).
    env.setdefault("HOROVOD_JOB_KEY", os.urandom(8).hex())

    # --elastic-timeout governs world (re)assembly after re-scaling
    # (reference runner.py:360 elastic_timeout, default 600 — distinct
    # from --start-timeout's process-startup wait, whose parser default
    # of 30 must NOT leak in here). `is None` check: an explicit 0 is a
    # fail-fast request, not "unset".
    elastic_timeout = getattr(args, "elastic_timeout", None)
    if elastic_timeout is None:
        elastic_timeout = 600
    # Launcher-side timeline: when the job records a timeline, membership
    # events (host blacklisted, strikes, parole) land in a sibling
    # `<timeline>.driver.json` — rank 0's own file belongs to the worker.
    driver_timeline = None
    timeline_path = env.get(_config.HOROVOD_TIMELINE)
    if timeline_path:
        from ...common.timeline import Timeline

        driver_timeline = Timeline(timeline_path + ".driver.json")
    driver = ElasticDriver(
        rendezvous, discovery, min_np=min_np, max_np=max_np,
        timeout=elastic_timeout,
        cooldown_range=getattr(args, "blacklist_cooldown_range", None),
        verbose=1 if args.verbose else 0, timeline=driver_timeline)

    def launcher_addr() -> str:
        # Shared with the static/jsrun paths so --network-interface pins
        # the advertised NIC here too (elastic is where it matters most:
        # hosts change at runtime and every newcomer must reach the
        # launcher over the pinned fabric).
        from ..runner import _launcher_addr

        hosts_now = [h for h, _ in driver.host_manager.current_hosts]
        plan_like = [type("S", (), {"hostname": h})() for h in hosts_now]
        return _launcher_addr(plan_like, getattr(args, "nics", None))

    def create_worker(slot, events):
        worker_env = _launch.slot_env(
            slot, controller_addr=launcher_addr(),
            controller_port=controller_port,
            rendezvous_addr=launcher_addr(),
            rendezvous_port=rendezvous_port, base_env=env)
        cmd = _launch.build_worker_command(
            slot, command, worker_env,
            ssh_port=getattr(args, "ssh_port", None))
        output_dir = getattr(args, "output_filename", None)
        if output_dir:
            # "a" not "w": a slot can be re-staffed across elastic rounds,
            # and each life's output should append rather than erase its
            # predecessor's.
            return _launch.execute_redirected(cmd, worker_env, events,
                                              output_dir, slot.rank,
                                              mode="a")
        return safe_shell_exec.execute(
            cmd, env=worker_env, events=events,
            prefix=str(slot.rank), stdout=sys.stdout, stderr=sys.stderr)

    driver.set_notify_client_factory(
        lambda hostname, local_rank: get_worker_client(
            launcher_addr(), rendezvous_port, hostname, local_rank, key))
    try:
        driver.start(args.np or min_np, create_worker)
        return driver.get_results()
    finally:
        driver.stop()
        rendezvous.stop_server()
        if driver_timeline is not None:
            driver_timeline.close()
