"""Worker liveness registry (parity: ``horovod/run/elastic/registration.py``).

The driver records each worker's terminal state; a host whose worker FAILED
is blacklisted, while SUCCESS counts toward clean job completion
(``registration.py:26-62``).
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"
# A graceful preemption departure (docs/liveness.md): the worker
# committed its elastic state and announced DRAIN before leaving. The
# driver re-activates the shrunk world like a failure, but the host is
# quarantined WITHOUT a blacklist strike and the round's exit code stays
# clean — preemption is the platform's fault, not the host's.
DRAINED = "DRAINED"


class WorkerStateRegistry:
    def __init__(self, driver, host_manager, verbose: bool = False):
        self._driver = driver
        self._host_manager = host_manager
        self._lock = threading.Lock()
        self._states: Dict[Tuple[str, int], str] = {}
        self._barrier = threading.Event()

    def reset(self) -> None:
        with self._lock:
            self._states.clear()
            self._barrier.clear()

    def record_ready(self, host: str, slot: int) -> None:
        with self._lock:
            self._states[(host, slot)] = READY

    def record_success(self, host: str, slot: int) -> None:
        self._record(host, slot, SUCCESS)

    def record_failure(self, host: str, slot: int) -> None:
        # Blacklist before recording: _record triggers the driver's
        # recovery re-activation, which must already see the shrunken
        # host set or the failed host lands back in the new plan.
        self._host_manager.blacklist(host)
        self._record(host, slot, FAILURE)

    def record_drained(self, host: str, slot: int) -> None:
        """A graceful preemption departure: quarantine the host (it is
        going away — respawning onto it would race its death) with ZERO
        blacklist strikes, then re-activate the shrunk world exactly
        like the failure path does."""
        self._host_manager.quarantine(host)
        self._record(host, slot, DRAINED)

    def _record(self, host: str, slot: int, state: str) -> None:
        with self._lock:
            self._states[(host, slot)] = state
        self._driver.on_worker_exit(host, slot, state)

    def count(self, state: str) -> int:
        with self._lock:
            return sum(1 for s in self._states.values() if s == state)

    def last_worker_states(self) -> Dict[Tuple[str, int], str]:
        with self._lock:
            return dict(self._states)
