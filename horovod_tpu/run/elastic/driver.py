"""Elastic driver (parity: ``horovod/run/elastic/driver.py:58-296``).

Responsibilities, matching the reference:

- poll the host discovery source every ``DISCOVER_HOSTS_FREQUENCY_SECS``
  (1 s) on a background thread (``driver.py:164-183``);
- gate start on ``wait_for_available_slots(min_np)`` (``driver.py:133``);
- assign ranks stably: hosts keep discovery-age order so existing workers'
  ranks survive scale-up, rank 0 stays on the oldest host
  (``discovery.py:113-121``);
- spawn one worker per slot through a caller-provided ``create_worker_fn``
  (``driver.py:259-277``);
- on worker exit record success/failure; failures blacklist the host
  (``registration.py:26-62``);
- notify live workers over the notification plane when membership changes
  (``driver.py:185-213``) and re-init the rendezvous with the new plan.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ...common import faults as _faults
from ...common import liveness as _liveness
from ...common import logging as _log
from ...common import timeline as _timeline
from ..common.util.hosts import HostInfo, SlotInfo, get_host_assignments
from .discovery import HostManager
from .registration import DRAINED, FAILURE, SUCCESS, WorkerStateRegistry
from .rendezvous import DRAIN_SCOPE, HEARTBEAT_SCOPE

DISCOVER_HOSTS_FREQUENCY_SECS = 1.0


class _WorkerHandle:
    """Per-worker shutdown event + classification marks (mutated under
    the driver lock): `removed` = slot left the plan (no accounting),
    `evicted` = liveness plane gave up on it (failure accounting
    regardless of exit code), `draining` = announced a graceful
    preemption drain."""

    __slots__ = ("event", "removed", "evicted", "draining")

    def __init__(self):
        self.event = threading.Event()
        self.removed = False
        self.evicted = False
        self.draining = False


class ElasticDriver:
    def __init__(self, rendezvous, discovery, min_np: int, max_np: int = 0,
                 timeout: Optional[float] = None,
                 cooldown_range: Optional[Tuple[int, int]] = None,
                 verbose: int = 0, timeline=None):
        self._rendezvous = rendezvous
        self._timeline = timeline  # launcher-side Timeline, optional
        self._host_manager = HostManager(discovery, cooldown_range)
        self._host_manager.set_on_blacklist(self._on_host_blacklisted)
        # Publish the rejoin grace surviving workers should honor before
        # concluding a failure was transient. It must cover the driver's
        # own worst-case plan rebuild (blacklist cooldown upper bound +
        # activation), and only the driver knows the cooldown range — so
        # the value travels through the rendezvous KV rather than being a
        # worker-side guess (see host_world._rejoin_grace_seconds).
        grace = 10.0 + (cooldown_range[1] if cooldown_range else 0.0)
        if hasattr(rendezvous, "put"):
            self._rendezvous.put("config", "rejoin_grace",
                                 repr(grace).encode())
        self._min_np = min_np
        self._max_np = max_np or 0
        # `is None` check: timeout=0 is an explicit fail-fast request.
        self._timeout = 600.0 if timeout is None else timeout
        self._verbose = verbose

        self._worker_registry = WorkerStateRegistry(self, self._host_manager)
        self._create_worker_fn: Optional[Callable] = None
        self._assignments: Dict[Tuple[str, int], SlotInfo] = {}
        self._world_size = 0
        self._rendezvous_round = 0

        self._lock = threading.RLock()
        self._shutdown = threading.Event()
        self._host_change = threading.Event()
        self._workers_active: Dict[Tuple[str, int], _WorkerHandle] = {}
        # Liveness plane (docs/liveness.md): armed by HOROVOD_HEARTBEAT_MS
        # > 0 when the rendezvous store is readable driver-side. Workers
        # push heartbeats into the KV; the discovery loop folds them into
        # the tracker and escalates silence miss -> SUSPECT -> EVICT.
        # Tracker state is guarded by self._lock.
        self._liveness: Optional[_liveness.LivenessTracker] = None
        if _liveness.enabled() and hasattr(rendezvous, "get"):
            self._liveness = _liveness.LivenessTracker()
        self._hb_seen: Dict[Tuple[str, int], bytes] = {}
        # ((host, slot), phase) -> consumed marker
        self._drain_seen: Dict[Tuple[Tuple[str, int], str], bytes] = {}
        self._requested_np = min_np
        self._round_failures = 0
        self._notify_client_factory = None  # injectable for tests
        self._result: Optional[int] = None
        self._done = threading.Event()
        self._discovery_thread = threading.Thread(
            target=self._discover_loop, daemon=True, name="elastic-discovery")

    # -- lifecycle -----------------------------------------------------------

    def start(self, np: int, create_worker_fn: Callable) -> None:
        """Begin: wait for min_np slots, assign, spawn workers (parity:
        ``driver.py:84``)."""
        self._create_worker_fn = create_worker_fn
        self._requested_np = max(np, self._min_np)
        self._host_manager.update_available_hosts()
        self._discovery_thread.start()
        while True:
            self.wait_for_available_slots(self._min_np)
            if self._activate_workers(self._requested_np):
                break
            self._shutdown.wait(DISCOVER_HOSTS_FREQUENCY_SECS)

    def stop(self) -> None:
        self._shutdown.set()
        with self._lock:
            handles = list(self._workers_active.values())
        for h in handles:
            h.event.set()
        if self._discovery_thread.is_alive():
            self._discovery_thread.join(timeout=5.0)

    def finished(self) -> bool:
        return self._done.is_set()

    def get_results(self) -> int:
        self._done.wait()
        return self._result if self._result is not None else 1

    # -- membership ----------------------------------------------------------

    def _blacklist_detail(self) -> str:
        info = self._host_manager.blacklist_info()
        if not info:
            return ""
        parts = []
        for host, st in info.items():
            if not st["blacklisted"]:
                continue
            kind = "permanently" if st["permanent"] else "in cooldown"
            parts.append(f"{host} ({kind}, strikes {st['strikes']})")
        return f"; blacklisted hosts: {', '.join(parts)}" if parts else ""

    def wait_for_available_slots(self, min_np: int):
        """Block until at least ``min_np`` slots exist (parity:
        ``driver.py:133``). Refusing to shrink below ``min_np`` comes
        with a clear error: the timeout message names every blacklisted
        host and whether it can ever return — "job died because the
        driver blacklisted its last hosts" must be diagnosable from the
        launcher log alone. The wait itself always runs the full timeout:
        discovery may hand out brand-new replacement hosts (autoscaler)
        that no blacklist state can predict."""
        deadline = time.time() + self._timeout
        while not self._shutdown.is_set():
            available = self._host_manager.available_slots()
            if available >= min_np:
                return
            if time.time() > deadline:
                raise TimeoutError(
                    f"timed out waiting for {min_np} slots; only "
                    f"{available} available{self._blacklist_detail()}")
            self._shutdown.wait(DISCOVER_HOSTS_FREQUENCY_SECS)

    def _discover_loop(self):
        while not self._shutdown.is_set():
            try:
                if self._host_manager.update_available_hosts():
                    self._host_change.set()
                    self._on_hosts_updated()
            # hvdlint: ignore[exception-discipline] -- discovery script
            # hiccups are transient; the loop retries next tick and no
            # collective signal flows through the driver's discovery path
            except Exception as e:
                _log.warning(f"host discovery failed: {e}")
            if self._liveness is not None:
                try:
                    self._check_liveness()
                # hvdlint: ignore[exception-discipline] -- the liveness
                # sweep must never kill the discovery loop; a failed pass
                # only delays detection by one tick
                except Exception as e:
                    _log.warning(f"liveness check failed: {e}")
            self._shutdown.wait(DISCOVER_HOSTS_FREQUENCY_SECS)

    # -- liveness plane (docs/liveness.md) -----------------------------------

    def _instant(self, name: str, args: dict) -> None:
        if self._timeline is not None:
            # hvdlint: ignore[timeline-instant-registry] -- generic
            # relay: every call site passes a catalog constant through
            self._timeline.instant(name, args)

    def _check_liveness(self):
        """One liveness pass, piggybacked on the discovery tick: fold KV
        heartbeats and drain markers into the tracker, escalate, act on
        evictions. Detection latency is bounded by the liveness timeout
        plus one tick — comfortably inside the 2x-timeout acceptance
        window the chaos tests assert."""
        to_evict = []
        with self._lock:
            tracker = self._liveness
            active = dict(self._workers_active)
            # A worker enters the tracker at its FIRST beat, not at
            # spawn: liveness defends a previously-live worker against
            # silent death; a worker still importing frameworks or
            # loading a checkpoint has never beaten and is the elastic
            # start-timeout's job — evicting it for slow startup would
            # blacklist healthy hosts on oversubscribed machines.
            for key in list(tracker.members()):
                if key not in active:
                    tracker.forget(key)
                    self._hb_seen.pop(key, None)
            for key, handle in active.items():
                host, slot = key
                kv_key = f"{host}:{slot}"
                beat = self._rendezvous.get(HEARTBEAT_SCOPE, kv_key)
                # Value-change detection, never clock comparison: the
                # driver's clock and the workers' never meet, so a beat
                # is "the counter moved", timed by the driver's own clock.
                if beat is not None and beat != self._hb_seen.get(key):
                    self._hb_seen[key] = beat
                    tracker.beat(key)
                for phase, name in (("begin", _timeline.DRAIN_BEGIN),
                                    ("commit", _timeline.DRAIN_COMMIT)):
                    marker = (key, phase)
                    if marker in self._drain_seen:
                        continue
                    if self._rendezvous.get(DRAIN_SCOPE,
                                            f"{kv_key}.{phase}") is None:
                        continue
                    self._drain_seen[marker] = b"1"
                    handle.draining = True
                    tracker.mark_draining(key)
                    self._instant(name, {"host": host, "slot": slot,
                                         "phase": phase})
                    _log.info(
                        f"elastic: worker {host}:{slot} drain {phase}")
            for ev in tracker.check():
                host, slot = ev.member
                args = {"host": host, "slot": slot,
                        "silence_ms": round(ev.silence_ms)}
                if ev.kind == _liveness.MISS:
                    self._instant(_timeline.HEARTBEAT_MISS, args)
                    _log.debug(f"elastic: heartbeat miss from "
                               f"{host}:{slot}")
                elif ev.kind == _liveness.SUSPECT_EVENT:
                    self._instant(_timeline.RANK_SUSPECT, args)
                    _log.warning(
                        f"elastic: worker {host}:{slot} is SUSPECT "
                        f"({ev.silence_ms:.0f}ms silent)")
                elif ev.kind == _liveness.EVICT:
                    self._instant(_timeline.RANK_EVICTED, args)
                    from ...common import metrics as _metrics

                    _metrics.inc("elastic.evictions")
                    _log.warning(
                        f"elastic: worker {host}:{slot} EVICTED after "
                        f"{ev.silence_ms:.0f}ms of silence")
                    handle = self._workers_active.get(ev.member)
                    if handle is not None:
                        handle.evicted = True
                        to_evict.append(ev.member)
        # Act outside the lock: terminating the worker and nudging the
        # survivors both cross process/network boundaries.
        for key in to_evict:
            with self._lock:
                handle = self._workers_active.get(key)
            if handle is not None:
                handle.event.set()  # terminate; exit routes to failure
        if to_evict:
            self._notify_survivors(exclude=set(to_evict))

    def _notify_survivors(self, exclude=()):
        """Membership-change nudge to every live worker NOT in
        ``exclude`` — survivors raise ``HostsUpdatedInterrupt`` at their
        next commit instead of wedging on a collective with the evicted
        rank."""
        factory = self._notify_client_factory
        if factory is None:
            return
        ts = time.time()
        with self._lock:
            keys = [k for k in self._assignments if k not in set(exclude)]
        for hostname, local_rank in keys:
            try:
                client = factory(hostname, local_rank)
                if client is not None:
                    client.notify_hosts_updated(ts)
            # hvdlint: ignore[exception-discipline] -- best-effort nudge:
            # an unreachable survivor learns of the change when its
            # collective fails, exactly as before the liveness plane
            except Exception as e:
                _log.debug(f"could not notify {hostname}:{local_rank}: {e}")

    def _consume_drain_marker(self, host: str, slot: int) -> bool:
        """At worker exit: True when the worker completed its drain
        protocol (commit marker present; begin alone is an uncommitted
        drain = a crash). Consumes the markers so a re-staffed slot's
        next life starts unmarked. A fast drain can finish between two
        discovery ticks — any phase the liveness sweep never saw gets
        its timeline instant emitted here, so DRAIN_BEGIN/DRAIN_COMMIT
        are recorded deterministically, not only when the 1 s poll wins
        the race."""
        if not hasattr(self._rendezvous, "get"):
            return False
        kv_key = f"{host}:{slot}"
        # Also retire the slot's heartbeat key: a re-staffed slot must
        # not inherit its previous life's counter — the first liveness
        # tick would read the stale value as a fresh beat and start the
        # new worker's silence clock while it is still importing
        # frameworks (exactly the slow-startup eviction the first-beat
        # admission rule exists to prevent).
        if hasattr(self._rendezvous, "delete"):
            self._rendezvous.delete(HEARTBEAT_SCOPE, kv_key)
        self._hb_seen.pop((host, slot), None)
        committed = False
        for phase, name in (("begin", _timeline.DRAIN_BEGIN),
                            ("commit", _timeline.DRAIN_COMMIT)):
            present = self._rendezvous.get(
                DRAIN_SCOPE, f"{kv_key}.{phase}") is not None
            if phase == "commit":
                committed = present
            if present and ((host, slot), phase) not in self._drain_seen:
                self._instant(name, {"host": host, "slot": slot,
                                     "phase": phase})
                _log.info(f"elastic: worker {host}:{slot} drain {phase}")
            if hasattr(self._rendezvous, "delete"):
                self._rendezvous.delete(DRAIN_SCOPE, f"{kv_key}.{phase}")
            self._drain_seen.pop(((host, slot), phase), None)
        return committed

    def _on_hosts_updated(self):
        # Gate on the *plan* actually changing, not merely the host set: a
        # discovery echo (e.g. a blacklisted host returning from cooldown
        # after the failure path already rebuilt the plan, or spare hosts
        # beyond max_np appearing) must not interrupt workers — they would
        # re-rendezvous expecting a new round that never comes.
        if not self._plan_changed():
            _log.debug("elastic: host set changed but plan is unchanged "
                       "and staffed; nothing to do")
            return
        _log.info("elastic: host set changed; notifying workers")
        self._notify_survivors()
        # Regrow/shrink the plan so the rendezvous the interrupted workers
        # re-fetch reflects the new host set, and spawn workers on any new
        # slots (parity: driver.py:185-213 + _activate_workers on update).
        # Never shrink below min_np on a discovery blip: keep the current
        # plan and let the failure path (which gates on min_np) handle any
        # actual worker deaths.
        if self._create_worker_fn is not None and not self._shutdown.is_set():
            if self._host_manager.available_slots() >= self._min_np:
                self._activate_workers(self._target_np())
            else:
                _log.warning(
                    "elastic: host update leaves fewer than min_np="
                    f"{self._min_np} slots; keeping current plan")

    def set_notify_client_factory(self, factory) -> None:
        self._notify_client_factory = factory

    def _on_host_blacklisted(self, host: str, info: dict) -> None:
        """Observer wired into the HostManager: every blacklist decision
        lands in the launcher timeline (when one is configured) so a
        post-mortem shows membership churn on the same time axis as the
        workers' collectives."""
        if self._timeline is not None:
            args = dict(info)
            if args.get("until") == float("inf"):
                # json.dumps would emit bare `Infinity` — invalid JSON
                # for strict trace parsers; `permanent` carries the fact.
                args["until"] = None
            self._timeline.instant(_timeline.HOST_BLACKLISTED, args)

    def blacklist_status(self):
        """Queryable blacklist state (strikes / cooldown / parole per
        host) — see ``HostManager.blacklist_info``."""
        return self._host_manager.blacklist_info()

    # -- rank assignment -----------------------------------------------------

    def _compute_assignments(self, np: int) -> List[SlotInfo]:
        hosts = [HostInfo(h, s) for h, s in self._host_manager.current_hosts]
        np_actual = min(sum(h.slots for h in hosts),
                        self._max_np or np, max(np, self._min_np))
        return get_host_assignments(hosts, np_actual)

    @staticmethod
    def _plan_key(plan: List[SlotInfo]):
        return sorted((s.hostname, s.local_rank, s.to_response_string())
                      for s in plan)

    def _plan_is_current(self, plan: List[SlotInfo]) -> bool:
        """True when ``plan`` equals the active assignments AND every slot
        has a live worker — i.e. re-activating would change nothing. Must
        be called under the lock."""
        if self._plan_key(plan) != self._plan_key(
                list(self._assignments.values())):
            return False
        return all((s.hostname, s.local_rank) in self._workers_active
                   for s in plan)

    def _plan_changed(self) -> bool:
        with self._lock:
            try:
                plan = self._compute_assignments(self._target_np())
            # hvdlint: ignore[exception-discipline] -- erring toward
            # "changed" only triggers a spurious notify, never a loss
            except Exception:
                return True  # can't tell; err on notifying
            return not self._plan_is_current(plan)

    def _activate_workers(self, np: int) -> bool:
        """(Re)assign ranks, spawn workers for newly-assigned slots, and
        terminate workers whose slot left the plan (blacklisted/removed
        hosts) (parity: ``driver.py:157,259-277``). Returns False — leaving
        the current plan untouched — when fewer than min_np slots exist at
        decision time (the available-slot pre-checks run unlocked, so a
        concurrent blacklist can shrink the world between check and act)."""
        with self._lock:
            plan = self._compute_assignments(np)
            if len(plan) < self._min_np:
                _log.warning(
                    f"elastic: only {len(plan)} slots available, below "
                    f"min_np={self._min_np}; keeping current plan")
                return False
            if self._plan_is_current(plan):
                # Nothing would change: same slots, same ranks, all
                # staffed. Bumping the round anyway is not harmless — a
                # worker mid-join on the current round would be orphaned
                # (it waits for the old round's coordinator; new arrivals
                # wait for the new round's), so dedupe here, where both
                # the failure path and the discovery thread land. This IS
                # a completed activation decision, so clear the round's
                # failure count like the full path does: the failure that
                # routed us here was already absorbed by a concurrent
                # activation (which respawned the dead slot), and keeping
                # its count would doom a fully recovered job's exit code.
                self._round_failures = 0
                return True
            self._world_size = plan[0].size if plan else 0
            self._rendezvous_round += 1
            self._round_failures = 0
            self._rendezvous.init(plan, self._rendezvous_round)
            new_slots = []
            assignments = {}
            for slot in plan:
                key = (slot.hostname, slot.local_rank)
                assignments[key] = slot
                if key not in self._workers_active:
                    new_slots.append(slot)
            removed = [k for k in self._workers_active
                       if k not in assignments]
            self._assignments = assignments
            for key in removed:
                handle = self._workers_active[key]
                handle.removed = True
                handle.event.set()
            for slot in new_slots:
                self._spawn(slot)
            return True

    def _spawn(self, slot: SlotInfo) -> None:
        handle = _WorkerHandle()
        key = (slot.hostname, slot.local_rank)
        self._workers_active[key] = handle

        def run():
            try:
                # Chaos seam: a kind=raise fault here simulates a launch-
                # side failure (bad ssh, unwritable output dir) for
                # slot.rank and must be accounted exactly like one.
                _faults.point("elastic.worker.start", rank=slot.rank)
                code = self._create_worker_fn(slot, [handle.event,
                                                     self._shutdown])
            # hvdlint: ignore[exception-discipline] -- converted, not
            # swallowed: code=1 routes it into the worker-failure
            # accounting (strikes/blacklist); the elastic.worker.start
            # chaos seam's FaultInjected relies on exactly this
            except Exception as e:
                # A launch-side failure (unwritable output dir, ssh exec
                # error) must be accounted like a worker failure — an
                # escaped exception would leave the slot unaccounted and
                # stall the driver forever.
                _log.warning(
                    f"worker {slot.hostname}:{slot.local_rank} failed to "
                    f"launch: {e}")
                code = 1
            host, lslot = slot.hostname, slot.local_rank
            # Classify under the lock: `removed`/`evicted` are only
            # honored while this worker's own handle is still the
            # registered one (a respawned slot carries a fresh handle).
            with self._lock:
                current = self._workers_active.get(key) is handle
                removed = handle.removed and current
                evicted = handle.evicted and current
            drained = self._consume_drain_marker(host, lslot)
            if removed:
                # Deliberately terminated when its slot left the plan —
                # neither a success nor a host-blacklisting failure.
                self.on_worker_removed(host, lslot)
            elif drained:
                # Completed the preemption drain protocol (commit marker
                # in the KV): clean departure, zero strikes, but the
                # world still shrinks and re-activates. Checked before
                # `evicted` — a drain whose farewell lost the race with
                # the liveness eviction is still a clean drain.
                from ...common import metrics as _metrics

                _metrics.inc("elastic.drains")
                self._worker_registry.record_drained(host, lslot)
            elif evicted:
                # The liveness plane gave up on this worker (silence past
                # the timeout) and terminated it; its exit code is
                # whatever the kill produced — the classification is
                # failure regardless (docs/liveness.md).
                self._worker_registry.record_failure(host, lslot)
            elif code == 0:
                self._worker_registry.record_success(host, lslot)
            else:
                self._worker_registry.record_failure(host, lslot)

        threading.Thread(target=run, daemon=True,
                         name=f"worker-{slot.hostname}-{slot.local_rank}"
                         ).start()

    # -- worker exit handling (called by WorkerStateRegistry) ---------------

    def on_worker_removed(self, host: str, slot: int) -> None:
        """A worker terminated because its slot left the plan; drop it from
        the active set with no success/failure accounting. If discovery
        flapped and the slot is back in the current plan, respawn it so no
        rank is left unstaffed."""
        with self._lock:
            self._workers_active.pop((host, slot), None)
            reborn = self._assignments.get((host, slot))
            if reborn is not None and not self._shutdown.is_set():
                self._spawn(reborn)
                return
            still_active = len(self._workers_active)
        if still_active == 0 and not self._shutdown.is_set():
            self._finish()

    def _finish(self) -> None:
        # Job over: success iff workers succeeded and none failed in the
        # current rendezvous round — failures recovered from in earlier
        # rounds don't doom an elastic job (parity: driver.py:279-295).
        successes = self._worker_registry.count(SUCCESS)
        self._result = (0 if self._round_failures == 0 and successes > 0
                        else 1)
        self._done.set()
        self._shutdown.set()

    def on_worker_exit(self, host: str, slot: int, state: str) -> None:
        with self._lock:
            self._workers_active.pop((host, slot), None)
            still_active = len(self._workers_active)
            if state == FAILURE:
                self._round_failures += 1
        if self._shutdown.is_set():
            return
        if still_active == 0:
            self._finish()
            return
        if state in (FAILURE, DRAINED):
            # Try to resume on the remaining hosts with as many slots as
            # are available (up to the requested/max np); workers meanwhile
            # hit HorovodInternalError and wait in their retry loop for the
            # new rendezvous. Retry if activation loses a race with another
            # concurrent blacklist.
            while not self._shutdown.is_set():
                try:
                    self.wait_for_available_slots(self._min_np)
                except TimeoutError:
                    self._result = 1
                    self._done.set()
                    self._shutdown.set()
                    return
                if self._activate_workers(self._target_np()):
                    return
                self._shutdown.wait(DISCOVER_HOSTS_FREQUENCY_SECS)

    def _target_np(self) -> int:
        """World size to aim for on membership change: grow to max_np when
        elastic bounds were given, else stay at the requested np."""
        return self._max_np or self._requested_np

    # -- introspection (used by tests, parity: driver accessors) -------------

    @property
    def host_manager(self) -> HostManager:
        return self._host_manager

    @property
    def world_size(self) -> int:
        return self._world_size

    def get_slot_info(self, host: str, slot: int) -> Optional[SlotInfo]:
        with self._lock:
            return self._assignments.get((host, slot))

    def get_assignments(self) -> List[SlotInfo]:
        with self._lock:
            return sorted(self._assignments.values(),
                          key=lambda s: s.rank)
