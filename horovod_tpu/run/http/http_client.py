"""HTTP KV client helpers (parity: ``horovod/run/http/http_client.py``)."""

from __future__ import annotations

import time
import urllib.error
import urllib.request
from typing import Optional


def read_data_from_kvstore(addr: str, port: int, scope: str,
                           key: str, timeout: float = 10.0,
                           retries: int = 3) -> Optional[bytes]:
    url = f"http://{addr}:{port}/{scope}/{key}"
    for attempt in range(retries):
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            if attempt == retries - 1:
                raise
        except (urllib.error.URLError, OSError):
            if attempt == retries - 1:
                raise
        time.sleep(0.5)
    return None


def put_data_into_kvstore(addr: str, port: int, scope: str, key: str,
                          value: bytes, timeout: float = 10.0) -> None:
    url = f"http://{addr}:{port}/{scope}/{key}"
    req = urllib.request.Request(url, data=value, method="PUT")
    with urllib.request.urlopen(req, timeout=timeout):
        pass


def delete_data_from_kvstore(addr: str, port: int, scope: str, key: str,
                             timeout: float = 10.0) -> None:
    url = f"http://{addr}:{port}/{scope}/{key}"
    req = urllib.request.Request(url, method="DELETE")
    with urllib.request.urlopen(req, timeout=timeout):
        pass
