"""HTTP KV client helpers (parity: ``horovod/run/http/http_client.py``).

Retries route through the shared ``common/faults.py`` Retrier under the
``KV`` scope, so one set of ``HOROVOD_RETRY_KV_*`` envs tunes every KV
read tree-wide (docs/fault-injection.md)."""

from __future__ import annotations

import urllib.error
import urllib.request
from typing import Optional

from ...common import config as _config
from ...common import faults as _faults


def read_data_from_kvstore(addr: str, port: int, scope: str,
                           key: str, timeout: float = 10.0,
                           retries: int = 3) -> Optional[bytes]:
    """One KV GET with retries. ``timeout`` bounds each request;
    ``retries`` is the call site's attempt budget (short-deadline callers
    pass 1 as a correctness contract, so attempts are NOT env-tunable);
    ``HOROVOD_RETRY_KV_{BASE_DELAY,MAX_DELAY,MULTIPLIER,DEADLINE}`` tune
    the backoff between those attempts."""
    url = f"http://{addr}:{port}/{scope}/{key}"

    def get() -> Optional[bytes]:
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None  # "not there (yet)" is an answer, not an error
            raise

    # max_attempts is pinned to the caller's ``retries``: short-deadline
    # call sites pass retries=1 as a correctness contract (e.g. the 2 s
    # stale-round poll), which a global HOROVOD_RETRY_MAX_ATTEMPTS must
    # not inflate. Delays/deadline stay env-tunable.
    retrier = _faults.Retrier(
        _config.retry_policy_from_env(
            "KV", pinned=("max_attempts",), max_attempts=retries,
            base_delay=0.5, max_delay=2.0, multiplier=1.5),
        f"kv.read/{scope}/{key}")
    return retrier.call(get, retry_on=(urllib.error.URLError, OSError))


def put_data_into_kvstore(addr: str, port: int, scope: str, key: str,
                          value: bytes, timeout: float = 10.0) -> None:
    url = f"http://{addr}:{port}/{scope}/{key}"
    req = urllib.request.Request(url, data=value, method="PUT")
    with urllib.request.urlopen(req, timeout=timeout):
        pass


def delete_data_from_kvstore(addr: str, port: int, scope: str, key: str,
                             timeout: float = 10.0) -> None:
    url = f"http://{addr}:{port}/{scope}/{key}"
    req = urllib.request.Request(url, method="DELETE")
    with urllib.request.urlopen(req, timeout=timeout):
        pass
