"""HTTP KV store + rendezvous server (parity:
``horovod/run/http/http_server.py:35-232``).

The launcher starts one ``RendezvousServer``; workers GET/PUT small values
under ``/scope/key`` paths. This plays the role of the reference's Gloo
rendezvous: the TPU-native runtime uses it to distribute the coordinator
address, slot assignments, and the elastic world state. DELETE is supported
for the elastic driver's re-rendezvous rounds.
"""

from __future__ import annotations

import collections
import socketserver
import threading
from http.server import BaseHTTPRequestHandler
from typing import Dict, List, Optional


class KVStoreHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # Silence per-request logging (parity: reference overrides log_message).
    def log_message(self, fmt, *args):
        pass

    def _split(self):
        parts = self.path.lstrip("/").split("/", 1)
        if len(parts) != 2:
            return None, None
        return parts[0], parts[1]

    def do_GET(self):
        scope, key = self._split()
        store = self.server.kvstore
        with self.server.kvstore_lock:
            value = store.get(scope, {}).get(key) if scope else None
        if value is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def do_PUT(self):
        scope, key = self._split()
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        if scope:
            with self.server.kvstore_lock:
                self.server.kvstore.setdefault(scope, {})[key] = value
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        scope, key = self._split()
        with self.server.kvstore_lock:
            scope_map = self.server.kvstore.get(scope, {})
            scope_map.pop(key, None)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class _KVServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, handler):
        self.kvstore: Dict[str, Dict[str, bytes]] = collections.defaultdict(
            dict)
        self.kvstore_lock = threading.Lock()
        super().__init__(addr, handler)


class RendezvousServer:
    """KV server owning the job's rendezvous state (parity:
    ``http_server.py:139-232``)."""

    def __init__(self, verbose: int = 0):
        self._server: Optional[_KVServer] = None
        self._thread: Optional[threading.Thread] = None
        self._verbose = verbose

    def start_server(self, handler_cls=KVStoreHandler) -> int:
        self._server = _KVServer(("0.0.0.0", 0), handler_cls)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="rendezvous-http")
        self._thread.start()
        return self._server.server_address[1]

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def init(self, host_alloc_plan: List, rendezvous_round: int = 0) -> None:
        """Load slot assignments into the store so each worker can GET its
        rank layout under ``/rank/<hostname>:<local_rank>`` (parity:
        ``RendezvousHandler`` scope init, ``http_server.py:139+``). Each
        record is stamped with the rendezvous round; the controller
        endpoint is keyed by the same round (see
        ``elastic/rendezvous.py``), so slot layout and coordinator can
        never pair across rounds."""
        with self._server.kvstore_lock:
            self._server.kvstore.pop("rank", None)
            # A new round means a possibly-new rank 0: drop superseded
            # controller endpoints (their round-scoped keys are unreadable
            # by current workers anyway; this is garbage collection).
            self._server.kvstore.pop("controller", None)
            store = self._server.kvstore.setdefault("rank", {})
            for slot in host_alloc_plan:
                key = f"{slot.hostname}:{slot.local_rank}"
                value = f"{slot.to_response_string()},{rendezvous_round}"
                store[key] = value.encode()

    def put(self, scope: str, key: str, value: bytes) -> None:
        with self._server.kvstore_lock:
            self._server.kvstore.setdefault(scope, {})[key] = value

    def get(self, scope: str, key: str) -> Optional[bytes]:
        with self._server.kvstore_lock:
            return self._server.kvstore.get(scope, {}).get(key)

    def delete(self, scope: str, key: str) -> None:
        """Driver-side key removal (the liveness monitor consumes drain
        markers so a re-staffed slot's next life starts unmarked)."""
        with self._server.kvstore_lock:
            self._server.kvstore.get(scope, {}).pop(key, None)

    def stop_server(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._thread.join(timeout=5.0)
            self._server = None
