"""Worker entry for the programmatic ``horovod_tpu.run.run()`` API (parity:
``horovod/run/run_task.py``): unpickle the user function, execute it, PUT
the pickled return value into the launcher's rendezvous KV store under
``/result/rank.<N>``.
"""

from __future__ import annotations

import sys


def main() -> int:
    import cloudpickle

    from ..common import config as _config
    from .http.http_client import put_data_into_kvstore

    fn_path = sys.argv[1]
    with open(fn_path, "rb") as f:
        func, args, kwargs = cloudpickle.load(f)

    result = func(*args, **kwargs)

    addr = _config.rendezvous_addr()
    port = _config.rendezvous_port()
    if addr is None or port is None:
        raw_port = _config.rendezvous_port_string()
        # Distinguish "launcher never set the env" from "the env is set
        # but garbage": the old raw int() raised showing the bad value,
        # and losing that would send debugging in the wrong direction.
        detail = (f" ({_config.HOROVOD_RENDEZVOUS_PORT}={raw_port!r} is "
                  f"not a valid port)" if addr is not None and raw_port
                  else "; run it under horovodrun")
        raise RuntimeError(
            "task_fn requires the launcher's rendezvous env "
            f"({_config.HOROVOD_RENDEZVOUS_ADDR}/"
            f"{_config.HOROVOD_RENDEZVOUS_PORT}){detail}")
    rank = _config.rank()
    put_data_into_kvstore(addr, port, "result", f"rank.{rank}",
                          cloudpickle.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
