"""Worker entry for the programmatic ``horovod_tpu.run.run()`` API (parity:
``horovod/run/run_task.py``): unpickle the user function, execute it, PUT
the pickled return value into the launcher's rendezvous KV store under
``/result/rank.<N>``.
"""

from __future__ import annotations

import os
import sys


def main() -> int:
    import cloudpickle

    from ..common import config as _config
    from .http.http_client import put_data_into_kvstore

    fn_path = sys.argv[1]
    with open(fn_path, "rb") as f:
        func, args, kwargs = cloudpickle.load(f)

    result = func(*args, **kwargs)

    addr = os.environ[_config.HOROVOD_RENDEZVOUS_ADDR]
    port = int(os.environ[_config.HOROVOD_RENDEZVOUS_PORT])
    rank = os.environ.get(_config.HOROVOD_RANK, "0")
    put_data_into_kvstore(addr, port, "result", f"rank.{rank}",
                          cloudpickle.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
