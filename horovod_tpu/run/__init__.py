"""Launcher package (parity: ``horovod/run/``): the ``horovodrun`` CLI,
slot assignment, HTTP rendezvous, per-host worker spawn, elastic driver,
and the programmatic ``run()`` API.
"""

from .runner import main, parse_args, run, run_commandline  # noqa: F401
