"""``horovodrun``-equivalent CLI + programmatic launch API (parity:
``horovod/run/runner.py``).

``parse_args`` mirrors the reference's flag groups (``runner.py:218-484``):
basic np/hosts, tuning params (with the ``--no-*`` negation pairs),
autotune, timeline, elastic (incl. ``--elastic-timeout``), stall check
(``--stall-check``/``--no-stall-check``), logging, ssh. ``_run``
dispatches static vs elastic (``runner.py:790-811``) and
``choose_launcher`` reproduces ``run_controller``'s fallback matrix
(``runner.py:732-763``): forced ``--launcher`` choices validate their
prerequisites with descriptive errors, and ``auto`` detects
jsrun-under-LSF → ssh-for-remote-hosts → local fork. (There is no mpirun
to shell out to on TPU; the launcher slot keeps the reference's
pluggable pattern.)

Programmatic use (parity: ``horovod.run.run()``, ``runner.py:824+``)::

    from horovod_tpu.run import run
    results = run(train_fn, args=(1,), np=4)   # list of per-rank returns
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import tempfile
from typing import List, Optional

from ..common import config as _config
from ..version import __version__
from . import launch as _launch
from .common.util import config_parser, hosts as _hosts
from .http.http_server import RendezvousServer


def check_build(verbose: bool = False) -> str:
    """Capability report (parity: ``horovodrun --check-build``,
    reference ``runner.py:112-146``) — what this installation can
    actually drive, probed live rather than baked at compile time.
    Every probe is guarded: a diagnostic command must never crash on a
    corrupt .so or hang on a wedged accelerator tunnel."""
    def mark(flag):
        return "X" if flag else " "

    def importable(mod):
        try:
            __import__(mod)
            return True
        except Exception:
            return False

    try:
        from ..common import native as _native

        native_ok = _native.NativeCore().available
    except Exception:
        native_ok = False
    try:
        import jax  # noqa: F401

        xla_ok = True
    except Exception:
        xla_ok = False
    platform = None
    if verbose and xla_ok:
        # Backend init can hang indefinitely on a wedged TPU tunnel, and
        # enumeration alone answers even while all compute wedges
        # (docs/troubleshooting.md) — so this is a *compute* probe like
        # bench._probe_backend: enumerate (flushed) then run a fenced
        # jitted matmul, in a bounded subprocess. Partial output on
        # timeout tells the two failure modes apart.
        import subprocess

        code = ("import jax, jax.numpy as jnp; "
                "print('ENUM=' + jax.default_backend(), flush=True); "
                "x = jnp.ones((128, 128), jnp.bfloat16); "
                "v = float(jax.jit(lambda a: (a @ a).sum())(x)); "
                "assert v == v; "
                "print('COMPUTE=' + jax.default_backend())")
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True, timeout=60)
            out = r.stdout or ""
            if r.returncode == 0 and "COMPUTE=" in out:
                platform = out.rsplit("COMPUTE=", 1)[1].strip()
            else:
                platform = "unreachable"
        except subprocess.TimeoutExpired as e:
            out = e.stdout or ""
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            if "ENUM=" in out:
                platform = ("%s enumerated, but compute WEDGED (tunnel "
                            "in the known mid-compute wedge)"
                            % out.rsplit("ENUM=", 1)[1].strip())
            else:
                platform = "unreachable (backend init timed out)"

    lines = [
        f"horovod_tpu v{__version__}:",
        "",
        "Available Frameworks:",
        f"    [{mark(xla_ok)}] JAX (native SPMD)",
        f"    [{mark(importable('tensorflow'))}] TensorFlow",
        f"    [{mark(importable('torch'))}] PyTorch",
        f"    [{mark(importable('mxnet'))}] MXNet",
        "",
        "Available Controllers:",
        f"    [{mark(native_ok)}] native TCP star (libhvdtpu.so)",
        "    [X] direct (single-process)",
        "",
        "Available Tensor Operations:",
        f"    [{mark(xla_ok)}] XLA collectives (ICI/DCN)",
        f"    [{mark(native_ok)}] host TCP ring (allreduce/allgatherv/"
        "broadcast/Adasum VHDD)",
        f"    [{mark(native_ok and xla_ok)}] host-via-XLA staging "
        "(HOROVOD_HOST_VIA_XLA)",
        f"    [{mark(xla_ok)}] Pallas flash attention (fwd+bwd)",
    ]
    if platform:
        lines.append("")
        lines.append(f"Default JAX backend: {platform}")
    return "\n".join(lines)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="horovodrun",
        description="TPU-native Horovod-compatible launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-v", "--version", action="version",
                        version=__version__)
    parser.add_argument("-cb", "--check-build", action="store_true",
                        help="Print the installation's available "
                             "frameworks, controllers, and tensor "
                             "operations, then exit. Handled after the "
                             "full parse, so --verbose works in either "
                             "position.")

    parser.add_argument("-np", "--num-proc", type=int, dest="np",
                        help="Total number of training processes.")
    parser.add_argument("-p", "--ssh-port", type=int, dest="ssh_port",
                        help="SSH port on all hosts.")
    parser.add_argument("--disable-cache", action="store_true",
                        dest="disable_cache",
                        help="Disable the response cache.")
    parser.add_argument("--start-timeout", type=int, dest="start_timeout",
                        default=30,
                        help="Seconds to wait for all processes to start.")
    parser.add_argument("--network-interface", dest="nics",
                        help="Comma-separated NICs for the control plane.")
    parser.add_argument("--output-filename", dest="output_filename",
                        help="Redirect worker output to <dir>/rank.<N>")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--config-file", dest="config_file",
                        help="YAML config file (same schema as the "
                             "reference's horovodrun config).")

    group_hosts = parser.add_mutually_exclusive_group()
    group_hosts.add_argument("-H", "--hosts", dest="hosts",
                             help="host1:slots,host2:slots list.")
    group_hosts.add_argument("-hostfile", "--hostfile", dest="hostfile",
                             help="Hostfile with 'host slots=N' lines.")

    group_params = parser.add_argument_group("tuning parameter arguments")
    group_params.add_argument("--fusion-threshold-mb", type=int,
                              dest="fusion_threshold_mb",
                              help="Fusion buffer threshold in MB.")
    group_params.add_argument("--cycle-time-ms", type=float,
                              dest="cycle_time_ms",
                              help="Background cycle time in ms.")
    group_params.add_argument("--cache-capacity", type=int,
                              dest="cache_capacity",
                              help="Response cache capacity.")
    group_params.add_argument("--hierarchical-allreduce",
                              action="store_const", const=True,
                              dest="hierarchical_allreduce",
                              help="Force hierarchical (ICIxDCN) allreduce.")
    group_params.add_argument("--no-hierarchical-allreduce",
                              action="store_const", const=False,
                              dest="hierarchical_allreduce",
                              help="Force the flat allreduce path even "
                                   "when a hier mesh exists.")
    group_params.add_argument("--hierarchical-allgather",
                              action="store_const", const=True,
                              dest="hierarchical_allgather",
                              help="Force hierarchical allgather.")
    group_params.add_argument("--no-hierarchical-allgather",
                              action="store_const", const=False,
                              dest="hierarchical_allgather",
                              help="Force the flat allgather path.")

    group_autotune = parser.add_argument_group("autotune arguments")
    group_autotune.add_argument("--autotune", action="store_const",
                                const=True, dest="autotune")
    group_autotune.add_argument("--no-autotune", action="store_const",
                                const=False, dest="autotune")
    group_autotune.add_argument("--autotune-log-file",
                                dest="autotune_log_file")
    group_autotune.add_argument("--autotune-warmup-samples", type=int,
                                dest="autotune_warmup_samples")
    group_autotune.add_argument("--autotune-steps-per-sample", type=int,
                                dest="autotune_steps_per_sample")
    group_autotune.add_argument("--autotune-bayes-opt-max-samples", type=int,
                                dest="autotune_bayes_opt_max_samples")
    group_autotune.add_argument("--autotune-gaussian-process-noise",
                                type=float,
                                dest="autotune_gaussian_process_noise")

    group_timeline = parser.add_argument_group("timeline arguments")
    group_timeline.add_argument("--timeline-filename",
                                dest="timeline_filename",
                                help="Chrome-tracing JSON output path.")
    group_timeline.add_argument("--timeline-mark-cycles",
                                action="store_const", const=True,
                                dest="timeline_mark_cycles")
    group_timeline.add_argument("--no-timeline-mark-cycles",
                                action="store_const", const=False,
                                dest="timeline_mark_cycles")

    group_elastic = parser.add_argument_group("elastic arguments")
    group_elastic.add_argument("--min-np", type=int, dest="min_np",
                               help="Minimum processes (elastic).")
    group_elastic.add_argument("--max-np", type=int, dest="max_np",
                               help="Maximum processes (elastic).")
    group_elastic.add_argument("--slots-per-host", type=int, dest="slots",
                               help="Slots per discovered host (elastic).")
    group_elastic.add_argument("--host-discovery-script",
                               dest="host_discovery_script",
                               help="Script printing 'host:slots' lines; "
                                    "enables elastic mode.")
    group_elastic.add_argument("--blacklist-cooldown-range", type=int,
                               nargs=2, dest="blacklist_cooldown_range",
                               help="Min/max seconds before a blacklisted "
                                    "host may be retried.")
    group_elastic.add_argument("--elastic-timeout", type=int,
                               dest="elastic_timeout",
                               help="Seconds to wait for the elastic "
                                    "world to (re)assemble after a "
                                    "re-scaling event (reference "
                                    "runner.py:360; default 600).")

    group_stall = parser.add_argument_group("stall check arguments")
    group_stall.add_argument("--no-stall-check", action="store_const",
                             const=True, dest="no_stall_check")
    group_stall.add_argument("--stall-check", action="store_const",
                             const=False, dest="no_stall_check",
                             help="Explicitly enable the stall inspector "
                                  "(overrides a config-file disable).")
    group_stall.add_argument("--stall-check-warning-time-seconds", type=int,
                             dest="stall_check_warning_time_seconds")
    group_stall.add_argument("--stall-check-shutdown-time-seconds", type=int,
                             dest="stall_check_shutdown_time_seconds")

    group_log = parser.add_argument_group("logging arguments")
    group_log.add_argument("--log-level", dest="log_level",
                           choices=["TRACE", "DEBUG", "INFO", "WARNING",
                                    "ERROR", "FATAL"])
    group_log.add_argument("--log-hide-timestamp", action="store_const",
                           const=True, dest="log_hide_timestamp")
    group_log.add_argument("--no-log-hide-timestamp", action="store_const",
                           const=False, dest="log_hide_timestamp")

    group_lib = parser.add_argument_group("library arguments")
    group_lib.add_argument("--launcher", dest="launcher", default="auto",
                           choices=["auto", "local", "ssh", "jsrun"],
                           help="Worker launch transport (the reference's "
                                "gloo/mpi/jsrun slot).")
    # Reference-compat no-ops: collectives always run on XLA/native ring.
    group_lib.add_argument("--gloo", action="store_true", help=argparse.SUPPRESS)
    group_lib.add_argument("--mpi", action="store_true", help=argparse.SUPPRESS)

    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="Training command to run.")

    args = parser.parse_args(argv)
    # Track which flags the user set explicitly so the config file never
    # overrides the command line (parity: runner.py override_args).
    # "Explicit" = the parsed value differs from the parser's default —
    # this keeps 0/0.0 explicit AND counts the --no-* negations, whose
    # explicit value is False against a None default (tri-state flags:
    # None = unset, True/False = user-forced either way).
    args._override_args = {
        a.dest for a in parser._actions
        if a.dest not in ("command", "help")
        and getattr(args, a.dest, None) != parser.get_default(a.dest)
    }
    return args


def _hostnames(args) -> List[_hosts.HostInfo]:
    if getattr(args, "hostfile", None):
        return _hosts.parse_hosts(_hosts.parse_host_files(args.hostfile))
    hosts_str = getattr(args, "hosts", None) or \
        f"localhost:{args.np or 1}"
    return _hosts.parse_hosts(hosts_str)


def _controller_addr(host_alloc_plan) -> str:
    """The address workers use to reach the rank-0 coordination services."""
    first = host_alloc_plan[0].hostname
    if _launch.is_local(first):
        return "127.0.0.1"
    return first


def _launcher_addr(plan, nics=None) -> str:
    """Address where workers reach launcher-side services (rendezvous).

    ``nics`` (the --network-interface allowlist, comma string or
    iterable) pins the advertised address to a named interface — the
    reference's NIC-restriction knob (``run/runner.py`` --network-
    interface + the driver service's interface intersection)."""
    if all(_launch.is_local(s.hostname) for s in plan):
        return "127.0.0.1"
    if nics:
        from .common.util.network import get_local_addresses

        allowed = ({n.strip() for n in nics.split(",") if n.strip()}
                   if isinstance(nics, str) else set(nics))
        for name, ip in get_local_addresses():
            if name in allowed:
                return ip
        raise ValueError(
            f"--network-interface {sorted(allowed)} matched no local "
            "interface with an IPv4 address")
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return socket.gethostname()


def _job_env(args, base_env: Optional[dict] = None) -> dict:
    """CLI-flag → env mapping shared by every launch flavor."""
    env = dict(base_env if base_env is not None else os.environ)
    config_parser.set_env_from_args(env, args)
    if getattr(args, "disable_cache", False):
        env[_config.HOROVOD_CACHE_CAPACITY] = "0"
    if getattr(args, "min_np", None):
        env[_config.HOROVOD_ELASTIC] = "1"
    return env


def _run_static(args, command: List[str], base_env: Optional[dict] = None,
                collect=None) -> int:
    hosts = _hostnames(args)
    np_ = args.np or sum(h.slots for h in hosts)
    plan = _hosts.get_host_assignments(hosts, np_)

    # Fail fast with named hosts before any worker launches (reference
    # runner.py:641-648 ssh check). Probe only hosts the plan actually
    # assigns ranks to — trailing unused hosts must not block a launch.
    _launch.check_ssh_all_hosts({s.hostname for s in plan},
                                ssh_port=getattr(args, "ssh_port", None))

    rendezvous = RendezvousServer(verbose=1 if args.verbose else 0)
    rendezvous_port = rendezvous.start_server()
    rendezvous.init(plan)
    controller_port = _launch.free_port()
    addr = _controller_addr(plan)

    env = _job_env(args, base_env)

    try:
        codes = _launch.launch_workers(
            plan, command, controller_addr=addr,
            controller_port=controller_port,
            rendezvous_addr=_launcher_addr(
                plan, getattr(args, 'nics', None)),
            rendezvous_port=rendezvous_port,
            ssh_port=getattr(args, "ssh_port", None), base_env=env,
            output_filename=getattr(args, "output_filename", None))
        if collect is not None and max(codes, default=1) == 0:
            collect(rendezvous, np_)
    finally:
        rendezvous.stop_server()
    return max(codes) if codes else 0


def _run_elastic(args, command: List[str],
                 base_env: Optional[dict] = None) -> int:
    from .elastic.runner import run_elastic

    return run_elastic(args, command, base_env)


def choose_launcher(args, hosts: List[_hosts.HostInfo]) -> str:
    """Pick the worker-launch transport (the reference's
    ``run_controller`` gloo→mpi→jsrun fallback matrix,
    ``run/runner.py:732-763``, mapped to this launcher's slots):

    - forced choices (``--launcher jsrun/ssh/local``) are validated and
      fail with a descriptive error when their prerequisite is missing
      (the reference's "Gloo support has not been built" pattern);
    - ``auto`` detects: **jsrun** inside an LSF allocation with the
      binary installed → **ssh** when the host plan reaches remote
      hosts → **local** fork otherwise.
    """
    from . import js_run
    from .util.lsf import LSFUtils

    choice = getattr(args, "launcher", "auto") or "auto"
    remote = sorted({h.hostname for h in hosts
                     if not _launch.is_local(h.hostname)})
    if choice == "jsrun":
        if not LSFUtils.using_lsf():
            raise ValueError(
                "--launcher jsrun requested but this process is not "
                "inside an LSF allocation (LSB_* env missing); run under "
                "bsub or use --launcher ssh/local")
        if not js_run.is_jsrun_installed():
            raise ValueError(
                "--launcher jsrun requested but the jsrun binary is not "
                "on PATH")
        return "jsrun"
    if choice == "local":
        if remote:
            raise ValueError(
                "--launcher local requested but the host plan reaches "
                f"remote hosts {remote[:3]}; use --launcher ssh")
        return "local"
    if choice == "ssh":
        return "ssh"
    # auto: scheduler first, then topology.
    if LSFUtils.using_lsf() and js_run.is_jsrun_installed():
        return "jsrun"
    return "ssh" if remote else "local"


def _run(args) -> int:
    if getattr(args, "check_build", False):
        print(check_build(verbose=getattr(args, "verbose", False)))
        return 0
    config_parser.load_config_file(args, getattr(args, "_override_args",
                                                 set()))
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        raise ValueError("no training command given")
    if getattr(args, "host_discovery_script", None) or \
            getattr(args, "min_np", None):
        # Elastic: discovery script, or fixed hosts with --min-np (the
        # reference's FixedHosts flavor, run/elastic/discovery.py).
        return _run_elastic(args, command)
    # LSF defaults (parity: runner.py:790 _run LSF branch): inside an
    # allocation the host list and np come from the scheduler.
    from .util.lsf import LSFUtils

    if LSFUtils.using_lsf() and not (args.hosts or args.hostfile):
        args.hosts = LSFUtils.get_hosts_string()
        if args.np is None:
            args.np = LSFUtils.get_num_processes()
    if args.np is None and not (args.hosts or args.hostfile):
        raise ValueError("-np (or -H/--hostfile) is required")
    launcher = choose_launcher(args, _hostnames(args))
    if args.verbose:
        print(f"hvdrun: using the {launcher} launcher", file=sys.stderr)
    if launcher == "jsrun":
        return _run_jsrun(args, command)
    return _run_static(args, command)


def _run_jsrun(args, command: List[str]) -> int:
    """Launch through LSF's jsrun (parity: ``run/js_run.py``): one jsrun
    invocation with an ERF rankfile; workers pick ranks up from the
    JSM/PMIX env and rendezvous over HTTP as usual."""
    from . import js_run

    hosts = _hostnames(args)
    np_ = args.np or sum(h.slots for h in hosts)
    plan = _hosts.get_host_assignments(hosts, np_)
    rendezvous = RendezvousServer(verbose=1 if args.verbose else 0)
    rendezvous_port = rendezvous.start_server()
    rendezvous.init(plan)

    env = _job_env(args)
    env[_config.HOROVOD_SIZE] = str(np_)
    env[_config.HOROVOD_RENDEZVOUS_ADDR] = _launcher_addr(
        plan, getattr(args, 'nics', None))
    env[_config.HOROVOD_RENDEZVOUS_PORT] = str(rendezvous_port)
    env[_config.HOROVOD_CONTROLLER_ADDR] = _controller_addr(plan)
    env[_config.HOROVOD_CONTROLLER_PORT] = str(_launch.free_port())
    # Rank order in the ERF must match the runner's plan, and the world is
    # exactly np_ ranks even if the allocation is larger.
    plan_hosts: dict = {}
    for slot in plan:
        plan_hosts[slot.hostname] = plan_hosts.get(slot.hostname, 0) + 1
    try:
        return js_run.js_run(
            np_, command, hosts=plan_hosts, env=env,
            output_filename=getattr(args, "output_filename", None),
            verbose=args.verbose)
    finally:
        rendezvous.stop_server()


def run_commandline(argv: Optional[List[str]] = None) -> int:
    return _run(parse_args(argv))


def main() -> None:
    sys.exit(run_commandline())


# ---- programmatic API (parity: horovod.run.run, runner.py:824+) ------------


def run(func, args=(), kwargs=None, np: int = 1,
        hosts: Optional[str] = None, hostfile: Optional[str] = None,
        ssh_port: Optional[int] = None, verbose: bool = False,
        use_cloudpickle: bool = True, env: Optional[dict] = None,
        output_filename: Optional[str] = None,
        network_interface: Optional[str] = None,
        start_timeout: int = 30, disable_cache: bool = False):
    """Run ``func(*args, **kwargs)`` on ``np`` ranks; return the list of
    per-rank return values in rank order (parity:
    ``horovod.run.run()``, reference ``runner.py:824+`` — the
    network_interface/start_timeout/disable_cache knobs mirror the CLI
    flags of the same names)."""
    import cloudpickle

    with tempfile.TemporaryDirectory(prefix="hvdrun_") as tmpdir:
        fn_path = os.path.join(tmpdir, "func.pkl")
        with open(fn_path, "wb") as f:
            cloudpickle.dump((func, tuple(args), dict(kwargs or {})), f)

        ns = argparse.Namespace(
            np=np, hosts=hosts, hostfile=hostfile, ssh_port=ssh_port,
            verbose=verbose, disable_cache=disable_cache, config_file=None,
            min_np=None, output_filename=output_filename,
            start_timeout=start_timeout, nics=network_interface,
            launcher="auto")
        command = [sys.executable, "-m", "horovod_tpu.run.task_fn", fn_path]
        base_env = dict(env if env is not None else os.environ)
        base_env.setdefault("PYTHONPATH", os.pathsep.join(
            p for p in sys.path if p))

        results = [None] * np

        def collect(rendezvous, np_):
            # Workers PUT their pickled return value under /result/rank.N
            # before exiting (task_fn), so by the time launch_workers
            # returns the store is fully populated.
            for r in range(np_):
                blob = rendezvous.get("result", f"rank.{r}")
                if blob is None:
                    raise RuntimeError(f"rank {r} returned no result")
                results[r] = cloudpickle.loads(blob)

        code = _run_static(ns, command, base_env, collect=collect)
        if code != 0:
            raise RuntimeError(f"horovod_tpu.run.run failed with exit code "
                               f"{code}")
        return results
