"""Per-slot worker launch (parity: ``horovod/run/gloo_run.py:64-99,183-259``).

The launcher computes slot assignments, starts the HTTP rendezvous, and
spawns one process per slot — locally via fork/exec, remotely via ssh —
with the full ``HOROVOD_*`` topology env exported, exactly as the
reference's gloo launcher does. The coordination endpoint
(``HOROVOD_CONTROLLER_ADDR/PORT``) points at the rank-0 host: the native
controller (csrc) binds ``port+1`` in the rank-0 process and
``jax.distributed`` uses ``port``, replacing the reference's Gloo
rendezvous + MPI comm world.
"""

from __future__ import annotations

import os
import shlex
import socket
import sys
import threading
from typing import Dict, List, Optional

from ..common import config as _config
from .common.util import safe_shell_exec
from .common.util.hosts import SlotInfo

LOCAL_HOSTNAMES = {"localhost", "127.0.0.1", "::1"}

SSH_COMMAND_PREFIX = "ssh -o PasswordAuthentication=no -o " \
                     "StrictHostKeyChecking=no"


def is_local(hostname: str) -> bool:
    if hostname in LOCAL_HOSTNAMES:
        return True
    try:
        return hostname in (socket.gethostname(), socket.getfqdn())
    except OSError:
        return False


def free_port() -> int:
    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def slot_env(slot: SlotInfo, controller_addr: str, controller_port: int,
             rendezvous_addr: str, rendezvous_port: int,
             base_env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """The env block a worker needs to join the world (parity: env names
    read by the reference's gloo context, ``gloo_context.cc:40-54``)."""
    env = dict(base_env if base_env is not None else os.environ)
    env[_config.HOROVOD_RANK] = str(slot.rank)
    env[_config.HOROVOD_SIZE] = str(slot.size)
    env[_config.HOROVOD_LOCAL_RANK] = str(slot.local_rank)
    env[_config.HOROVOD_LOCAL_SIZE] = str(slot.local_size)
    env[_config.HOROVOD_CROSS_RANK] = str(slot.cross_rank)
    env[_config.HOROVOD_CROSS_SIZE] = str(slot.cross_size)
    env[_config.HOROVOD_CONTROLLER_ADDR] = controller_addr
    env[_config.HOROVOD_CONTROLLER_PORT] = str(controller_port)
    env[_config.HOROVOD_RENDEZVOUS_ADDR] = rendezvous_addr
    env[_config.HOROVOD_RENDEZVOUS_PORT] = str(rendezvous_port)
    env["HOROVOD_HOSTNAME"] = slot.hostname
    return env


def build_worker_command(slot: SlotInfo, command: List[str],
                         env: Dict[str, str], ssh_port: Optional[int] = None):
    """argv (local) or ssh command string (remote) for one slot (parity:
    ``gloo_run.py:64-99`` get_remote_command)."""
    if is_local(slot.hostname):
        return command
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in sorted(env.items())
        if k.startswith("HOROVOD_") or k in (
            "PATH", "PYTHONPATH", "JAX_PLATFORMS", "TPU_WORKER_ID"))
    port_arg = f" -p {ssh_port}" if ssh_port else ""
    remote = f"cd {shlex.quote(os.getcwd())} > /dev/null 2>&1 ; " \
             f"env {exports} {' '.join(shlex.quote(c) for c in command)}"
    return f"{SSH_COMMAND_PREFIX}{port_arg} {slot.hostname} " \
           f"{shlex.quote(remote)}"


def check_ssh_all_hosts(hostnames, ssh_port: Optional[int] = None,
                        timeout: float = 15.0) -> None:
    """Preflight: every remote host must be reachable over passwordless
    ssh BEFORE any worker launches (reference ``runner.py:641-648`` —
    failing one rank mid-launch leaves the rest to time out at
    rendezvous; failing fast here names the broken hosts instead).
    Probes run in parallel; raises listing every unreachable host."""
    import concurrent.futures
    import subprocess

    remote = sorted({h for h in hostnames if not is_local(h)})
    if not remote:
        return

    def probe(host):
        port_args = ["-p", str(ssh_port)] if ssh_port else []
        cmd = (SSH_COMMAND_PREFIX.split() + port_args
               + ["-o", "BatchMode=yes",
                  "-o", f"ConnectTimeout={int(timeout)}", host, "true"])
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=timeout + 5)
            return host, r.returncode == 0, (r.stderr or "").strip()
        except subprocess.TimeoutExpired:
            return host, False, f"ssh timed out after {timeout:.0f}s"

    with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(16, len(remote))) as pool:
        results = list(pool.map(probe, remote))
    bad = [(h, msg) for h, ok, msg in results if not ok]
    if bad:
        detail = "; ".join(f"{h}: {msg or 'ssh failed'}" for h, msg in bad)
        raise RuntimeError(
            f"ssh preflight failed for {len(bad)}/{len(remote)} remote "
            f"host(s) — {detail}. Passwordless ssh to every host is "
            "required (reference horovodrun contract).")


def execute_redirected(cmd, env, events, output_dir: str, rank: int,
                       mode: str = "w") -> int:
    """Run a worker with stdout/stderr redirected to
    ``<output_dir>/rank.<rank>/stdout|stderr`` (reference
    ``--output-filename`` layout). ``mode="a"`` lets elastic re-staffed
    slots append across lives instead of erasing their predecessor's
    output."""
    rank_dir = os.path.join(output_dir, f"rank.{rank}")
    os.makedirs(rank_dir, exist_ok=True)
    with open(os.path.join(rank_dir, "stdout"), mode) as out_f, \
            open(os.path.join(rank_dir, "stderr"), mode) as err_f:
        return safe_shell_exec.execute(
            cmd, env=env, events=events, prefix=None,
            stdout=out_f, stderr=err_f)


def launch_workers(host_alloc_plan: List[SlotInfo], command: List[str],
                   controller_addr: str, controller_port: int,
                   rendezvous_addr: str, rendezvous_port: int,
                   ssh_port: Optional[int] = None,
                   base_env: Optional[Dict[str, str]] = None,
                   events: Optional[List[threading.Event]] = None,
                   prefix_output: bool = True,
                   output_filename: Optional[str] = None) -> List[int]:
    """Spawn every slot's worker, pump output, return exit codes in rank
    order. One failing worker triggers termination of the rest (parity:
    ``gloo_run.py:183-259`` launch + MultiFileWriter behavior). With
    ``output_filename`` set, each rank's stdout/stderr go to
    ``<dir>/rank.<N>/stdout|stderr`` instead of the launcher's streams
    (reference ``--output-filename`` semantics)."""
    exit_codes: List[Optional[int]] = [None] * len(host_alloc_plan)
    abort = threading.Event()
    all_events = list(events or []) + [abort]
    threads = []

    # One shared per-job key: the native controller rejects hellos carrying
    # a different key, so two jobs colliding on a default controller port
    # fail loudly instead of cross-connecting.
    base_env = dict(base_env if base_env is not None else os.environ)
    base_env.setdefault("HOROVOD_JOB_KEY", os.urandom(8).hex())

    def run_slot(i: int, slot: SlotInfo):
        # Any launch-side failure (unwritable --output-filename dir, exec
        # error) must count as this rank failing and abort the rest —
        # an escaped exception would leave peers blocked in rendezvous
        # forever waiting for a rank that never comes up.
        try:
            env = slot_env(slot, controller_addr, controller_port,
                           rendezvous_addr, rendezvous_port, base_env)
            cmd = build_worker_command(slot, command, env, ssh_port)
            if output_filename:
                code = execute_redirected(cmd, env, all_events,
                                          output_filename, slot.rank)
            else:
                code = safe_shell_exec.execute(
                    cmd, env=env, events=all_events,
                    prefix=f"{slot.rank}" if prefix_output else None,
                    stdout=sys.stdout, stderr=sys.stderr)
        except Exception as e:
            print(f"[launcher] rank {slot.rank} failed to launch: {e}",
                  file=sys.stderr)
            code = 1
        exit_codes[i] = code
        if code != 0:
            abort.set()

    for i, slot in enumerate(host_alloc_plan):
        t = threading.Thread(target=run_slot, args=(i, slot), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    return [c if c is not None else 1 for c in exit_codes]
