"""Pre-launch driver/task services (parity:
``horovod/run/common/service/driver_service.py:43`` + ``task_service.py``).

Before spawning ranks across hosts, the launcher can probe connectivity:
a ``HorovodRunDriverService`` runs on the launch host; one
``HorovodRunTaskService`` per target host registers its reachable
addresses back, giving the driver a routable interface set (the
reference's NIC-discovery round). On TPU pods the VM metadata usually
answers this, so the probe is optional — but the service pair is also the
transport for Spark-style integrations.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ...common import config as _config
from ..common.util import network


class RegisterTaskRequest:
    def __init__(self, index: int, task_addresses: List[Tuple[str, int]]):
        self.index = index
        self.task_addresses = task_addresses


class AllTaskAddressesRequest:
    def __init__(self, index: int):
        self.index = index


class AllTaskAddressesResponse:
    def __init__(self, all_task_addresses: Dict[int, List[Tuple[str, int]]]):
        self.all_task_addresses = all_task_addresses


class TaskIndexRequest:
    def __init__(self, hostname: str):
        self.hostname = hostname


class TaskIndexResponse:
    def __init__(self, index: int):
        self.index = index


class HorovodRunDriverService(network.BasicService):
    NAME = "horovodrun driver service"

    def __init__(self, num_hosts: int, key: bytes, nics=None):
        super().__init__(self.NAME, key, nics)
        self._num_hosts = num_hosts
        self._all_task_addresses: Dict[int, List[Tuple[str, int]]] = {}
        self._hostnames: Dict[str, int] = {}
        self._wait_cond = threading.Condition()

    def _handle(self, req, client_address):
        if isinstance(req, RegisterTaskRequest):
            with self._wait_cond:
                self._all_task_addresses[req.index] = req.task_addresses
                self._wait_cond.notify_all()
            return network.AckResponse()
        if isinstance(req, AllTaskAddressesRequest):
            return AllTaskAddressesResponse(dict(self._all_task_addresses))
        if isinstance(req, TaskIndexRequest):
            with self._wait_cond:
                if req.hostname not in self._hostnames:
                    self._hostnames[req.hostname] = len(self._hostnames)
            return TaskIndexResponse(self._hostnames[req.hostname])
        return super()._handle(req, client_address)

    def wait_for_initial_registration(
            self, timeout: Optional[float] = None) -> None:
        """Block until every host registered. The default deadline is the
        ``DRIVER`` retry scope's (``HOROVOD_RETRY_DRIVER_DEADLINE``,
        coded default 30 s; 0 = wait forever, per the RetryPolicy
        sentinel) — slow-provisioning pods tune the env instead of
        patching call sites."""
        if timeout is None:
            timeout = _config.retry_policy_from_env(
                "DRIVER", deadline=30.0).deadline
            if timeout <= 0:
                timeout = None  # deadline=0 means unbounded, not instant
        with self._wait_cond:
            ok = self._wait_cond.wait_for(
                lambda: len(self._all_task_addresses) >= self._num_hosts,
                timeout=timeout)
        if not ok:
            raise TimeoutError(
                f"only {len(self._all_task_addresses)}/{self._num_hosts} "
                "hosts registered with the driver service")

    def task_addresses_for_driver(self, index: int):
        return self._all_task_addresses.get(index)


class HorovodRunTaskService(network.BasicService):
    NAME_FMT = "horovodrun task service #%d"

    def __init__(self, index: int, key: bytes, nics=None):
        super().__init__(self.NAME_FMT % index, key, nics)
        self.index = index


def probe_routable_addresses(addresses: List[Tuple[str, int]],
                             service_name: str, key: bytes,
                             timeout: Optional[float] = None
                             ) -> List[Tuple[str, int]]:
    """The subset of a service's advertised (ip, port) pairs the caller
    can actually reach (authenticated ping round-trip). The per-address
    connect timeout comes from the ``PROBE`` retry scope
    (``HOROVOD_RETRY_PROBE_DEADLINE``, coded default 2 s) when the
    caller didn't pass one; an explicit ``timeout`` is a call-site
    contract and pinned against env override. Probes are single-attempt
    by design (pinned): a dead address must cost one bounded connect,
    not an env-inflated retry storm per NIC."""
    policy = _config.retry_policy_from_env(
        "PROBE",
        pinned=("max_attempts",) + (
            ("deadline",) if timeout is not None else ()),
        deadline=timeout if timeout is not None else 2.0,
        max_attempts=1)
    # RetryPolicy's deadline=0 sentinel means "no deadline", but a probe
    # must stay bounded — and 0 passed as a socket timeout would mean
    # non-blocking connects that fail every healthy address.
    probe_timeout = policy.deadline if policy.deadline > 0 else 2.0
    reachable = []
    for addr in addresses:
        try:
            network.BasicClient(service_name, [addr], key,
                                probe_timeout=probe_timeout,
                                attempts=max(1, policy.max_attempts))
            reachable.append(addr)
        except (ConnectionError, OSError):
            continue
    return reachable


def get_common_interfaces(driver: "HorovodRunDriverService",
                          num_hosts: int, key: bytes,
                          timeout: Optional[float] = None
                          ) -> Dict[int, List[Tuple[str, int]]]:
    """Routable address set per registered task host (parity:
    ``run/common/service/driver_service.py:43`` NIC-intersection round):
    every task advertised one address per local interface; the driver
    probes them all and keeps the routable subset, so later launch traffic
    (ssh targets, rendezvous endpoints) only uses interfaces that actually
    carry driver<->host traffic. Hosts with zero routable addresses raise
    — the reference fails the launch for the same reason."""
    routable: Dict[int, List[Tuple[str, int]]] = {}
    for index in range(num_hosts):
        addrs = driver.task_addresses_for_driver(index)
        if addrs is None:
            raise RuntimeError(f"host index {index} never registered")
        if num_hosts > 1:
            # Loopback is trivially routable from a co-located driver but
            # useless to every OTHER host; exclude it so consumers can
            # take any returned address (the reference's NIC intersection
            # excludes lo for the same reason).
            addrs = [a for a in addrs if not a[0].startswith("127.")]
        ok = probe_routable_addresses(
            addrs, HorovodRunTaskService.NAME_FMT % index, key,
            timeout=timeout)
        if not ok:
            raise RuntimeError(
                f"no routable interface to host index {index} "
                f"(advertised: {addrs})")
        routable[index] = ok
    return routable


class HorovodRunDriverClient(network.BasicClient):
    def __init__(self, addresses, key):
        super().__init__(HorovodRunDriverService.NAME, addresses, key)

    def register_task(self, index: int,
                      task_addresses: List[Tuple[str, int]]) -> None:
        self._request(RegisterTaskRequest(index, task_addresses))

    def all_task_addresses(self, index: int = 0):
        resp = self._request(AllTaskAddressesRequest(index))
        return resp.all_task_addresses

    def task_index(self, hostname: str) -> int:
        return self._request(TaskIndexRequest(hostname)).index
