"""Gradient compression (parity: ``horovod/torch/compression.py:46``).

Thin binding over the tree-wide compressor implementation
(``horovod_tpu/common/compression.py``): this module only supplies the
torch cast primitives; the compress/decompress logic — and the wire
format policy (fp16 for reference-script compatibility, bfloat16 as the
MXU-native TPU extension) — lives in one place.
"""

import torch

from ..common.compression import make_framework_compression

_WIRE = {"float16": torch.float16, "bfloat16": torch.bfloat16}

Compression = make_framework_compression(
    cast=lambda tensor, dtype: tensor.type(_WIRE.get(dtype, dtype)),
    is_floating=lambda tensor: tensor.dtype.is_floating_point,
)

# Reference-compatible module-level names.
Compressor = Compression.Compressor
NoneCompressor = Compression.none
FP16Compressor = Compression.fp16
BF16Compressor = Compression.bf16
