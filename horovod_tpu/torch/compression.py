"""Gradient compression (parity: ``horovod/torch/compression.py:46``).

On TPU the natural wire format is bfloat16 (MXU-native); fp16 is kept for
reference-script compatibility.
"""

import torch


class Compressor:
    """Interface: ``compress(tensor) -> (tensor, ctx)``,
    ``decompress(tensor, ctx) -> tensor``."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point:
            return tensor.type(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.type(ctx) if ctx is not None else tensor


class BF16Compressor(Compressor):
    """TPU-native extension: bfloat16 wire format (same exponent range as
    fp32, no overflow scaling needed)."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point:
            return tensor.type(torch.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.type(ctx) if ctx is not None else tensor


class Compression:
    """Option enum (parity: reference ``Compression.none`` /
    ``Compression.fp16``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
