"""``import horovod_tpu.torch as hvd`` — the PyTorch binding.

Mirrors the reference's ``horovod/torch/__init__.py`` public surface:
init/rank/size family, allreduce/allgather/broadcast (+async/in-place),
``DistributedOptimizer``, broadcast_parameters/optimizer_state/object,
``Compression``, ``SyncBatchNorm``, and ``hvd.elastic`` — on the native
TCP-ring host plane (see ``mpi_ops.py`` for the architecture note).
"""

from ..common.exceptions import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from ..common.state import (  # noqa: F401
    ccl_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    xla_built,
)
from .mpi_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    ReduceOp,
    Sum,
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    barrier,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    join,
    local_rank,
    local_size,
    poll,
    rank,
    shutdown,
    size,
    synchronize,
)
from .. import (  # noqa: F401
    liveness_report,
    metrics,
    metrics_report,
    ring_traffic,
    stall_report,
)
from .compression import Compression  # noqa: F401
from .functions import (  # noqa: F401
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
)
from .optimizer import DistributedOptimizer  # noqa: F401
from .sync_batch_norm import SyncBatchNorm  # noqa: F401
from . import elastic  # noqa: F401
