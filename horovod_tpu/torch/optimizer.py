"""DistributedOptimizer for PyTorch (parity: ``torch/optimizer.py:31-421``).

Wraps any ``torch.optim.Optimizer`` so that gradients are allreduced across
process ranks as they become ready during ``backward()``: each parameter
gets a post-accumulate-grad hook that enqueues an async in-place allreduce,
and ``step()`` synchronizes all outstanding handles first. Communication
overlaps with the rest of backprop exactly as in the reference's
grad-accumulator-hook design; the transport is the native TCP ring (host
plane) instead of MPI/NCCL.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

import torch

from . import mpi_ops as _ops
from .compression import Compression
from .mpi_ops import Adasum, Average, Sum


class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step=1, op=Average,
                 gradient_predivide_factor=1.0):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self.op = op
        self.backward_passes_per_step = backward_passes_per_step
        self.gradient_predivide_factor = gradient_predivide_factor

        if named_parameters is not None:
            named_parameters = list(named_parameters)
            all_params = {
                p for group in self.param_groups for p in group["params"]}
            named = {p for _, p in named_parameters}
            unnamed = all_params - named
            if unnamed:
                raise ValueError(
                    "named_parameters was specified but one or more model "
                    "parameters were not named (parity check, reference "
                    "torch/optimizer.py:51-68)")
            if len({name for name, _ in named_parameters}) < len(
                    named_parameters):
                raise ValueError("parameter names must be unique")
            self._parameter_names = {p: name for name, p in named_parameters}
        else:
            self._parameter_names = {
                p: f"allreduce.noname.{gi}.{pi}"
                for gi, group in enumerate(self.param_groups)
                for pi, p in enumerate(group["params"])
            }

        self._handles = {}
        self._allreduce_delay = {}
        self._grad_accs = []  # keepalive for legacy hook path
        self._hook_handles = []
        self._requires_update = set()
        self._synchronized = False
        self._should_synchronize = True
        if _ops.size() > 1:
            self._register_hooks()

    # -- hooks ---------------------------------------------------------------

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if not p.requires_grad:
                    continue
                self._requires_update.add(p)
                self._allreduce_delay[p] = self.backward_passes_per_step
                if hasattr(p, "register_post_accumulate_grad_hook"):
                    h = p.register_post_accumulate_grad_hook(
                        self._make_post_hook(p))
                    self._hook_handles.append(h)
                else:  # pragma: no cover - old torch
                    p_tmp = p.expand_as(p)
                    grad_acc = p_tmp.grad_fn.next_functions[0][0]
                    grad_acc.register_hook(self._make_legacy_hook(p))
                    self._grad_accs.append(grad_acc)

    def _make_post_hook(self, p):
        def hook(param):
            self._on_grad_ready(p)

        return hook

    def _make_legacy_hook(self, p):  # pragma: no cover - old torch
        def hook(*ignore):
            self._on_grad_ready(p)

        return hook

    def _on_grad_ready(self, p):
        if p.grad is None:
            return
        if p in self._handles and self._handles[p][0] is not None:
            if self._allreduce_delay[p] <= 0:
                raise AssertionError(
                    "Gradients were computed more than "
                    "backward_passes_per_step times before call to step(). "
                    "Increase backward_passes_per_step to accumulate "
                    "gradients locally.")
        assert not p.grad.requires_grad
        assert self._allreduce_delay[p] > 0
        self._allreduce_delay[p] -= 1
        if self._allreduce_delay[p] == 0:
            self._handles[p] = self._allreduce_grad_async(p)

    def _allreduce_grad_async(self, p):
        name = self._parameter_names.get(p)
        tensor = p.grad
        # Average via Sum with prescale=1/factor, postscale=factor/size:
        # net scale is always 1/size, but the split controls fp dynamic
        # range when gradient_predivide_factor is used (parity: reference
        # divisor logic, torch/mpi_ops.py:91-129).
        prescale = 1.0
        postscale = 1.0
        op = self.op
        if op == Average:
            op = Sum
            prescale = 1.0 / self.gradient_predivide_factor
            postscale = self.gradient_predivide_factor / _ops.size()
        elif op == Adasum:
            pass
        tensor_compressed, ctx = self._compression.compress(tensor)
        handle = _ops.allreduce_async_(
            tensor_compressed, name=name, op=op, prescale_factor=prescale,
            postscale_factor=postscale)
        return handle, (tensor_compressed, ctx)

    # -- synchronization -----------------------------------------------------

    def synchronize(self):
        """Complete all outstanding allreduces (parity:
        ``torch/optimizer.py:137-175``)."""
        missing = [p for p in self._requires_update if p not in self._handles]
        for p in missing:
            # Parameters whose hooks never fired this step (e.g. unused
            # branches): allreduce their current grads now.
            if p.grad is None:
                p.grad = p.data.new_zeros(p.data.shape)
            self._allreduce_delay[p] = 0
            self._handles[p] = self._allreduce_grad_async(p)
        for p, (handle, (compressed, ctx)) in list(self._handles.items()):
            if handle is None:
                continue
            output = _ops.synchronize(handle)
            self._allreduce_delay[p] = self.backward_passes_per_step
            p.grad.copy_(self._compression.decompress(output, ctx))
        self._handles.clear()
        self._synchronized = True

    @contextmanager
    def skip_synchronize(self):
        """Use when calling ``synchronize()`` manually before ``step()``."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                import warnings

                warnings.warn(
                    "optimizer.step() called without wrapping it in "
                    "optimizer.skip_synchronize() after a manual "
                    "synchronize(); this can cause training slowdown")
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step() or optimizer.synchronize(). This "
                "is prohibited as it can cause a race condition. (parity: "
                "reference torch/optimizer.py:189-194)")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


class _DistributedAdasumOptimizer(torch.optim.Optimizer):
    """Adasum *delta* flavor (parity: ``torch/optimizer.py:197-365``): the
    inner optimizer computes a local parameter delta, deltas are combined
    across ranks with the scaling-insensitive Adasum operator, and the
    combined delta is applied to the start-of-step parameters."""

    def __init__(self, params, compression=Compression.none):
        super(self.__class__, self).__init__(params)
        self._compression = compression

    def step(self, closure=None):
        starts = {}
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    starts[p] = p.data.clone()
        loss = super(self.__class__, self).step(closure)
        if _ops.size() > 1:
            handles = []
            for gi, group in enumerate(self.param_groups):
                for pi, p in enumerate(group["params"]):
                    if p not in starts:
                        continue
                    delta = p.data - starts[p]
                    compressed, ctx = self._compression.compress(delta)
                    h = _ops.allreduce_async(
                        compressed, name=f"adasum.delta.{gi}.{pi}",
                        op=Adasum)
                    handles.append((p, h, ctx))
            for p, h, ctx in handles:
                delta = self._compression.decompress(_ops.synchronize(h), ctx)
                p.data.copy_(starts[p] + delta)
        return loss


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: int = Average,
                         gradient_predivide_factor: float = 1.0):
    """Wrap ``optimizer`` for distributed gradient averaging (parity:
    ``hvd.DistributedOptimizer``, reference ``torch/optimizer.py:368-421``).

    ``op=hvd.Adasum`` selects the delta-based Adasum optimizer."""
    if gradient_predivide_factor != 1.0 and op != Average:
        raise ValueError(
            "gradient_predivide_factor not supported with op != Average")
    if op != Adasum:
        cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
                   dict(_DistributedOptimizer.__dict__))
        return cls(optimizer.param_groups, named_parameters, compression,
                   backward_passes_per_step, op, gradient_predivide_factor)
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedAdasumOptimizer.__dict__))
    return cls(optimizer.param_groups, compression)
