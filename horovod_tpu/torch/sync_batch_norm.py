"""Cross-rank synchronized BatchNorm (parity: ``torch/sync_batch_norm.py``).

Batch statistics are combined across all process ranks with allreduce of
(count, sum, sum-of-squares) in fp32, and the backward pass allreduces the
two gradient sums — the same math as the reference's
``_SyncBatchNorm`` autograd function, carried by the native ring instead of
MPI/NCCL.
"""

from __future__ import annotations

import torch
from torch.nn.modules.batchnorm import _BatchNorm

from . import mpi_ops as _ops
from .mpi_ops import Sum


class SyncBatchNorm(_BatchNorm):
    """Applies synchronized Batch Normalization over the global batch.

    Drop-in for ``torch.nn.BatchNorm{1,2,3}d`` in distributed data-parallel
    training; statistics are computed over the batch slices of *all*
    ranks."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D input)")

    def forward(self, input):
        # Fall back to local BN only in eval mode with tracked stats
        # (parity: reference condition, torch/sync_batch_norm.py:55) or at
        # size 1 where there is nothing to synchronize.
        if (not self.training and self.track_running_stats) or \
                _ops.size() == 1:
            return super().forward(input)
        self._check_input_dim(input)
        if self.momentum is None:
            exponential_average_factor = 0.0
        else:
            exponential_average_factor = self.momentum
        if self.num_batches_tracked is not None:
            self.num_batches_tracked.add_(1)
            if self.momentum is None:
                exponential_average_factor = \
                    1.0 / float(self.num_batches_tracked)
        return _SyncBatchNormFn.apply(
            input, self.weight, self.bias, self.running_mean,
            self.running_var, self.eps, exponential_average_factor)


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, input, weight, bias, running_mean, running_var, eps,
                momentum):
        input = input.contiguous()
        reduce_dims = [0] + list(range(2, input.dim()))
        count = input.numel() // input.size(1)

        local = torch.empty(2 * input.size(1) + 1, dtype=torch.float32)
        local[0] = float(count)
        local[1: 1 + input.size(1)] = \
            input.sum(dim=reduce_dims).to(torch.float32)
        local[1 + input.size(1):] = \
            (input * input).sum(dim=reduce_dims).to(torch.float32)

        total = _ops.synchronize(_ops.allreduce_async(
            local, op=Sum, name="sync_batch_norm.fwd"))
        count_all = total[0]
        mean = total[1: 1 + input.size(1)] / count_all
        sumsq = total[1 + input.size(1):]
        var = sumsq / count_all - mean * mean
        invstd = torch.rsqrt(var + eps)

        if running_mean is not None:
            running_mean.mul_(1 - momentum).add_(
                mean.to(running_mean.dtype), alpha=momentum)
            # unbiased variance for running stats, as torch BN does
            unbiased = var * (count_all / (count_all - 1)) \
                if count_all > 1 else var
            running_var.mul_(1 - momentum).add_(
                unbiased.to(running_var.dtype), alpha=momentum)

        shape = [1, input.size(1)] + [1] * (input.dim() - 2)
        xhat = (input.to(torch.float32) - mean.view(shape)) * \
            invstd.view(shape)
        out = xhat
        if weight is not None:
            out = out * weight.to(torch.float32).view(shape)
        if bias is not None:
            out = out + bias.to(torch.float32).view(shape)

        ctx.save_for_backward(input, weight, mean, invstd)
        ctx.count_all = float(count_all)
        return out.to(input.dtype)

    @staticmethod
    def backward(ctx, grad_output):
        input, weight, mean, invstd = ctx.saved_tensors
        grad_output = grad_output.contiguous()
        reduce_dims = [0] + list(range(2, input.dim()))
        shape = [1, input.size(1)] + [1] * (input.dim() - 2)

        gof = grad_output.to(torch.float32)
        xf = input.to(torch.float32)
        xmu = xf - mean.view(shape)

        sum_dy = gof.sum(dim=reduce_dims)
        sum_dy_xmu = (gof * xmu).sum(dim=reduce_dims)

        stacked = torch.cat([sum_dy, sum_dy_xmu])
        total = _ops.synchronize(_ops.allreduce_async(
            stacked, op=Sum, name="sync_batch_norm.bwd"))
        sum_dy_all = total[: input.size(1)]
        sum_dy_xmu_all = total[input.size(1):]
        n = ctx.count_all

        w = weight.to(torch.float32).view(shape) if weight is not None \
            else torch.ones(shape, dtype=torch.float32)
        grad_input = w * invstd.view(shape) * (
            gof
            - sum_dy_all.view(shape) / n
            - xmu * invstd.view(shape) ** 2 * sum_dy_xmu_all.view(shape) / n
        )

        grad_weight = None
        if weight is not None and ctx.needs_input_grad[1]:
            grad_weight = (sum_dy_xmu * invstd).to(weight.dtype)
        grad_bias = None
        if ctx.needs_input_grad[2]:
            grad_bias = sum_dy.to(grad_output.dtype)

        return (grad_input.to(input.dtype), grad_weight, grad_bias, None,
                None, None, None)
