"""Elastic state for PyTorch (parity: ``torch/elastic.py:23-90``)."""

from __future__ import annotations

import copy

from ..elastic.state import ObjectState, State
from . import mpi_ops as _ops
from .functions import broadcast_object, broadcast_optimizer_state, \
    broadcast_parameters


class TorchState(ObjectState):
    """Elastic state tracking a torch model + optimizer plus arbitrary
    picklable attributes. ``sync()`` broadcasts from the coordinator after
    a membership change; ``restore()`` rolls back to the last commit."""

    def __init__(self, model=None, optimizer=None, **kwargs):
        self.model = model
        self.optimizer = optimizer
        self._saved_model_state = None
        self._saved_optimizer_state = None
        super().__init__(bcast_object=broadcast_object, **kwargs)

    def _public_attrs(self):
        return {
            k: v
            for k, v in self.__dict__.items()
            if not k.startswith("_") and k not in ("model", "optimizer")
        }

    def save(self):
        if self.model is not None:
            self._saved_model_state = copy.deepcopy(self.model.state_dict())
        if self.optimizer is not None:
            self._saved_optimizer_state = copy.deepcopy(
                self.optimizer.state_dict())
        super().save()

    def restore(self):
        if self.model is not None and self._saved_model_state is not None:
            self.model.load_state_dict(self._saved_model_state)
        if self.optimizer is not None and \
                self._saved_optimizer_state is not None:
            self.optimizer.load_state_dict(self._saved_optimizer_state)
        super().restore()

    def sync(self):
        if self.model is not None:
            broadcast_parameters(self.model.state_dict(), root_rank=0)
        if self.optimizer is not None:
            broadcast_optimizer_state(self.optimizer, root_rank=0)
        super().sync()


def _reinitialize():
    _ops.shutdown()
    _ops.init()


def run(func):
    """Elastic retry loop for torch training functions (parity:
    ``torch/elastic.py:23`` + ``common/elastic.py:147-168``): catches
    ``HorovodInternalError`` (restore + reinit) and
    ``HostsUpdatedInterrupt`` (reinit), re-initializing the *process-rank*
    world. The shared guarded loop lives in ``elastic.state.retry_loop``."""
    from ..elastic.state import retry_loop

    return retry_loop(func, _reinitialize)
