"""High-level broadcast helpers (parity: ``torch/functions.py:30-226``)."""

from __future__ import annotations

import pickle
from typing import Any, Iterable, Optional

import numpy as np
import torch

from ..common.host_world import world as _world
from . import mpi_ops as _ops


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast model parameters from ``root_rank`` to all processes.

    Accepts a ``state_dict`` (mapping) or an iterable of
    ``(name, tensor)`` pairs, like the reference."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None:
            continue
        if not isinstance(p, torch.Tensor):
            continue  # non-tensor state entries are synced via state dicts
        handles.append(_ops.broadcast_async_(p.data, root_rank,
                                             name=f"bcast.param.{name}"))
    for h in handles:
        _ops.synchronize(h)


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0):
    """Broadcast optimizer state from ``root_rank``.

    The reference reconstructs scalar hyperparameters tensor-by-tensor
    (``torch/functions.py:84-183``); pickling the state dict through
    ``broadcast_object`` gives identical results with one code path for
    every optimizer type, so that is the native design here. Tensor state
    (momentum buffers, exp_avg, ...) is broadcast in place to avoid
    re-allocating on non-root ranks."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    if _ops.size() == 1:
        return
    state_dict = optimizer.state_dict()
    # One pickle broadcast carries param_groups (hyperparameters) and all
    # tensor state; non-root ranks load it wholesale.
    synced = broadcast_object(
        {"param_groups": state_dict["param_groups"],
         "state": state_dict["state"]}, root_rank, name="bcast.opt.state")
    if _ops.rank() != root_rank:
        optimizer.load_state_dict(synced)


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    """Broadcast an arbitrary picklable object (parity:
    ``torch/functions.py:185-226``)."""
    name = name or "bcast.object"
    if _ops.size() == 1:
        return obj
    w = _world()
    if _ops.rank() == root_rank:
        payload = pickle.dumps(obj)
        length = np.asarray([len(payload)], np.int64)
    else:
        payload = b""
        length = np.zeros(1, np.int64)
    length = w.broadcast_np(length, root_rank, name + ".len")
    buf = np.zeros(int(length[0]), np.uint8)
    if _ops.rank() == root_rank:
        buf[:] = np.frombuffer(payload, np.uint8)
    buf = w.broadcast_np(buf, root_rank, name + ".data")
    return pickle.loads(buf.tobytes())


def allgather_object(obj: Any, name: Optional[str] = None) -> list:
    """Gather a picklable object from every rank (capability extension;
    the reference gained this post-0.19)."""
    name = name or "allgather.object"
    if _ops.size() == 1:
        return [obj]
    w = _world()
    # Unequal pickles ride the ragged allgatherv directly (reference
    # MPI_Allgatherv, ops/mpi_operations.cc:140-175).
    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    gathered, sizes = w.allgatherv_np(payload, name + ".data")
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    return [pickle.loads(gathered[offsets[r]: offsets[r + 1]].tobytes())
            for r in range(w.size)]
