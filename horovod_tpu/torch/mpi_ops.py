"""PyTorch binding: asynchronous collective ops on the native host plane.

Capability parity with the reference's ``horovod/torch/mpi_ops.py:91-538``
(allreduce/allgather/broadcast + ``_async``/in-place variants, autograd
integration, ``poll``/``synchronize``/``join``), re-architected TPU-native:
instead of a pybind11 module dispatching per-dtype C++ functions into an
MPI/NCCL background thread (``torch/mpi_ops_v2.cc:53-265``), torch CPU
tensors ride the native C++ ring data plane over TCP
(``csrc/hvd/ring_ops.cc``) negotiated by the same controller/cycle loop that
serves the XLA plane. Ranks are *processes*, exactly as in the reference —
one training process per rank, spawned by ``horovod_tpu.run``.

Handles are small ints resolved by a Python handle table (the
``HandleManager`` role, reference ``torch/handle_manager.{h,cc}``) backed by
the native handle futures.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np
import torch

from ..common import native as _native
from ..common.exceptions import HorovodInternalError
from ..common.host_world import world as _world
from ..ops.xla import Adasum, Average, Max, Min, ReduceOp, Sum  # noqa: F401

__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "cross_rank", "cross_size", "is_initialized",
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "allgather", "allgather_async", "broadcast", "broadcast_",
    "broadcast_async", "broadcast_async_", "poll", "synchronize", "join",
    "barrier", "Average", "Sum", "Adasum", "Min", "Max", "ReduceOp",
]

TORCH_DTYPE_CODES = {
    torch.uint8: 0,
    torch.int8: 1,
    torch.int16: 3,
    torch.int32: 4,
    torch.int64: 5,
    torch.float16: 6,
    torch.float32: 7,
    torch.float64: 8,
    torch.bool: 9,
    torch.bfloat16: 10,
}


def init(comm=None):
    """Initialize the process-rank world (parity: ``hvd.init()``)."""
    _world().init(comm=comm)


def shutdown():
    _world().shutdown()


def is_initialized() -> bool:
    return _world().initialized


def rank() -> int:
    _world().require_init()
    return _world().rank


def size() -> int:
    _world().require_init()
    return _world().size


def local_rank() -> int:
    _world().require_init()
    return _world().local_rank


def local_size() -> int:
    _world().require_init()
    return _world().local_size


def cross_rank() -> int:
    _world().require_init()
    return _world().cross_rank


def cross_size() -> int:
    _world().require_init()
    return _world().cross_size


# ---- handle table -----------------------------------------------------------


class _Handle:
    __slots__ = ("native", "output", "post", "result", "error",
                 "keepalive")

    def __init__(self, native: Optional[int], output, post: Optional[Callable],
                 result=None, error=None):
        self.native = native
        self.output = output
        self.post = post
        self.result = result
        self.error = error
        self.keepalive = None


_handles: Dict[int, _Handle] = {}
_handles_lock = threading.Lock()
_next_handle = 0
_name_counter = 0
_name_lock = threading.Lock()


def _new_handle(entry: _Handle) -> int:
    global _next_handle
    with _handles_lock:
        h = _next_handle
        _next_handle += 1
        _handles[h] = entry
        return h


def _auto_name(prefix: str) -> str:
    global _name_counter
    with _name_lock:
        _name_counter += 1
        return f"torch.{prefix}.noname.{_name_counter}"


def _check_tensor(tensor: torch.Tensor) -> torch.Tensor:
    if tensor.device.type != "cpu":
        raise ValueError(
            "horovod_tpu.torch operates on host (CPU) tensors; device "
            f"tensors belong on the XLA plane (got {tensor.device})")
    if tensor.dtype not in TORCH_DTYPE_CODES:
        raise ValueError(f"unsupported torch dtype {tensor.dtype}")
    return tensor.contiguous()


def _resolve_op(op: Optional[int], average: Optional[bool]) -> int:
    """Back-compat shim for the deprecated ``average`` argument (parity:
    ``common/util.py`` handle_average_backwards_compatibility)."""
    if average is not None:
        if op is not None:
            raise ValueError("specify either op or average, not both")
        return Average if average else Sum
    # Neither given: Average, the reference's default
    # (get_average_backwards_compatibility_fun, common/util.py:216-234).
    return Average if op is None else op


# ---- core submissions -------------------------------------------------------


def _submit_allreduce(tensor: torch.Tensor, output: torch.Tensor, name: str,
                      op: int, prescale_factor: float,
                      postscale_factor: float) -> int:
    w = _world()
    w.require_init()
    n = w.size
    if op == Adasum and (n & (n - 1)) != 0:
        raise ValueError("Adasum requires a power-of-two world size")
    if w.size == 1 or not w.native:
        scale = prescale_factor * (
            postscale_factor if op not in (Min, Max) else 1.0)
        if scale == 1.0:
            # Exact identity — never round-trip integers through float64
            # (int64 above 2^53 would lose precision).
            if output.data_ptr() != tensor.data_ptr():
                output.copy_(tensor)
        else:
            output.copy_((tensor.to(torch.float64) * scale).to(tensor.dtype))
        return _new_handle(_Handle(None, output, None, result=output))
    code = TORCH_DTYPE_CODES[tensor.dtype]
    h = w.enqueue(name, _native.OP_ALLREDUCE, op, code,
                  tuple(tensor.shape), tensor.data_ptr(), output.data_ptr(),
                  prescale=prescale_factor, postscale=postscale_factor)
    # The background thread reads the input buffer when the response fires:
    # both tensors must stay alive until synchronize().
    entry = _Handle(h, output, None)
    entry.keepalive = tensor
    return _new_handle(entry)


def allreduce_async(tensor: torch.Tensor, average: Optional[bool] = None,
                    name: Optional[str] = None, op: Optional[int] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0) -> int:
    tensor = _check_tensor(tensor)
    output = tensor.clone()
    return _submit_allreduce(tensor, output, name or _auto_name("allreduce"),
                             _resolve_op(op, average), prescale_factor,
                             postscale_factor)


def allreduce_async_(tensor: torch.Tensor, average: Optional[bool] = None,
                     name: Optional[str] = None, op: Optional[int] = None,
                     prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0) -> int:
    t = _check_tensor(tensor)
    if t.data_ptr() != tensor.data_ptr():
        raise ValueError("in-place allreduce requires a contiguous tensor")
    return _submit_allreduce(t, t, name or _auto_name("allreduce_"),
                             _resolve_op(op, average), prescale_factor,
                             postscale_factor)


class _AllreduceFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, name, op, prescale_factor, postscale_factor):
        ctx.op = op
        ctx.prescale_factor = prescale_factor
        ctx.postscale_factor = postscale_factor
        return synchronize(allreduce_async(
            tensor, name=name, op=op, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor))

    @staticmethod
    def backward(ctx, grad_output):
        reduced = synchronize(allreduce_async(
            grad_output, op=ctx.op, prescale_factor=ctx.prescale_factor,
            postscale_factor=ctx.postscale_factor))
        return reduced, None, None, None, None


def allreduce(tensor: torch.Tensor, average: Optional[bool] = None,
              name: Optional[str] = None, compression=None,
              op: Optional[int] = None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0) -> torch.Tensor:
    """Differentiable allreduce (parity: ``torch/mpi_ops.py:162-254``)."""
    from .compression import Compression

    compression = compression or Compression.none
    resolved = _resolve_op(op, average)
    compressed, ctx = compression.compress(tensor)
    summed = _AllreduceFn.apply(compressed, name, resolved, prescale_factor,
                                postscale_factor)
    return compression.decompress(summed, ctx)


def allreduce_(tensor: torch.Tensor, average: Optional[bool] = None,
               name: Optional[str] = None, op: Optional[int] = None,
               prescale_factor: float = 1.0,
               postscale_factor: float = 1.0) -> torch.Tensor:
    return synchronize(allreduce_async_(
        tensor, average, name, op=op, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor))


# ---- allgather --------------------------------------------------------------


def _submit_allgather(tensor: torch.Tensor, name: str,
                      sizes_out: Optional[list] = None) -> int:
    w = _world()
    w.require_init()
    if tensor.dim() == 0:
        tensor = tensor.reshape(1)
    if w.size == 1 or not w.native:
        out = tensor.clone()
        if sizes_out is not None:
            sizes_out.append(np.asarray([out.shape[0]], np.int64))
        return _new_handle(_Handle(None, out, None, result=out))
    # True ragged allgatherv (parity: MPI_Allgatherv,
    # mpi_operations.cc:140-175): per-rank dim-0 sizes ride the response
    # and the native executor allocates the output once they arrive — no
    # size pre-exchange, no padded bandwidth.
    t = tensor.contiguous()
    rest = tuple(t.shape[1:])
    code = TORCH_DTYPE_CODES[t.dtype]
    h = w.enqueue(name, _native.OP_ALLGATHER, 1, code, tuple(t.shape),
                  t.data_ptr(), 0)

    def post(_unused) -> torch.Tensor:
        fetched = w.result_fetch(h)
        if fetched is None:
            raise HorovodInternalError(
                f"allgather result missing for '{name}'")
        raw, dims = fetched
        out = torch.empty((int(sum(dims)),) + rest, dtype=t.dtype)
        if len(raw):
            ctypes.memmove(out.data_ptr(), raw, len(raw))
        if sizes_out is not None:
            sizes_out.append(np.asarray(dims, np.int64))
        return out

    entry = _Handle(h, None, post)
    entry.keepalive = t
    return _new_handle(entry)


def allgather_async(tensor: torch.Tensor, name: Optional[str] = None) -> int:
    return _submit_allgather(_check_tensor(tensor),
                             name or _auto_name("allgather"))


class _AllgatherFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, name):
        ctx.dim0 = tensor.shape[0] if tensor.dim() > 0 else 1
        w = _world()
        w.require_init()
        name = name or _auto_name("allgather")
        # The gather's response carries every rank's dim-0 size
        # (allgatherv); capture them for backward's slice math so backward
        # never runs a second negotiated collective under an
        # auto-generated name that could drift across ranks.
        sizes_out: list = []
        out = synchronize(_submit_allgather(_check_tensor(tensor), name,
                                            sizes_out=sizes_out))
        ctx.sizes = (sizes_out[0] if sizes_out
                     else np.asarray([ctx.dim0], np.int64))
        return out

    @staticmethod
    def backward(ctx, grad_output):
        # Parity: reference reduces the gathered grad then narrows to this
        # rank's slice (torch/mpi_ops.py:304-330).
        w = _world()
        reduced = synchronize(allreduce_async(grad_output, op=Sum))
        offset = int(ctx.sizes[: w.rank].sum())
        return reduced.narrow(0, offset, ctx.dim0), None


def allgather(tensor: torch.Tensor, name: Optional[str] = None) -> torch.Tensor:
    return _AllgatherFn.apply(_check_tensor(tensor), name)


# ---- broadcast --------------------------------------------------------------


def _submit_broadcast(tensor: torch.Tensor, output: torch.Tensor,
                      root_rank: int, name: str) -> int:
    w = _world()
    w.require_init()
    if w.size == 1 or not w.native:
        if root_rank != w.rank:
            raise ValueError(
                f"root_rank {root_rank} out of range for size {w.size}")
        if output.data_ptr() != tensor.data_ptr():
            output.copy_(tensor)
        return _new_handle(_Handle(None, output, None, result=output))
    code = TORCH_DTYPE_CODES[tensor.dtype]
    h = w.enqueue(name, _native.OP_BROADCAST, 1, code, tuple(tensor.shape),
                  tensor.data_ptr(), output.data_ptr(), root_rank=root_rank)
    entry = _Handle(h, output, None)
    entry.keepalive = tensor
    return _new_handle(entry)


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None) -> int:
    t = _check_tensor(tensor)
    return _submit_broadcast(t, t.clone(), root_rank,
                             name or _auto_name("broadcast"))


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None) -> int:
    t = _check_tensor(tensor)
    if t.data_ptr() != tensor.data_ptr():
        raise ValueError("in-place broadcast requires a contiguous tensor")
    return _submit_broadcast(t, t, root_rank,
                             name or _auto_name("broadcast_"))


class _BroadcastFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        return synchronize(broadcast_async(tensor, root_rank, name=name))

    @staticmethod
    def backward(ctx, grad_output):
        reduced = synchronize(allreduce_async(grad_output, op=Sum))
        if _world().rank != ctx.root_rank:
            reduced = reduced * 0
        return reduced, None, None


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    return _BroadcastFn.apply(_check_tensor(tensor), root_rank, name)


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name))


# ---- completion -------------------------------------------------------------


def poll(handle: int) -> bool:
    """True when the collective behind ``handle`` has completed (parity:
    ``torch/mpi_ops.py:481-491``)."""
    with _handles_lock:
        entry = _handles.get(handle)
    if entry is None:
        raise ValueError(f"unknown handle {handle}")
    if entry.native is None:
        return True
    r, _ = _world().test(entry.native)
    return r != 0


def synchronize(handle: int) -> torch.Tensor:
    """Block until completion; return the output tensor. Raises
    ``HorovodInternalError`` on collective failure (the elastic retry
    loop's trigger, parity: ``torch/mpi_ops.py:497-527``)."""
    with _handles_lock:
        entry = _handles.pop(handle, None)
    if entry is None:
        raise ValueError(f"unknown or already-synchronized handle {handle}")
    if entry.native is None:
        if entry.error is not None:
            raise HorovodInternalError(str(entry.error))
        return entry.result
    r, err = _world().wait(entry.native)
    if r < 0:
        raise HorovodInternalError(err)
    out = entry.output
    return entry.post(out) if entry.post is not None else out


def barrier():
    _world().barrier(_auto_name("barrier"))


def join(device: int = -1) -> int:
    """Graceful departure (parity: ``hvd.join()``, ``operations.cc:937-961``):
    this process stops submitting tensors and contributes zeros to the
    remaining processes' allreduces until every process has joined; returns
    the last joined rank. ``device`` is accepted for API parity and ignored
    (host plane). A collective failure propagates as
    ``HorovodInternalError`` so the elastic retry loop can restore +
    reinit."""
    return _world().join()
